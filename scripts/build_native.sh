#!/bin/sh
# Build the native host-side components into tpusvm/_native/.
# Requires g++ (C++17). Python never requires the result — every native
# entry point has a pure-Python fallback (tpusvm/data/native_io.py).
set -e
cd "$(dirname "$0")/.."
mkdir -p tpusvm/_native
g++ -std=c++17 -O3 -march=native -Wall -shared -fPIC -pthread \
    native/csv_reader.cpp -o tpusvm/_native/libtpusvm_io.so
echo "built tpusvm/_native/libtpusvm_io.so"
