#!/usr/bin/env bash
# Local mirror of the CI lint gate (.github/workflows/ci.yml):
#   scripts/lint.sh            lint the shipping trees
#   scripts/lint.sh --format json | jq .counts
# Extra args pass straight through to `python -m tpusvm.analysis`.
# ruff is run too when available (CI installs it; the dev container may
# not have it — the tpusvm linter is the part with no extra deps).
set -euo pipefail
cd "$(dirname "$0")/.."

if command -v ruff >/dev/null 2>&1; then
    ruff check .
else
    echo "lint.sh: ruff not installed; skipping style tier (CI runs it)" >&2
fi

PYTHONPATH=. exec python -m tpusvm.analysis tpusvm/ benchmarks/ scripts/ bench.py "$@"
