#!/usr/bin/env python
"""Generate the reference's expected MNIST CSVs (mnist3_train_data.csv /
mnist3_test_data.csv layout: header row, 784 pixel columns, last column =
digit label 0-9).

The reference assumes these files exist in cwd and ships neither them nor a
converter (SURVEY.md §4: "The CSVs themselves are not in the repo"). This
script is the missing fixture generator. Sources, in order of preference:

  1. --idx DIR     directory with the standard IDX files
                   (train-images-idx3-ubyte[.gz], train-labels-idx1-ubyte[.gz],
                   t10k-images-idx3-ubyte[.gz], t10k-labels-idx1-ubyte[.gz])
  2. --npz FILE    an .npz with arrays x_train, y_train, x_test, y_test
                   (the keras mnist.npz layout)
  3. --synthetic   deterministic MNIST-shaped synthetic data
                   (tpusvm.data.mnist_like_multiclass) — for air-gapped
                   environments; labels 0-9, pixels in [0, 255]

Usage:
  python scripts/make_mnist_csv.py --idx ~/mnist --out-dir data/
  python scripts/make_mnist_csv.py --synthetic --out-dir data/
"""

from __future__ import annotations

import argparse
import gzip
import os
import struct
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _open_maybe_gz(path):
    return gzip.open(path, "rb") if path.endswith(".gz") else open(path, "rb")


def _find(dir_, stem):
    for name in (stem, stem + ".gz"):
        p = os.path.join(dir_, name)
        if os.path.exists(p):
            return p
    raise FileNotFoundError(f"{stem}[.gz] not found in {dir_}")


def read_idx_images(path: str) -> np.ndarray:
    with _open_maybe_gz(path) as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        if magic != 2051:
            raise ValueError(f"{path}: bad IDX image magic {magic}")
        data = np.frombuffer(f.read(n * rows * cols), np.uint8)
    return data.reshape(n, rows * cols)


def read_idx_labels(path: str) -> np.ndarray:
    with _open_maybe_gz(path) as f:
        magic, n = struct.unpack(">II", f.read(8))
        if magic != 2049:
            raise ValueError(f"{path}: bad IDX label magic {magic}")
        return np.frombuffer(f.read(n), np.uint8).astype(np.int64)


def load_idx(dir_):
    return (
        read_idx_images(_find(dir_, "train-images-idx3-ubyte")),
        read_idx_labels(_find(dir_, "train-labels-idx1-ubyte")),
        read_idx_images(_find(dir_, "t10k-images-idx3-ubyte")),
        read_idx_labels(_find(dir_, "t10k-labels-idx1-ubyte")),
    )


def load_npz(path):
    z = np.load(path)
    return (
        z["x_train"].reshape(len(z["x_train"]), -1),
        z["y_train"].astype(np.int64),
        z["x_test"].reshape(len(z["x_test"]), -1),
        z["y_test"].astype(np.int64),
    )


def load_synthetic(n_train, n_test, seed):
    from tpusvm.data.synthetic import mnist_like_multiclass

    X, labels = mnist_like_multiclass(n=n_train + n_test, d=784, seed=seed)
    X = np.clip(np.round(X), 0, 255).astype(np.int64)
    return X[:n_train], labels[:n_train], X[n_train:], labels[n_train:]


def write_csv(path: str, X: np.ndarray, labels: np.ndarray) -> None:
    """Reference CSV layout: header (discarded by readers, defines column
    count — main3.cpp:27), one row per sample, integer pixels, label last."""
    d = X.shape[1]
    header = ",".join([f"pixel{i}" for i in range(d)] + ["label"])
    rows = np.column_stack([X.astype(np.int64), labels.astype(np.int64)])
    np.savetxt(path, rows, fmt="%d", delimiter=",", header=header, comments="")
    print(f"wrote {path}: {len(rows)} rows x {d} features")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    src = ap.add_mutually_exclusive_group(required=True)
    src.add_argument("--idx", metavar="DIR", help="directory with IDX files")
    src.add_argument("--npz", metavar="FILE", help="keras-layout mnist.npz")
    src.add_argument("--synthetic", action="store_true")
    ap.add_argument("--out-dir", default=".", help="output directory")
    ap.add_argument("--prefix", default="mnist3",
                    help="file prefix (reference expects 'mnist3')")
    ap.add_argument("--n-train", type=int, default=60000,
                    help="synthetic train size")
    ap.add_argument("--n-test", type=int, default=10000,
                    help="synthetic test size")
    ap.add_argument("--seed", type=int, default=587, help="synthetic seed")
    args = ap.parse_args(argv)

    if args.idx:
        xtr, ytr, xte, yte = load_idx(args.idx)
    elif args.npz:
        xtr, ytr, xte, yte = load_npz(args.npz)
    else:
        xtr, ytr, xte, yte = load_synthetic(args.n_train, args.n_test, args.seed)

    os.makedirs(args.out_dir, exist_ok=True)
    write_csv(os.path.join(args.out_dir, f"{args.prefix}_train_data.csv"), xtr, ytr)
    write_csv(os.path.join(args.out_dir, f"{args.prefix}_test_data.csv"), xte, yte)
    return 0


if __name__ == "__main__":
    sys.exit(main())
