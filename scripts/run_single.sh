#!/usr/bin/env bash
# Single-accelerator SMO training run — the TPU equivalent of the
# reference's code/gpu_svm.sh (1 node, --gres=gpu:1, runs ./gpu_svm on
# MNIST-60k). Here: one TPU chip, the blocked working-set solver, the
# MNIST-60k-shaped workload, reference hyperparameters (zero flags needed
# for a parity run).
#
# Real-data variant (after scripts/make_mnist_csv.py has produced CSVs):
#   scripts/run_single.sh --train mnist3_train_data.csv --test mnist3_test_data.csv
#
# On a Cloud TPU VM there is no SLURM; run directly, or under
# `gcloud compute tpus tpu-vm ssh ... --command` for remote submission.
set -euo pipefail
cd "$(dirname "$0")/.."

if [ "$#" -gt 0 ]; then
  exec python -m tpusvm train --mode single "$@"
fi
exec python -m tpusvm train --mode single --synthetic mnist-like \
  --n 60000 --n-test 10000
