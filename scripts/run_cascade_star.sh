#!/usr/bin/env bash
# Modified two-layer (star) Cascade SVM run — the TPU equivalent of the
# reference's code/mpi_svm2.sh (2 nodes x 32 tasks, mpirun -np 4
# ./mpi_svm2). Every shard trains in parallel, support vectors gather to
# shard 0 for the merged retrain (mpi_svm_main2.cpp:439-769 capability).
# Star topology accepts any shard count (no power-of-two restriction).
#
#   scripts/run_cascade_star.sh                # P = all visible devices
#   SHARDS=8 scripts/run_cascade_star.sh       # explicit P
#
# CPU-simulated mesh and multi-host notes: see run_cascade_tree.sh.
set -euo pipefail
cd "$(dirname "$0")/.."

ARGS=(--mode cascade --topology star)
[ -n "${SHARDS:-}" ] && ARGS+=(--shards "$SHARDS")
if [ "$#" -gt 0 ]; then
  exec python -m tpusvm train "${ARGS[@]}" "$@"
fi
exec python -m tpusvm train "${ARGS[@]}" --synthetic mnist-like \
  --n 60000 --n-test 10000
