#!/usr/bin/env bash
# Training-size sweep — the TPU equivalent of the reference's
# code/gpu_svm4.sh (loop n in 10000..60000 running ./gpu_svm4 $n, i.e. the
# gpu_svm_main4.cu n_limit build; report Table 2 / BASELINE.md B3).
#
#   scripts/run_sweep_n.sh                          # synthetic, 10k..60k
#   scripts/run_sweep_n.sh --train mnist3_train_data.csv --test mnist3_test_data.csv
#
# Any extra flags are forwarded to every run; --n-limit supplies the cap
# exactly as gpu_svm_main4 took argv[1]. benchmarks/sweep_n.py is the
# richer harness (JSON output, per-phase timings) — this script is the
# operational parity launcher.
set -euo pipefail
cd "$(dirname "$0")/.."

for n in 10000 20000 30000 40000 50000 60000; do
  echo "=== n_limit = $n ==="
  if [ "$#" -gt 0 ]; then
    python -m tpusvm train --mode single --n-limit "$n" "$@"
  else
    python -m tpusvm train --mode single --synthetic mnist-like \
      --n 60000 --n-test 10000 --n-limit "$n"
  fi
done
