#!/usr/bin/env bash
# Classical (binary-tree) Cascade SVM run — the TPU equivalent of the
# reference's code/mpi_svm3.sh (2 nodes x 32 tasks, mpirun -np 2
# ./mpi_svm3). Shard count P maps to mesh size instead of MPI ranks; the
# tree topology requires P to be a power of two, exactly like the
# reference's __builtin_ctz world-size check (mpi_svm_main3.cpp:420-428).
#
#   scripts/run_cascade_tree.sh                # P = all visible devices
#   SHARDS=8 scripts/run_cascade_tree.sh       # explicit P
#
# Without TPU hardware, simulate a mesh on CPU the same way the tests do
# (--platform cpu, because site configuration may override JAX_PLATFORMS):
#   XLA_FLAGS=--xla_force_host_platform_device_count=8 \
#     SHARDS=8 scripts/run_cascade_tree.sh --platform cpu
# Multi-host pods need no mpirun equivalent: launch the same command WITH
# --distributed on every host — the CLI then calls
# jax.distributed.initialize() (the MPI_Init equivalent) and the hosts form
# one global mesh (TPU metadata supplies the geometry; off-TPU pass
# --coordinator-address/--num-processes/--process-id):
#   SHARDS=8 scripts/run_cascade_tree.sh --distributed
set -euo pipefail
cd "$(dirname "$0")/.."

ARGS=(--mode cascade --topology tree)
[ -n "${SHARDS:-}" ] && ARGS+=(--shards "$SHARDS")
if [ "$#" -gt 0 ]; then
  exec python -m tpusvm train "${ARGS[@]}" "$@"
fi
exec python -m tpusvm train "${ARGS[@]}" --synthetic mnist-like \
  --n 60000 --n-test 10000
