#!/usr/bin/env bash
# Multi-host distributed cascade launcher — the operational counterpart of
# the reference's 2-node mpirun submission (code/mpi_svm3.sh: SLURM
# allocates 2 nodes x 32 tasks, mpirun -np 2 ./mpi_svm3). Here each HOST
# runs this script once with its rank; jax.distributed.initialize (the
# MPI_Init equivalent, wired behind --distributed) forms one global device
# mesh spanning the hosts, and the cascade's collectives ride ICI within a
# host / DCN between hosts.
#
# On TPU pods the geometry is auto-discovered from the TPU metadata:
#   scripts/run_distributed.sh                       # on every pod host
#
# Off-TPU (or for a localhost test cluster), pass the geometry explicitly:
#   COORD=10.0.0.1:8476 NPROC=2 PID=0 scripts/run_distributed.sh   # host 0
#   COORD=10.0.0.1:8476 NPROC=2 PID=1 scripts/run_distributed.sh   # host 1
#
# A 2-process localhost smoke (one CPU device per process — the same
# cluster tests/test_distributed.py forms):
#   COORD=127.0.0.1:8476 NPROC=2 PID=0 scripts/run_distributed.sh \
#       --platform cpu --synthetic blobs --n 64 --d 8 --gamma 0.5 &
#   COORD=127.0.0.1:8476 NPROC=2 PID=1 scripts/run_distributed.sh \
#       --platform cpu --synthetic blobs --n 64 --d 8 --gamma 0.5
#
# Extra arguments are forwarded to `tpusvm train` (after the defaults
# below, so user flags win).
set -euo pipefail
cd "$(dirname "$0")/.."

GEO=()
if [[ -n "${COORD:-}" ]]; then
  GEO+=(--coordinator-address "$COORD")
fi
if [[ -n "${NPROC:-}" ]]; then
  GEO+=(--num-processes "$NPROC")
fi
if [[ -n "${PID:-}" ]]; then
  GEO+=(--process-id "$PID")
fi

exec python -m tpusvm --distributed "${GEO[@]}" train \
  --synthetic mnist-like --mode cascade --topology "${TOPOLOGY:-tree}" \
  ${SHARDS:+--shards "$SHARDS"} \
  "$@"
