#!/usr/bin/env bash
# Post-adoption TPU refresh batch (round 4, after fused_fupdate became the
# TPU default): re-capture the artifacts whose committed rows predate the
# tuned solver config, plus a repeated headline under the new default.
#
#   scripts/capture_tpu_refresh.sh [outdir]   # default: benchmarks/results/tpu_refresh_<utc>
#
# Same operating constraints as capture_tpu_round.sh (verify skill):
# one heavy measurement per process, pre-flight the relay/backend, bound
# every step, tolerate per-step failure, pause between processes.
set -uo pipefail
cd "$(dirname "$0")/.."

OUT=${1:-benchmarks/results/tpu_refresh_$(date -u +%Y%m%dT%H%M%SZ)}
mkdir -p "$OUT"
echo "capturing to $OUT" >&2

if ! pgrep -f relay.py >/dev/null 2>&1; then
  if python - <<'EOF'
import importlib.util, sys
sys.exit(0 if importlib.util.find_spec("axon") else 1)
EOF
  then
    echo "FATAL: axon tunnel relay process is dead — backend init would" \
         "hang. See the verify skill root-cause check." >&2
    exit 2
  fi
fi
if ! timeout 240 python -c "import jax; assert jax.devices()[0].platform == 'tpu', jax.devices()"; then
  echo "FATAL: TPU backend did not initialise as platform=tpu within 240s" >&2
  exit 2
fi
echo "pre-flight OK: TPU backend live" >&2
sleep 10

step () {  # step <name> <logfile> <cmd...>
  local name=$1 log=$2; shift 2
  echo "=== $name ===" >&2
  if timeout 1800 "$@" >"$log" 2>"$log.err"; then
    echo "$name OK -> $log" >&2
  else
    echo "WARNING: $name failed/hung (rc=$?); continuing — see $log.err" >&2
  fi
  sleep 30
}

# (a) headline under the adopted fused default, three repeats for a
#     noise-banded quote (the committed single capture sits in a ~12%
#     run-to-run band) — INTERLEAVED with same-session A/B rows:
#       ab_tuned    = the shipping config (q=2048/mi=4096/wss=2/approx/
#                     fused-auto/packed) via probe_split (fixed seed-0
#                     sibling instance of the headline workload)
#       ab_round1   = the exact round-1 shipping config (q=1024/mi=1024/
#                     wss=1/exact/unfused/FLAT layout) — settles the
#                     open tuned-vs-untuned question (round-1's 0.4133 s
#                     vs round-4's 0.46-0.53 s has never been measured
#                     in one session)
#       ab_fusedoff = tuned config with fused f-update OFF — the round-4
#                     fused adoption rested on a single unfused sample;
#                     three interleaved repeats give it a noise band
for i in 1 2 3; do
  step "headline_fused_$i" "$OUT/bench_headline_fused_$i.json" python bench.py
  step "ab_tuned_$i" "$OUT/ab_tuned_$i.jsonl" \
    python benchmarks/probe_split.py 2048 4096 5000 2 none 0 approx auto packed
  step "ab_round1_$i" "$OUT/ab_round1_$i.jsonl" \
    python benchmarks/probe_split.py 1024 1024 5000 1 none 0 exact 0 flat
  step "ab_fusedoff_$i" "$OUT/ab_fusedoff_$i.jsonl" \
    python benchmarks/probe_split.py 2048 4096 5000 2 none 0 approx 0 packed
done

# (b) n-sweep refresh (B3): the committed sweep_n_tpu_v5e.jsonl rows are
#     round-1 (q=1024/max_inner=1024/wss=1, pre-tuning); harness defaults
#     are now the tuned config. One size per process.
for n in 10000 20000 30000 40000 50000 60000; do
  step "sweep_n_$n" "$OUT/sweep_n_$n.jsonl" \
    python benchmarks/sweep_n.py --sizes "$n"
done

# (b2) BEYOND the reference's 60k ceiling (gpu_svm_main4.cu:487-498 caps
#      its sweep there): show the solver leaving the ~1%-of-HBM
#      latency-bound regime as the O(n*d*q) contraction grows. f32 X at
#      480k x 784 is ~1.5 GB — comfortably HBM-resident on one v5e chip.
for n in 120000 240000 480000; do
  step "sweep_n_big_$n" "$OUT/sweep_n_big_$n.jsonl" \
    python benchmarks/sweep_n.py --sizes "$n"
done

# (c) 10-class OVR refresh: the committed ovr_10class_tpu_v5e.jsonl row is
#     round-1 (27.8 s train, pre-tuning)
step ovr_10class "$OUT/ovr_10class.jsonl" python benchmarks/ovr_10class.py

# (d) fast-edge grid probes under the adopted fused kernel (the r4 grid's
#     two fastest rows measured unfused; args: q mi max_outer wss
#     precision refine selection fused [layout] [eta_exclude])
step probe_q2048_mi8192_fused "$OUT/probe_q2048_mi8192_fused.jsonl" \
  python benchmarks/probe_split.py 2048 8192 5000 2 none 0 approx fused
step probe_q1536_mi8192_fused "$OUT/probe_q1536_mi8192_fused.jsonl" \
  python benchmarks/probe_split.py 1536 8192 5000 2 none 0 approx fused

# (e) eta_exclude A/B at the shipping config (VERDICT r4 #5): the cost of
#     folding the XLA engine's degenerate-partner exclusion into the
#     kernel's gain selection — one extra cross-lane reduction per inner
#     iteration. Two repeats each, interleaved, for a noise check.
for i in 1 2; do
  step "etax_on_$i" "$OUT/etax_on_$i.jsonl" \
    python benchmarks/probe_split.py 2048 4096 5000 2 none 0 approx auto packed 1
  step "etax_off_$i" "$OUT/etax_off_$i.jsonl" \
    python benchmarks/probe_split.py 2048 4096 5000 2 none 0 approx auto packed 0
done

# (f) multipair A/B (VERDICT r4 #3, adopt-or-kill): the batched slot-pair
#     kernel vs the sequential kernel at the same first-order config.
#     Interpret-mode counts: p=8 converges in ~2.4x fewer kernel
#     iterations at ~3.7x the updates on a q=2048 subproblem — whether
#     that wins wall-clock depends on the slot work pipelining against
#     the global step's reduction latency, measurable only on hardware.
#     wss=1 rows (multipair requires first-order); mp1 = control.
for i in 1 2; do
  for mp in 8 4 1; do
    step "mp${mp}_$i" "$OUT/mp${mp}_$i.jsonl" \
      python benchmarks/probe_split.py 2048 4096 5000 1 none 0 approx auto packed 0 "$mp"
  done
done

echo "capture complete: $OUT — merge sweep rows, update" \
     "benchmarks/results/README.md + README.md headline quotes" >&2
