#!/usr/bin/env bash
# TPU refresh batch (rounds 4-5): re-capture artifacts whose committed
# rows predate the tuned solver config, settle the open A/B questions
# (tuned vs round-1, fused noise band, eta_exclude cost, multipair
# adopt-or-kill), and extend the n-sweep past the reference's ceiling.
#
#   scripts/capture_tpu_refresh.sh [outdir]   # default: benchmarks/results/tpu_refresh_<utc>
#
# ORDERED FOR A SHORT HARDWARE WINDOW (round-4's was ~40 min before the
# tunnel wedged): pass 1 captures ONE row of every question — headline,
# the three headline A/B configs, the two new-kernel A/Bs — so even a
# brief window settles each question with at least one sample; pass 2+
# adds repeats for noise bands; the long tail (sweeps, OVR, probes) runs
# last.
#
# Same operating constraints as capture_tpu_round.sh (verify skill):
# one heavy measurement per process, pre-flight the relay/backend, bound
# every step, tolerate per-step failure, pause between processes.
set -uo pipefail
cd "$(dirname "$0")/.."

OUT=${1:-benchmarks/results/tpu_refresh_$(date -u +%Y%m%dT%H%M%SZ)}
mkdir -p "$OUT"
echo "capturing to $OUT" >&2

if ! pgrep -f relay.py >/dev/null 2>&1; then
  if python - <<'EOF'
import importlib.util, sys
sys.exit(0 if importlib.util.find_spec("axon") else 1)
EOF
  then
    echo "FATAL: axon tunnel relay process is dead — backend init would" \
         "hang. See the verify skill root-cause check." >&2
    exit 2
  fi
fi
if ! timeout 240 python -c "import jax; assert jax.devices()[0].platform == 'tpu', jax.devices()"; then
  echo "FATAL: TPU backend did not initialise as platform=tpu within 240s" >&2
  exit 2
fi
echo "pre-flight OK: TPU backend live" >&2
sleep 10

step () {  # step <name> <logfile> <cmd...>
  local name=$1 log=$2; shift 2
  echo "=== $name ===" >&2
  if timeout 1800 "$@" >"$log" 2>"$log.err"; then
    echo "$name OK -> $log" >&2
  else
    echo "WARNING: $name failed/hung (rc=$?); continuing — see $log.err" >&2
  fi
  sleep 30
}

# probe_split args: q mi max_outer wss precision refine selection fused
#                   [layout] [eta_exclude] [multipair]
#   ab_tuned    = shipping config (q=2048/mi=4096/wss=2/approx/fused-auto)
#   ab_round1   = exact round-1 shipping config (q=1024/mi=1024/wss=1/
#                 exact/unfused/FLAT) — settles tuned-vs-untuned
#                 (round-1's 0.4133 s vs round-4's 0.46-0.53 s has never
#                 been measured in one session)
#   ab_fusedoff = tuned config, fused f-update OFF (the round-4 adoption
#                 rested on ONE unfused sample — ADVICE r4 #1)
#   etax_on/off = VERDICT r4 #5: cost of the unified degenerate-partner
#                 exclusion (one extra cross-lane reduction per iteration)
#   mp{8,4,1}   = VERDICT r4 #3 adopt-or-kill: batched slot-pair kernel
#                 vs the sequential kernel, wss=1 rows (mp1 = control)
for i in 1 2 3; do
  step "headline_fused_$i" "$OUT/bench_headline_fused_$i.json" python bench.py
  step "ab_tuned_$i" "$OUT/ab_tuned_$i.jsonl" \
    python benchmarks/probe_split.py 2048 4096 5000 2 none 0 approx auto packed
  step "ab_round1_$i" "$OUT/ab_round1_$i.jsonl" \
    python benchmarks/probe_split.py 1024 1024 5000 1 none 0 exact 0 flat
  step "ab_fusedoff_$i" "$OUT/ab_fusedoff_$i.jsonl" \
    python benchmarks/probe_split.py 2048 4096 5000 2 none 0 approx 0 packed
  step "etax_on_$i" "$OUT/etax_on_$i.jsonl" \
    python benchmarks/probe_split.py 2048 4096 5000 2 none 0 approx auto packed 1
  step "etax_off_$i" "$OUT/etax_off_$i.jsonl" \
    python benchmarks/probe_split.py 2048 4096 5000 2 none 0 approx auto packed 0
  step "mp8_$i" "$OUT/mp8_$i.jsonl" \
    python benchmarks/probe_split.py 2048 4096 5000 1 none 0 approx auto packed 0 8
  step "mp4_$i" "$OUT/mp4_$i.jsonl" \
    python benchmarks/probe_split.py 2048 4096 5000 1 none 0 approx auto packed 0 4
  step "mp1_$i" "$OUT/mp1_$i.jsonl" \
    python benchmarks/probe_split.py 2048 4096 5000 1 none 0 approx auto packed 0 1
done

# (b) n-sweep refresh (B3): the committed sweep_n_tpu_v5e.jsonl rows are
#     round-1 (q=1024/max_inner=1024/wss=1, pre-tuning); harness defaults
#     are now the tuned config. One size per process.
for n in 10000 20000 30000 40000 50000 60000; do
  step "sweep_n_$n" "$OUT/sweep_n_$n.jsonl" \
    python benchmarks/sweep_n.py --sizes "$n"
done

# (b2) BEYOND the reference's 60k ceiling (gpu_svm_main4.cu:487-498 caps
#      its sweep there): show the solver leaving the ~1%-of-HBM
#      latency-bound regime as the O(n*d*q) contraction grows. f32 X at
#      480k x 784 is ~1.5 GB — comfortably HBM-resident on one v5e chip.
#      The recipe's strict-stop tail outgrows the 1e6 update bound by
#      240k (CPU evidence rows); 1e7 costs only minutes at TPU rates.
for n in 120000 240000 480000; do
  step "sweep_n_big_$n" "$OUT/sweep_n_big_$n.jsonl" \
    python benchmarks/sweep_n.py --sizes "$n" --max-iter 10000000
done

# (c) 10-class OVR refresh: the committed ovr_10class_tpu_v5e.jsonl row is
#     round-1 (27.8 s train, pre-tuning)
step ovr_10class "$OUT/ovr_10class.jsonl" python benchmarks/ovr_10class.py

# (d) fast-edge grid probes under the adopted fused kernel (the r4 grid's
#     two fastest rows measured unfused)
step probe_q2048_mi8192_fused "$OUT/probe_q2048_mi8192_fused.jsonl" \
  python benchmarks/probe_split.py 2048 8192 5000 2 none 0 approx fused
step probe_q1536_mi8192_fused "$OUT/probe_q1536_mi8192_fused.jsonl" \
  python benchmarks/probe_split.py 1536 8192 5000 2 none 0 approx fused

echo "capture complete: $OUT — merge sweep rows, update" \
     "benchmarks/results/README.md + README.md headline quotes" >&2
