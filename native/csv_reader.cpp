// Native CSV data loader — the framework's host-side IO fast path.
//
// The reference's data layer is a C++ read_CSV (main3.cpp:13-54;
// gpu_svm_main4.cu:16-59 adds the n_limit cap): skip the header line (it
// only defines the column count), parse comma-separated doubles, last
// column is the integer label, rows with fewer than 2 fields are skipped,
// and in binary mode label != 1 maps to -1. This file is the TPU
// framework's native equivalent: same row/label semantics, but
// multi-threaded — the file is split at newline boundaries into per-thread
// byte ranges parsed concurrently, then copied into one contiguous
// row-major buffer in file order. Exposed through a plain C ABI consumed
// by ctypes (tpusvm/data/native_io.py); no pybind11 dependency.
//
// Build: scripts/build_native.sh  ->  tpusvm/_native/libtpusvm_io.so

#include <cctype>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Chunk {
  std::vector<double> X;
  std::vector<int32_t> Y;
  long rows = 0;
  bool parse_error = false;
};

// Parse one [begin, end) slice of complete lines into chunk storage.
// d_features = columns - 1 (from the header). Contract matches the Python
// reader (tpusvm/data/csv_reader.py): rows with < 2 fields are skipped;
// an unparsable field or a row whose field count differs from the
// header's is a parse error (the Python reader raises there too — the
// fast path must not silently return different data than the fallback).
// row_cap >= 0 stops after that many kept rows WITHOUT looking at later
// lines — the Python reader breaks at the cap, so malformed rows past it
// must not raise.
void parse_slice(const char* begin, const char* end, long d_features,
                 int binary_labels, long row_cap, Chunk* out) {
  std::vector<double> fields;
  fields.reserve(d_features + 1);
  const char* p = begin;
  while (p < end) {
    if (row_cap >= 0 && out->rows >= row_cap) return;
    const char* line_end = static_cast<const char*>(
        memchr(p, '\n', static_cast<size_t>(end - p)));
    if (line_end == nullptr) line_end = end;

    // Field count = comma count + 1, exactly Python's line.split(','):
    // rows with fewer than 2 fields are skipped WITHOUT parsing (a bare
    // "7", an empty line, or a whitespace-only line is not an error).
    long n_fields = 1;
    for (const char* c = p; c < line_end; ++c)
      if (*c == ',') ++n_fields;

    if (p != line_end && n_fields >= 2) {
      if (n_fields != d_features + 1) {
        out->parse_error = true;
        return;
      }
      fields.clear();
      bool bad_field = false;
      const char* q = p;
      for (long k = 0; k < n_fields; ++k) {
        const char* field_end = static_cast<const char*>(
            memchr(q, ',', static_cast<size_t>(line_end - q)));
        if (field_end == nullptr) field_end = line_end;
        char* next = nullptr;
        double v = strtod(q, &next);
        // The parse is bounded to this comma-delimited span: the number
        // must start inside it (next > q, next <= field_end — otherwise
        // strtod's leading-whitespace skip consumed text from a later
        // field or line) and leave only whitespace behind. Empty,
        // whitespace-only, and trailing-garbage fields all raise in the
        // Python fallback (float()), so they are errors here too.
        if (next == q || next > field_end) {
          bad_field = true;
          break;
        }
        // strtod accepts C hex floats ("0x10"); Python's float() does not
        for (const char* c = q; c < next && !bad_field; ++c)
          if (*c == 'x' || *c == 'X') bad_field = true;
        if (bad_field) break;
        for (const char* c = next; c < field_end && !bad_field; ++c)
          if (!isspace(static_cast<unsigned char>(*c))) bad_field = true;
        if (bad_field) break;
        fields.push_back(v);
        q = field_end < line_end ? field_end + 1 : line_end;
      }
      if (bad_field) {
        out->parse_error = true;
        return;
      }
      size_t base = out->X.size();
      out->X.resize(base + d_features, 0.0);
      for (long j = 0; j < d_features; ++j) out->X[base + j] = fields[j];
      int32_t label = static_cast<int32_t>(fields.back());
      out->Y.push_back(binary_labels ? (label == 1 ? 1 : -1) : label);
      out->rows += 1;
    }
    p = line_end < end ? line_end + 1 : end;
  }
}

}  // namespace

extern "C" {

struct CsvData {
  int64_t n;
  int64_t d;
  double* X;       // row-major (n, d), owned
  int32_t* Y;      // (n,), owned
  int64_t error;   // 0 = ok, 1 = parse error, 2 = out of memory (X/Y null)
};

// Returns nullptr on IO error. n_limit < 0 means "no cap".
CsvData* tpusvm_read_csv(const char* path, int64_t n_limit,
                         int binary_labels, int n_threads) {
  FILE* fp = fopen(path, "rb");
  if (fp == nullptr) return nullptr;
  fseek(fp, 0, SEEK_END);
  long size = ftell(fp);
  fseek(fp, 0, SEEK_SET);
  std::string buf(static_cast<size_t>(size), '\0');
  if (size > 0 && fread(&buf[0], 1, static_cast<size_t>(size), fp) !=
                      static_cast<size_t>(size)) {
    fclose(fp);
    return nullptr;
  }
  fclose(fp);

  // header line: defines the column count, content discarded
  const char* data = buf.data();
  const char* data_end = data + buf.size();
  const char* hdr_end = static_cast<const char*>(
      memchr(data, '\n', buf.size()));
  if (hdr_end == nullptr) hdr_end = data_end;
  long d_features = 0;
  for (const char* c = data; c < hdr_end; ++c)
    if (*c == ',') ++d_features;  // columns - 1 = feature count
  const char* body = hdr_end < data_end ? hdr_end + 1 : data_end;

  if (n_threads <= 0) {
    unsigned hw = std::thread::hardware_concurrency();
    n_threads = hw ? static_cast<int>(hw) : 4;
  }
  long body_len = static_cast<long>(data_end - body);
  if (body_len < (1 << 20)) n_threads = 1;  // small file: threads cost more
  // n_limit must stop the scan at the cap (the Python reader breaks there,
  // so malformed rows past it never raise) — that early-exit semantics is
  // inherently sequential
  if (n_limit >= 0) n_threads = 1;

  // split [body, data_end) at newline boundaries into n_threads slices
  std::vector<const char*> starts{body};
  for (int t = 1; t < n_threads; ++t) {
    const char* guess = body + body_len * t / n_threads;
    const char* nl = static_cast<const char*>(
        memchr(guess, '\n', static_cast<size_t>(data_end - guess)));
    starts.push_back(nl == nullptr ? data_end : nl + 1);
  }
  starts.push_back(data_end);

  std::vector<Chunk> chunks(static_cast<size_t>(n_threads));
  std::vector<std::thread> workers;
  for (int t = 0; t < n_threads; ++t) {
    workers.emplace_back(parse_slice, starts[t], starts[t + 1], d_features,
                         binary_labels, n_limit,
                         &chunks[static_cast<size_t>(t)]);
  }
  for (auto& w : workers) w.join();

  CsvData* out = static_cast<CsvData*>(malloc(sizeof(CsvData)));
  if (out == nullptr) return nullptr;
  out->n = 0;
  out->d = d_features;
  out->X = nullptr;
  out->Y = nullptr;
  out->error = 0;

  for (const auto& c : chunks) {
    if (c.parse_error) {
      out->error = 1;
      return out;
    }
  }

  int64_t total = 0;
  for (const auto& c : chunks) total += c.rows;
  if (n_limit >= 0 && total > n_limit) total = n_limit;
  if (total == 0) return out;  // malloc(0) may legally return NULL

  out->n = total;
  out->X = static_cast<double*>(
      malloc(sizeof(double) * static_cast<size_t>(total * d_features)));
  out->Y = static_cast<int32_t*>(
      malloc(sizeof(int32_t) * static_cast<size_t>(total)));
  if (out->X == nullptr || out->Y == nullptr) {
    free(out->X);
    free(out->Y);
    out->X = nullptr;
    out->Y = nullptr;
    out->n = 0;
    out->error = 2;
    return out;
  }

  int64_t row = 0;
  for (const auto& c : chunks) {
    if (row >= total) break;
    int64_t take = c.rows;
    if (row + take > total) take = total - row;
    memcpy(out->X + row * d_features, c.X.data(),
           sizeof(double) * static_cast<size_t>(take * d_features));
    memcpy(out->Y + row, c.Y.data(),
           sizeof(int32_t) * static_cast<size_t>(take));
    row += take;
  }
  return out;
}

void tpusvm_free_csv(CsvData* data) {
  if (data == nullptr) return;
  free(data->X);
  free(data->Y);
  free(data);
}

}  // extern "C"
