"""tpusvm.stream — sharded out-of-core data pipeline.

Every other path in the repo consumes one in-memory array; this package
makes datasets a first-class ON-DISK artifact — the enabling layer for
larger-than-RAM and multi-host workloads (ROADMAP "production-scale").
The reference already sketches the shape (rank 0 computes global min/max,
then scatters shards to workers, mpi_svm_main3.cpp:463-539); here the
shards live on disk with their statistics in a manifest, and every
consumer streams:

  format.py   versioned layout: packed .npz shards + JSON manifest
              (per-shard row counts, feature min/max, class counts,
              content checksums); ShardWriter / ingest_* producers,
              ShardedDataset reader handle, StreamStatus validation
  stats.py    mergeable per-shard statistics: MinMaxScaler fitted from
              the manifest BIT-IDENTICALLY to a full-array fit
  reader.py   ShardReader: background-thread prefetch with a hard
              prefetch_depth + 1 residency bound, deterministic order,
              on-the-fly scaling
  assign.py   global row -> cascade-leaf assignment (contiguous or
              stratified, = data.partition semantics) computed from the
              manifest; shard-streamed Partition construction; row
              gathering for tune folds
  infer.py    predict_stream / evaluate_stream over prefetched batches
  append.py   crash-safe tail append: ShardWriter.open_append reopens a
              committed dataset and grows it bit-identically to a
              one-shot ingest of the concatenation, exactly-once under
              kill (per-batch CRC journal ledger)

CLI: `tpusvm ingest` writes a dataset; `tpusvm train --data`,
`tpusvm predict --data`, `tpusvm tune --data`, and `tpusvm info <dir>`
consume one.
"""

from tpusvm.stream.append import AppendError, AppendWriter, append_blocks
from tpusvm.stream.assign import (
    RowAssignment,
    assign_rows,
    gather_rows,
    partition_from_dataset,
)
from tpusvm.stream.format import (
    FORMAT_VERSION,
    Manifest,
    ShardError,
    ShardInfo,
    ShardWriter,
    ShardedDataset,
    ingest_arrays,
    ingest_blocks,
    ingest_csv,
    is_dataset_dir,
    open_dataset,
    shard_checksum,
)
from tpusvm.stream.infer import evaluate_stream, predict_stream
from tpusvm.stream.reader import ShardReader
from tpusvm.stream.stats import (
    ShardStats,
    compute_stats,
    merge_stats,
    scaler_from_stats,
)

__all__ = [
    "AppendError",
    "AppendWriter",
    "FORMAT_VERSION",
    "Manifest",
    "RowAssignment",
    "ShardError",
    "ShardInfo",
    "ShardReader",
    "ShardStats",
    "ShardWriter",
    "ShardedDataset",
    "append_blocks",
    "assign_rows",
    "compute_stats",
    "evaluate_stream",
    "gather_rows",
    "ingest_arrays",
    "ingest_blocks",
    "ingest_csv",
    "is_dataset_dir",
    "merge_stats",
    "open_dataset",
    "partition_from_dataset",
    "predict_stream",
    "scaler_from_stats",
    "shard_checksum",
]
