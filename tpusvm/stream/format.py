"""Versioned sharded on-disk dataset format.

Layout: a directory holding packed .npz shards plus one JSON manifest —

    dataset/
      manifest.json        format version, global shape, per-shard metadata
      shard-00000.npz      arrays "X" (n_i, d) float64, "Y" (n_i,) int32
      shard-00001.npz      ...

The manifest records, per shard: filename, row count, the global row offset
(global row order IS the concatenation of shards in manifest order), feature
min/max, class counts (tpusvm.stream.stats), and a content checksum (sha256
over the array bytes + a shape/dtype header, so the hash is a statement
about the DATA, independent of npz container details like compression or
zip timestamps). The reference's preprocessing facts — rank-0 global
min/max, per-rank row counts (mpi_svm_main3.cpp:463-539) — are therefore
all answerable from the manifest alone, without touching a shard.

Writing goes through ShardWriter, which buffers appended blocks and cuts
shards of exactly rows_per_shard rows (last one short), so ingest's peak
memory is one shard regardless of dataset size. `ingest_csv` streams the
CSV through data.read_csv_blocks; `ingest_arrays` shards an in-memory
array (tests, synthetic generators).

Versioning follows the house serialization rule (models/serialization.py):
a manifest without format_version, or with an unknown one, is rejected
with a clear error instead of being half-parsed.
"""

from __future__ import annotations

import dataclasses
import hashlib
import io
import json
import os
import zipfile
import zlib
from typing import Iterable, Iterator, List, Optional, Tuple

import numpy as np

from tpusvm import faults
from tpusvm.data.csv_reader import read_csv_blocks
from tpusvm.utils.durable import fsync_replace
from tpusvm.status import StreamStatus
from tpusvm.stream.stats import (
    ShardStats,
    compute_stats,
    merge_stats,
    scaler_from_stats,
)

FORMAT_VERSION = 1
MANIFEST_NAME = "manifest.json"
JOURNAL_NAME = "ingest.journal.json"
JOURNAL_VERSION = 1
DEFAULT_ROWS_PER_SHARD = 65536

# np.load failure modes on damaged bytes: BadZipFile/zlib.error escape the
# (OSError, ValueError, KeyError) net — a truncated or bit-flipped npz used
# to surface as a raw traceback from the prefetch thread (ISSUE 7 satellite)
_UNREADABLE = (OSError, ValueError, KeyError, EOFError,
               zipfile.BadZipFile, zlib.error)


class ShardError(ValueError):
    """A shard failed to load or verify; names the shard and carries the
    StreamStatus so callers branch on codes, not string matching.

    ValueError subclass: the pre-existing load_shard(verify=True)
    contract raised ValueError, and every caller of that contract keeps
    working while gaining .filename/.status."""

    def __init__(self, filename: str, status: StreamStatus,
                 detail: str = ""):
        self.filename = filename
        self.status = StreamStatus(status)
        msg = f"shard {filename}: {self.status.name}"
        if detail:
            msg += f" ({detail})"
        msg += " — re-ingest or restore the file"
        super().__init__(msg)


def shard_checksum(X: np.ndarray, Y: np.ndarray) -> str:
    """sha256 over shape/dtype header + row bytes (container-independent)."""
    h = hashlib.sha256()
    h.update(f"{X.shape[0]},{X.shape[1]},{X.dtype},{Y.dtype}".encode())
    h.update(np.ascontiguousarray(X).tobytes())
    h.update(np.ascontiguousarray(Y).tobytes())
    return h.hexdigest()


@dataclasses.dataclass
class ShardInfo:
    """One shard's manifest entry."""

    filename: str
    row_start: int
    stats: ShardStats
    sha256: str

    @property
    def n_rows(self) -> int:
        return self.stats.n_rows

    def to_json(self) -> dict:
        return {
            "filename": self.filename,
            "row_start": int(self.row_start),
            "sha256": self.sha256,
            **self.stats.to_json(),
        }

    @classmethod
    def from_json(cls, obj: dict) -> "ShardInfo":
        return cls(
            filename=str(obj["filename"]),
            row_start=int(obj["row_start"]),
            stats=ShardStats.from_json(obj),
            sha256=str(obj["sha256"]),
        )


@dataclasses.dataclass
class Manifest:
    """The dataset-level metadata: shape, label convention, shard table."""

    n_rows: int
    n_features: int
    shards: List[ShardInfo]
    binary: bool = True
    positive_label: Optional[int] = None  # set when binary ingest remapped

    def global_stats(self) -> ShardStats:
        return merge_stats([s.stats for s in self.shards])

    def to_json(self) -> dict:
        return {
            "format_version": FORMAT_VERSION,
            "n_rows": int(self.n_rows),
            "n_features": int(self.n_features),
            "binary": bool(self.binary),
            "positive_label": self.positive_label,
            "shards": [s.to_json() for s in self.shards],
        }

    @classmethod
    def from_json(cls, obj: dict) -> "Manifest":
        if "format_version" not in obj:
            raise ValueError(
                "not a tpusvm sharded-dataset manifest (no format_version)"
            )
        v = obj["format_version"]
        if v != FORMAT_VERSION:
            raise ValueError(
                f"unsupported manifest format_version {v!r} (this build "
                f"reads version {FORMAT_VERSION}); re-ingest the dataset"
            )
        m = cls(
            n_rows=int(obj["n_rows"]),
            n_features=int(obj["n_features"]),
            shards=[ShardInfo.from_json(s) for s in obj["shards"]],
            binary=bool(obj["binary"]),
            positive_label=(None if obj.get("positive_label") is None
                            else int(obj["positive_label"])),
        )
        # internal consistency: offsets/counts must tile [0, n_rows)
        off = 0
        for s in m.shards:
            if s.row_start != off:
                raise ValueError(
                    f"manifest corrupt: shard {s.filename} row_start "
                    f"{s.row_start} != running offset {off}"
                )
            off += s.n_rows
        if off != m.n_rows:
            raise ValueError(
                f"manifest corrupt: shard rows sum to {off}, "
                f"n_rows says {m.n_rows}"
            )
        return m


def is_dataset_dir(path: str) -> bool:
    """True when `path` is a directory holding a sharded-dataset manifest
    (how the CLI tells a shards dir from a CSV file)."""
    return os.path.isdir(path) and os.path.exists(
        os.path.join(path, MANIFEST_NAME)
    )


class ShardWriter:
    """Streaming writer: append (X, Y) blocks of any size, get fixed-size
    shards + a manifest out. Peak memory = one shard's rows.

    Usage:
        with ShardWriter(out_dir, rows_per_shard=65536) as w:
            for X, Y in blocks:
                w.append(X, Y)
        manifest = w.manifest

    The manifest is written (atomically, temp-file + rename) on close; a
    crash mid-ingest leaves no manifest, so the directory is never
    mistaken for a complete dataset. Every SHARD write is atomic too
    (bytes staged to a temp file, os.replace), retried under the shared
    I/O retry policy (tpusvm.faults.retry), and journaled: after each
    durable shard the journal (ingest.journal.json) records the shard
    table so far, so a killed ingest resumes from the last durable shard
    (resume=True) instead of leaving an unrecoverable directory. The
    journal is deleted when close() commits the manifest.
    """

    def __init__(self, out_dir: str,
                 rows_per_shard: int = DEFAULT_ROWS_PER_SHARD,
                 binary: bool = True,
                 positive_label: Optional[int] = None,
                 resume: bool = False):
        if rows_per_shard < 1:
            raise ValueError(
                f"rows_per_shard must be >= 1, got {rows_per_shard}"
            )
        self.out_dir = out_dir
        self.rows_per_shard = rows_per_shard
        self.binary = binary
        self.positive_label = positive_label
        self.manifest: Optional[Manifest] = None
        self._shards: List[ShardInfo] = []
        self._pending: List[Tuple[np.ndarray, np.ndarray]] = []
        self._pending_rows = 0
        self._row_start = 0
        self._n_features: Optional[int] = None
        self._closed = False
        self._retry = faults.Retry(faults.DEFAULT_IO_POLICY,
                                   op="ingest.write_shard")
        self._journal_retry = faults.Retry(faults.DEFAULT_IO_POLICY,
                                           op="stream.journal")
        os.makedirs(out_dir, exist_ok=True)
        if resume:
            self._load_journal()

    @classmethod
    def open_append(cls, out_dir: str,
                    rows_per_shard: Optional[int] = None,
                    resume: bool = False):
        """Reopen a COMMITTED dataset directory and append to its tail.

        Returns a stream.append.AppendWriter: the grown dataset is
        bit-identical (shard layout, stats, manifest) to a one-shot
        ingest of the concatenated data, with exactly-once crash safety
        journaled per batch (see tpusvm/stream/append.py)."""
        from tpusvm.stream.append import AppendWriter

        return AppendWriter(out_dir, rows_per_shard=rows_per_shard,
                            resume=resume)

    # ------------------------------------------------------- crash safety
    @property
    def rows_durable(self) -> int:
        """Rows already safely on disk (resume=True): the caller skips
        this many input rows before appending."""
        return self._row_start

    def _journal_path(self) -> str:
        return os.path.join(self.out_dir, JOURNAL_NAME)

    def _write_journal(self) -> None:
        """Atomically record the durable shard table (one rewrite per
        shard — O(shards^2) JSON total, noise next to the shard bytes),
        under the shared I/O retry: transients re-run the whole write,
        kills at the ``stream.journal`` point leave the previous journal
        (and the shard it described) intact for resume."""
        self._journal_retry(self._write_journal_once)

    def _write_journal_once(self) -> None:
        faults.point("stream.journal", shards=len(self._shards))
        obj = {
            "journal_version": JOURNAL_VERSION,
            "rows_per_shard": self.rows_per_shard,
            "binary": self.binary,
            "positive_label": self.positive_label,
            "n_features": self._n_features,
            "shards": [s.to_json() for s in self._shards],
        }
        tmp = self._journal_path() + ".tmp"
        with open(tmp, "w") as f:
            json.dump(obj, f, indent=1)
            f.write("\n")
        fsync_replace(tmp, self._journal_path())

    def _load_journal(self) -> None:
        """Adopt a crashed ingest's durable prefix (resume=True).

        Every journaled shard is re-verified against its checksum before
        being trusted — a shard the journal lists but the disk lost (or
        corrupted) makes resume an error, not a silent hole. No journal
        = nothing to resume, start fresh (mirrors cascade --resume)."""
        jp = self._journal_path()
        if not os.path.exists(jp):
            return
        with open(jp) as f:
            obj = json.load(f)
        if obj.get("journal_version") != JOURNAL_VERSION:
            raise ValueError(
                f"unsupported ingest journal version "
                f"{obj.get('journal_version')!r} in {jp!r}"
                + (" — this is an APPEND-session journal; resume it "
                   "with ShardWriter.open_append(dir, resume=True)"
                   if obj.get("mode") == "append" else "")
            )
        for key, have in (("rows_per_shard", self.rows_per_shard),
                          ("binary", self.binary),
                          ("positive_label", self.positive_label)):
            if obj[key] != have:
                raise ValueError(
                    f"ingest journal was written with {key}={obj[key]!r}, "
                    f"this resume passes {have!r}; re-run with the "
                    "original settings or delete the directory"
                )
        shards = [ShardInfo.from_json(s) for s in obj["shards"]]
        for info in shards:
            path = os.path.join(self.out_dir, info.filename)
            try:
                with np.load(path, allow_pickle=False) as z:
                    X, Y = z["X"], z["Y"]
            except _UNREADABLE as e:
                raise ShardError(
                    info.filename, StreamStatus.CHECKSUM_MISMATCH,
                    f"journaled shard unreadable on resume: {e}"
                ) from e
            if shard_checksum(X, Y) != info.sha256:
                raise ShardError(info.filename,
                                 StreamStatus.CHECKSUM_MISMATCH,
                                 "journaled shard fails its checksum "
                                 "on resume")
        self._shards = shards
        self._row_start = sum(s.n_rows for s in shards)
        self._n_features = (None if obj["n_features"] is None
                            else int(obj["n_features"]))

    def __enter__(self) -> "ShardWriter":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is None:
            self.close()

    def append(self, X: np.ndarray, Y: np.ndarray) -> None:
        X = np.ascontiguousarray(X, np.float64)
        Y = np.ascontiguousarray(Y, np.int32)
        if X.ndim != 2 or Y.ndim != 1 or len(X) != len(Y):
            raise ValueError(
                f"append expects (n, d) X and (n,) Y, got {X.shape} / {Y.shape}"
            )
        if self._n_features is None:
            self._n_features = X.shape[1]
        elif X.shape[1] != self._n_features:
            raise ValueError(
                f"feature count changed mid-ingest: {X.shape[1]} vs "
                f"{self._n_features}"
            )
        if len(X) == 0:
            return
        self._pending.append((X, Y))
        self._pending_rows += len(X)
        while self._pending_rows >= self.rows_per_shard:
            self._flush_shard(self.rows_per_shard)

    def _take(self, n: int) -> Tuple[np.ndarray, np.ndarray]:
        """Pop exactly n rows off the pending buffers."""
        xs, ys, taken = [], [], 0
        while taken < n:
            X, Y = self._pending[0]
            need = n - taken
            if len(X) <= need:
                xs.append(X)
                ys.append(Y)
                taken += len(X)
                self._pending.pop(0)
            else:
                xs.append(X[:need])
                ys.append(Y[:need])
                self._pending[0] = (X[need:], Y[need:])
                taken = n
        self._pending_rows -= n
        if len(xs) == 1:
            return xs[0], ys[0]
        return np.concatenate(xs), np.concatenate(ys)

    def _write_shard_atomic(self, filename: str, X: np.ndarray,
                            Y: np.ndarray) -> None:
        """Stage the npz bytes, then temp-file + os.replace — the same
        discipline as the manifest, so a crash never leaves a truncated
        shard-*.npz behind a committed manifest. The injection point
        sits inside the retried body: transient write faults re-run the
        whole write, corrupt rules mangle the staged bytes (caught later
        by the checksum), kills die pre-rename leaving no partial file."""
        buf = io.BytesIO()
        np.savez(buf, X=X, Y=Y)
        payload = faults.point("ingest.write_shard", payload=buf.getvalue(),
                               shard=filename)
        path = os.path.join(self.out_dir, filename)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(payload)
        fsync_replace(tmp, path)

    def _flush_shard(self, n: int) -> None:
        X, Y = self._take(n)
        idx = len(self._shards)
        filename = f"shard-{idx:05d}.npz"
        self._retry(self._write_shard_atomic, filename, X, Y)
        self._shards.append(ShardInfo(
            filename=filename,
            row_start=self._row_start,
            stats=compute_stats(X, Y),
            sha256=shard_checksum(X, Y),
        ))
        self._row_start += n
        self._write_journal()

    def close(self) -> Manifest:
        if self._closed:
            return self.manifest
        self._closed = True
        if self._pending_rows:
            self._flush_shard(self._pending_rows)
        if not self._shards:
            raise ValueError(
                "ShardWriter: no rows appended — refusing to write an "
                "empty dataset (there is no honest manifest for it)"
            )
        self.manifest = Manifest(
            n_rows=self._row_start,
            n_features=int(self._n_features),
            shards=self._shards,
            binary=self.binary,
            positive_label=self.positive_label,
        )
        # commit transition 1: journal durable, manifest about to land —
        # a kill here resumes by adopting every journaled shard and
        # idempotently rewriting this manifest
        faults.point("stream.journal", commit=True)
        tmp = os.path.join(self.out_dir, MANIFEST_NAME + ".tmp")
        with open(tmp, "w") as f:
            json.dump(self.manifest.to_json(), f, indent=1)
            f.write("\n")
        fsync_replace(tmp, os.path.join(self.out_dir, MANIFEST_NAME))
        # commit transition 2: manifest durable, journal not yet gone —
        # a kill here is the already-committed case (resume re-closes)
        faults.point("stream.journal", committed=True)
        # the manifest supersedes the journal: a committed dataset is no
        # longer a resumable crash site
        jp = self._journal_path()
        if os.path.exists(jp):
            os.remove(jp)
        return self.manifest


def ingest_blocks(out_dir: str,
                  blocks: Iterable[Tuple[np.ndarray, np.ndarray]],
                  rows_per_shard: int = DEFAULT_ROWS_PER_SHARD,
                  binary: bool = True,
                  positive_label: Optional[int] = None,
                  resume: bool = False) -> Manifest:
    """Shard any (X, Y)-block iterator (the generic ingest core).

    resume=True adopts a crashed ingest's journal: rows already durable
    in verified shards are skipped off the front of the block stream
    (the SOURCE must be replayed identically — same CSV, same order),
    so the finished dataset is bit-identical to an uninterrupted ingest.
    """
    with ShardWriter(out_dir, rows_per_shard, binary=binary,
                     positive_label=positive_label, resume=resume) as w:
        skip = w.rows_durable
        for X, Y in blocks:
            if skip:
                if len(X) <= skip:
                    skip -= len(X)
                    continue
                X, Y = X[skip:], Y[skip:]
                skip = 0
            w.append(X, Y)
    return w.manifest


def ingest_arrays(out_dir: str, X: np.ndarray, Y: np.ndarray,
                  rows_per_shard: int = DEFAULT_ROWS_PER_SHARD,
                  binary: Optional[bool] = None,
                  positive_label: Optional[int] = None,
                  resume: bool = False) -> Manifest:
    """Shard an in-memory array pair (synthetic generators, tests).

    binary defaults to whether Y only carries {+1, -1}."""
    Y = np.asarray(Y)
    if binary is None:
        binary = bool(set(np.unique(Y).tolist()) <= {1, -1})
    return ingest_blocks(out_dir, [(np.asarray(X), Y)], rows_per_shard,
                         binary=binary, positive_label=positive_label,
                         resume=resume)


def ingest_csv(out_dir: str, csv_path: str,
               rows_per_shard: int = DEFAULT_ROWS_PER_SHARD,
               n_limit: Optional[int] = None,
               binary: bool = True,
               positive_label: int = 1,
               block_rows: int = 8192,
               resume: bool = False) -> Manifest:
    """Stream a labelled CSV into shards with reference reader semantics
    (header skipped, short rows dropped, n_limit cap, one-vs-rest label
    mapping with a parameterised positive class). Peak memory is
    max(block_rows, rows_per_shard) rows — the CSV is never whole in RAM.
    resume=True continues a killed ingest of the SAME CSV from its
    journal (ingest_blocks).
    """
    return ingest_blocks(
        out_dir,
        read_csv_blocks(csv_path, block_rows=min(block_rows, rows_per_shard),
                        n_limit=n_limit, binary=binary,
                        positive_label=positive_label),
        rows_per_shard,
        binary=binary,
        positive_label=positive_label if binary else None,
        resume=resume,
    )


class ShardedDataset:
    """Read-side handle on an ingested dataset directory.

    Loading granularity is one shard; `load_labels` reads ONLY the Y
    member of each npz (np.load on an npz is lazy per member), so a
    labels-only pass — stratified assignment, fold splitting — costs 4
    bytes/row of IO, not the full feature bytes.
    """

    def __init__(self, path: str, manifest: Manifest):
        self.path = path
        self.manifest = manifest

    @property
    def n_rows(self) -> int:
        return self.manifest.n_rows

    @property
    def n_features(self) -> int:
        return self.manifest.n_features

    @property
    def n_shards(self) -> int:
        return len(self.manifest.shards)

    def shard_path(self, i: int) -> str:
        return os.path.join(self.path, self.manifest.shards[i].filename)

    def load_shard(self, i: int, verify: bool = False
                   ) -> Tuple[np.ndarray, np.ndarray]:
        """One shard's (X, Y); verify=True re-checksums the content.

        Every failure mode is a ShardError NAMING the shard and carrying
        a StreamStatus — a bit-flipped npz no longer surfaces as a raw
        zlib/zipfile traceback from the prefetch thread: MISSING_FILE
        for an absent file, CHECKSUM_MISMATCH for unreadable bytes, and
        (verify=True) whichever integrity code the manifest check finds.
        """
        info = self.manifest.shards[i]
        faults.point("stream.read_shard", shard=info.filename)
        try:
            with np.load(self.shard_path(i), allow_pickle=False) as z:
                X, Y = z["X"], z["Y"]
        except FileNotFoundError as e:
            raise ShardError(info.filename, StreamStatus.MISSING_FILE,
                             str(e)) from e
        except _UNREADABLE as e:
            raise ShardError(info.filename, StreamStatus.CHECKSUM_MISMATCH,
                             f"unreadable shard bytes: "
                             f"{type(e).__name__}: {e}") from e
        if verify:
            status = self._check_shard(i, X, Y)
            if status != StreamStatus.OK:
                raise ShardError(info.filename, status)
        return X, Y

    def load_labels(self) -> np.ndarray:
        """All labels in global row order (Y-only pass; X never read)."""
        ys = []
        for i in range(self.n_shards):
            with np.load(self.shard_path(i), allow_pickle=False) as z:
                ys.append(z["Y"])
        return np.concatenate(ys)

    def load_arrays(self) -> Tuple[np.ndarray, np.ndarray]:
        """The whole dataset, concatenated — MATERIALISES n_rows x
        n_features in memory; the escape hatch for consumers that need a
        flat array (single-chip fit), not the streaming path."""
        xs, ys = [], []
        for i in range(self.n_shards):
            X, Y = self.load_shard(i)
            xs.append(X)
            ys.append(Y)
        return np.concatenate(xs), np.concatenate(ys)

    def iter_shards(self) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        for i in range(self.n_shards):
            yield self.load_shard(i)

    def stats(self) -> ShardStats:
        return self.manifest.global_stats()

    def scaler(self):
        """MinMaxScaler fitted from manifest stats — bit-identical to a
        fit on the concatenated array (stream.stats.scaler_from_stats)."""
        return scaler_from_stats(self.stats())

    # -------------------------------------------------------- validation
    def _check_shard(self, i: int, X: np.ndarray,
                     Y: np.ndarray) -> StreamStatus:
        info = self.manifest.shards[i]
        if (len(X) != info.n_rows or len(Y) != info.n_rows
                or X.shape[1] != self.n_features):
            return StreamStatus.ROW_COUNT_MISMATCH
        if shard_checksum(X, Y) != info.sha256:
            return StreamStatus.CHECKSUM_MISMATCH
        s = compute_stats(X, Y)
        if (not np.array_equal(s.min_val, info.stats.min_val)
                or not np.array_equal(s.max_val, info.stats.max_val)
                or s.class_counts != info.stats.class_counts):
            return StreamStatus.STATS_MISMATCH
        return StreamStatus.OK

    def validate(self) -> List[StreamStatus]:
        """Re-derive every shard's manifest claims from its bytes; one
        StreamStatus per shard (all OK == the dataset is exactly what the
        manifest says it is). Loads one shard at a time."""
        out = []
        for i in range(self.n_shards):
            if not os.path.exists(self.shard_path(i)):
                out.append(StreamStatus.MISSING_FILE)
                continue
            try:
                with np.load(self.shard_path(i), allow_pickle=False) as z:
                    X, Y = z["X"], z["Y"]
            except _UNREADABLE:
                # includes BadZipFile/zlib.error: damaged container bytes
                # are an integrity failure, not a crash
                out.append(StreamStatus.CHECKSUM_MISMATCH)
                continue
            out.append(self._check_shard(i, X, Y))
        return out


def open_dataset(path: str) -> ShardedDataset:
    """Open an ingested dataset directory (reads + validates the manifest's
    internal consistency; shard bytes are checked by validate())."""
    manifest_path = os.path.join(path, MANIFEST_NAME)
    if not os.path.exists(manifest_path):
        raise FileNotFoundError(
            f"{path!r} is not a sharded dataset (no {MANIFEST_NAME}; "
            "create one with `tpusvm ingest`)"
        )
    with open(manifest_path) as f:
        manifest = Manifest.from_json(json.load(f))
    return ShardedDataset(path, manifest)
