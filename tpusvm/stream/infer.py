"""Streamed inference: decision scores over prefetched batches.

The reference's predict phase (gpu_svm_main3.cu:277-296) scores an
in-memory test matrix in one pass; tpusvm's decision_function keeps that
shape. This module removes the "in-memory" part: batches flow off a
ShardReader — IO for the next shard overlapping the device matmul of the
current batch — through the model's own decision_function/predict (so the
train-time scaler, the SV-only sum, and the strict >0 sign rule are
exactly the in-memory code path; the scores literally come from the same
jitted kernel), with peak memory bounded by prefetch_depth + 1 shards
plus one batch.

A FIXED batch_size means the jitted scoring kernel compiles once for the
stream (plus once for the short tail batch) — the compile-cache discipline
serve.buckets applies to online traffic, applied to offline scans.
"""

from __future__ import annotations

from typing import Iterator, Optional, Tuple

import numpy as np

from tpusvm.stream.format import ShardedDataset
from tpusvm.stream.reader import ShardReader


DEFAULT_BATCH = 4096


def predict_stream(model, dataset: ShardedDataset,
                   batch_size: int = DEFAULT_BATCH,
                   prefetch_depth: int = 2,
                   ) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """Yield (scores, Y) per fixed-size batch, in global row order.

    scores is model.decision_function on the RAW batch rows (the model
    applies its train-time scaler itself — the scaled-with-TRAIN-min/max
    evaluation protocol, main3.cpp:338-339); Y is the batch's stored
    labels. Binary models yield (m,) scores; one-vs-rest (m, K).
    """
    reader = ShardReader(dataset, prefetch_depth=prefetch_depth)
    for Xb, Yb in reader.batches(batch_size):
        yield np.asarray(model.decision_function(Xb)), Yb


def evaluate_stream(model, dataset: ShardedDataset,
                    batch_size: int = DEFAULT_BATCH,
                    prefetch_depth: int = 2,
                    n_limit: Optional[int] = None) -> Tuple[float, int]:
    """Accuracy of `model` over the dataset, never holding more than the
    residency bound. Returns (accuracy, n_rows_scored).

    n_limit caps scored rows (the gpu_svm_main4 argv[1] semantics applied
    to evaluation); the reader is closed early, so capped runs do not pay
    IO for the rest of the dataset.
    """
    correct = 0
    scored = 0
    reader = ShardReader(dataset, prefetch_depth=prefetch_depth)
    batches = reader.batches(batch_size)
    for Xb, Yb in batches:
        if n_limit is not None and scored + len(Xb) > n_limit:
            keep = n_limit - scored
            Xb, Yb = Xb[:keep], Yb[:keep]
        if len(Xb):
            pred = np.asarray(model.predict(Xb))
            correct += int((pred == Yb).sum())
            scored += len(Xb)
        if n_limit is not None and scored >= n_limit:
            batches.close()  # releases the reader via its finally
            break
    if scored == 0:
        raise ValueError("evaluate_stream: no rows scored")
    return correct / scored, scored
