"""Background-prefetching shard reader with a hard residency bound.

The accelerator-feeding discipline (tf.data, Murray et al. 2021; PAPERS.md):
IO for shard k+1 overlaps compute on shard k, so the consumer never stalls
on disk — double buffering generalised to a depth-`prefetch_depth` pipeline.
Concurrency model mirrors serve.batcher's: ONE daemon producer thread does
all the loading, any consumer iterates; hand-off is a queue, shutdown is a
sentinel, and a producer exception is re-raised in the consumer (never
swallowed in a dead thread).

The memory contract is enforced by construction, not convention: a
semaphore with `prefetch_depth + 1` permits gates every shard LOAD, and a
shard's permit is released only when the consumer moves past its block
(or the reader closes). At any instant

    resident shards = permits held <= prefetch_depth + 1

counted across the producer's in-flight load, the queue, and the block the
consumer is holding. `max_live_shards` records the high-water mark — the
counting hook the tests assert on.

Shard ORDER is deterministic: manifest order by default, or a fixed
permutation drawn from np.random.default_rng(seed) — same seed, same
traversal, on every platform (the tune/folds reproducibility rule applied
to IO).
"""

from __future__ import annotations

import queue
import threading
from typing import Iterator, Optional, Tuple

import numpy as np

from tpusvm import faults
from tpusvm.stream.format import ShardedDataset, ShardError
from tpusvm.status import StreamStatus

_SENTINEL = object()


class ShardReader:
    """Iterate a ShardedDataset's (X, Y) blocks with background prefetch.

    Args:
      dataset: an open ShardedDataset.
      prefetch_depth: shards loaded ahead of the consumer (>= 1; 1 is
        classic double buffering). Peak residency is prefetch_depth + 1
        shards, enforced by a permit per resident shard.
      seed: None = manifest order; an int = a deterministic shuffled
        shard order (np.random.default_rng(seed).permutation).
      shards: optional subset of shard indices to read — only those
        shards are ever loaded (manifest order unless seed shuffles the
        subset). The pod tier's leaf loader: a leaf streams exactly the
        shards overlapping its row range, with per-shard blocks
        byte-identical to a full-manifest pass, and the residency bound
        unchanged. Indices must be unique and in range.
      scaler: optional fitted MinMaxScaler applied on the fly (e.g. the
        manifest-fitted global scaler), so consumers see scaled rows
        without a second pass over the data.
      dtype: optional numpy dtype the X block is cast to after scaling.
      transform: optional row-wise feature transform applied LAST (after
        scaler and dtype), per shard, on the producer thread — the
        approximate-kernel prefetch hook (tpusvm.approx.FeatureMap
        .transform_np): mapped features are produced while IO overlaps
        compute, so no materialised (n, D) feature array ever exists and
        the residency bound is unchanged (a block is one resident shard
        whether raw or mapped). Must be a pure (m, d) -> (m, D) function.
      verify: re-checksum each shard against the manifest on load.
      metrics: an obs.registry.MetricsRegistry for the pipeline health
        counters (default: the process-wide default_registry) —
        `stream.shards_loaded` (loads completed),
        `stream.producer_stalls` (loads that had to WAIT for a permit:
        the consumer is the bottleneck — healthy), and
        `stream.consumer_stalls` (consumer polls that found the queue
        empty: disk is the bottleneck — raise prefetch_depth), plus the
        `stream.live_shards` high-water gauge (the residency bound the
        tests audit via max_live_shards).

    Iterating yields (X, Y) per shard. `batches(m)` re-chunks the stream
    into fixed m-row batches (last one short) without widening the
    residency bound — a batch view borrows the current block.
    """

    def __init__(self, dataset: ShardedDataset, prefetch_depth: int = 2,
                 seed: Optional[int] = None, scaler=None, dtype=None,
                 verify: bool = False, metrics=None,
                 retry_policy: Optional[faults.RetryPolicy] = None,
                 transform=None, shards=None):
        if prefetch_depth < 1:
            raise ValueError(
                f"prefetch_depth must be >= 1, got {prefetch_depth}"
            )
        self.dataset = dataset
        self.prefetch_depth = prefetch_depth
        self.scaler = scaler
        self.dtype = dtype
        self.transform = transform
        self.verify = verify
        if shards is None:
            order = np.arange(dataset.n_shards)
        else:
            order = np.asarray(shards, np.int64)
            if order.ndim != 1 or len(set(order.tolist())) != len(order):
                raise ValueError(
                    "shards must be a flat sequence of unique indices; "
                    f"got {shards!r}"
                )
            if order.size and (order.min() < 0
                               or order.max() >= dataset.n_shards):
                raise IndexError(
                    f"shard indices out of range [0, {dataset.n_shards})"
                )
        if seed is not None:
            order = np.random.default_rng(seed).permutation(order)
        self.shard_order = order
        if metrics is None:
            from tpusvm.obs.registry import default_registry

            metrics = default_registry()
        self._loaded = metrics.counter("stream.shards_loaded")
        self._producer_stalls = metrics.counter("stream.producer_stalls")
        self._consumer_stalls = metrics.counter("stream.consumer_stalls")
        self._live_gauge = metrics.gauge("stream.live_shards")
        # transient read faults (injected or real flaky I/O) are retried
        # with backoff before the consumer ever hears about them; a read
        # that stays broken surfaces as ShardError(READ_FAILED) naming
        # the shard, not a raw exception from the prefetch thread
        self._retry = faults.Retry(
            retry_policy or faults.DEFAULT_IO_POLICY,
            op="stream.read_shard", metrics=metrics,
        )
        # residency accounting: one permit per resident shard
        self._permits = threading.Semaphore(prefetch_depth + 1)
        self._lock = threading.Lock()
        self._live = 0
        self.max_live_shards = 0
        self._q: "queue.Queue" = queue.Queue()
        self._stop = threading.Event()
        self._consumer_holds = False
        self._started = False
        self._worker = threading.Thread(target=self._produce, daemon=True,
                                        name="tpusvm-stream-reader")

    # ---------------------------------------------------------- producer
    def _acquire(self) -> bool:
        """One permit per shard load; polls so close() can interrupt."""
        stalled = False
        while not self._stop.is_set():
            if self._permits.acquire(timeout=0.05):
                with self._lock:
                    self._live += 1
                    self.max_live_shards = max(self.max_live_shards,
                                               self._live)
                self._live_gauge.set_max(self.max_live_shards)
                return True
            if not stalled:
                # first miss only: one stalled LOAD = one stall, however
                # many 50ms polls it spans
                stalled = True
                self._producer_stalls.inc()
        return False

    def _release(self) -> None:
        with self._lock:
            self._live -= 1
        self._permits.release()

    def _produce(self) -> None:
        try:
            for i in self.shard_order:
                if not self._acquire():
                    return  # closed while waiting for a permit
                try:
                    try:
                        X, Y = self._retry(self.dataset.load_shard, int(i),
                                           verify=self.verify)
                    except faults.RetryExhaustedError as e:
                        raise ShardError(
                            self.dataset.manifest.shards[int(i)].filename,
                            StreamStatus.READ_FAILED, str(e),
                        ) from e
                    if self.scaler is not None:
                        X = self.scaler.transform(X)
                    if self.dtype is not None:
                        X = np.asarray(X, self.dtype)
                    if self.transform is not None:
                        X = self.transform(X)
                except BaseException:
                    self._release()
                    raise
                self._loaded.inc()
                self._q.put((X, Y))
                if self._stop.is_set():
                    return
            self._q.put(_SENTINEL)
        except BaseException as e:  # noqa: BLE001 — re-raised consumer-side
            self._q.put(e)

    # ---------------------------------------------------------- consumer
    def __iter__(self) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        if self._started:
            raise RuntimeError(
                "ShardReader is single-pass; construct a new reader to "
                "re-read (same seed = same order)"
            )
        # tpusvm: guarded-by=written on the consumer thread before the producer exists (Thread.start is the fence)
        self._started = True
        self._worker.start()
        try:
            while True:
                try:
                    item = self._q.get_nowait()
                except queue.Empty:
                    # the consumer outran the producer: disk/IO is the
                    # bottleneck for this stretch
                    self._consumer_stalls.inc()
                    item = self._q.get()
                if item is _SENTINEL:
                    return
                if isinstance(item, BaseException):
                    raise item
                if self._consumer_holds:
                    self._release()  # moving past the previous block
                # tpusvm: guarded-by=consumer-thread confined (only the single consumer and its finally-close touch it)
                self._consumer_holds = True
                yield item
                # NOTE: the yielded block's permit is released when the
                # consumer asks for the NEXT block (or in close()) — the
                # block it is still processing stays counted as resident.
        finally:
            self.close()

    def batches(self, batch_size: int
                ) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        """Fixed-size (X, Y) batches re-chunked across shard boundaries.

        Peak residency is unchanged (a carried remainder is a copy of at
        most batch_size - 1 rows, not a retained shard).
        """
        if batch_size < 1:
            raise ValueError(f"batch_size must be >= 1, got {batch_size}")
        rx, ry = None, None
        for X, Y in self:
            if rx is not None:
                X = np.concatenate([rx, X])
                Y = np.concatenate([ry, Y])
                rx = ry = None
            n_full = len(X) // batch_size * batch_size
            for s in range(0, n_full, batch_size):
                yield X[s:s + batch_size], Y[s:s + batch_size]
            if n_full < len(X):
                # copy: the remainder must not pin the whole shard block
                rx, ry = X[n_full:].copy(), Y[n_full:].copy()
        if rx is not None:
            yield rx, ry

    @property
    def live_shards(self) -> int:
        with self._lock:
            return self._live

    def close(self) -> None:
        """Stop the producer and drop queued blocks. Idempotent."""
        self._stop.set()
        if self._started:
            while True:
                try:
                    item = self._q.get_nowait()
                except queue.Empty:
                    break
                if item is not _SENTINEL and not isinstance(item,
                                                            BaseException):
                    self._release()
            if self._consumer_holds:
                # tpusvm: guarded-by=consumer-thread confined (close runs on the consumer's __iter__ finally, or after it exits)
                self._consumer_holds = False
                self._release()
