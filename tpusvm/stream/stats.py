"""Mergeable per-shard dataset statistics.

The reference's distributed preprocessing is rank 0 computing global
feature min/max over the FULL dataset, then broadcasting it before the
scatter (mpi_svm_main3.cpp:529-539) — which requires rank 0 to hold all of
X. This module is the out-of-core replacement: each shard records its own
min/max and class counts at INGEST time, and the global statistics are an
exact merge of the partials — min/max are selections, so elementwise
minimum/maximum over shards is bit-identical to np.min/np.max on the
concatenated array, and class counts are plain sums. `MinMaxScaler` can
therefore be fitted from a manifest without a single row of X in memory
(scaler_from_stats), and stratified leaf assignment can budget per class
from counts alone.

JSON round-tripping preserves the bit-parity claim: Python's json module
serialises floats with repr (shortest round-trip form), so a float64
min/max written to the manifest reads back as the identical bit pattern.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Sequence

import numpy as np

from tpusvm.data.scaler import MinMaxScaler, merge_minmax


@dataclasses.dataclass
class ShardStats:
    """Statistics of one shard (or of a whole dataset, after merging).

    min_val/max_val are per-feature float64 (the CSV readers' dtype);
    class_counts maps raw int label -> row count.
    """

    n_rows: int
    min_val: np.ndarray
    max_val: np.ndarray
    class_counts: Dict[int, int]

    def to_json(self) -> dict:
        return {
            "n_rows": int(self.n_rows),
            "min": [float(v) for v in self.min_val],
            "max": [float(v) for v in self.max_val],
            # JSON object keys are strings; from_json undoes this
            "class_counts": {str(k): int(v)
                             for k, v in sorted(self.class_counts.items())},
        }

    @classmethod
    def from_json(cls, obj: dict) -> "ShardStats":
        return cls(
            n_rows=int(obj["n_rows"]),
            min_val=np.asarray(obj["min"], np.float64),
            max_val=np.asarray(obj["max"], np.float64),
            class_counts={int(k): int(v)
                          for k, v in obj["class_counts"].items()},
        )


def compute_stats(X: np.ndarray, Y: np.ndarray) -> ShardStats:
    """Per-shard statistics of one (X, Y) block. X must be non-empty
    (a shard with zero rows has no honest min/max)."""
    X = np.asarray(X)
    Y = np.asarray(Y)
    if len(X) == 0:
        raise ValueError("compute_stats: empty shard")
    labels, counts = np.unique(Y, return_counts=True)
    return ShardStats(
        n_rows=int(len(X)),
        min_val=np.min(X, axis=0).astype(np.float64),
        max_val=np.max(X, axis=0).astype(np.float64),
        class_counts={int(l): int(c) for l, c in zip(labels, counts)},
    )


def merge_stats(parts: Sequence[ShardStats]) -> ShardStats:
    """Exact merge of per-shard statistics into dataset-global ones.

    min/max merge through data.scaler.merge_minmax (bit-identical to a
    full-array fit); counts are summed. Raises on an empty sequence.
    """
    if not parts:
        raise ValueError("merge_stats: no shard stats to merge")
    lo, hi = merge_minmax((p.min_val, p.max_val) for p in parts)
    counts: Dict[int, int] = {}
    for p in parts:
        for k, v in p.class_counts.items():
            counts[k] = counts.get(k, 0) + v
    return ShardStats(
        n_rows=sum(p.n_rows for p in parts),
        min_val=lo,
        max_val=hi,
        class_counts=counts,
    )


def scaler_from_stats(stats: ShardStats) -> MinMaxScaler:
    """The rank-0 global min/max broadcast, done without ever holding X:
    a MinMaxScaler whose transform is bit-identical to one fitted on the
    concatenated array (including the degenerate-range branch, which
    lives in the scaler itself)."""
    return MinMaxScaler.from_stats(stats.min_val, stats.max_val)
