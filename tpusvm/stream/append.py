"""Crash-safe append ingest: reopen a committed dataset, grow its tail.

The stream-side half of the online-learning loop (ROADMAP): micro-batches
arrive continuously and must land DURABLY in the dataset a `tpusvm
refresh`/autopilot refit will read, with exactly-once semantics under a
kill at any instant. `AppendWriter` (reached as
`ShardWriter.open_append(dir)`) reopens a committed dataset directory and
appends blocks to it so that the grown dataset is BIT-IDENTICAL — shard
boundaries, per-shard stats, checksums, manifest JSON — to a one-shot
ingest of the concatenated data:

  * the manifest's existing shard table is adopted verbatim, so the
    merged feature min/max is the exact merge of OLD and new stats (the
    reopen close() bug this module exists to prevent: a naive rewriter
    would refit the range from the tail only);
  * a short trailing shard is adopted into the pending buffer and
    re-cut at rows_per_shard boundaries exactly as a one-shot ingest
    would have cut it, which also keeps the global row order a strict
    PREFIX EXTENSION — the contract `tune.warm.deployed_seed` and
    `stream.assign` enforce by name;
  * every session shard is staged under `<name>.npz.stage` and renamed
    into place only at commit, so the files a reader's manifest points
    at are NEVER touched mid-session.

Exactly-once under kill: the ingest journal (same `ingest.journal.json`
file, `journal_version` 2, mode "append") records after every durable
flush the session shard table (= the durable high-water row id) plus a
per-batch content CRC ledger. A resumed session (`open_append(dir,
resume=True)`) verifies every journaled shard against its checksum,
re-derives the high-water mark, and the caller REPLAYS the same batch
stream: rows at or below the mark are skipped (their CRCs re-verified —
a divergent replay is an `AppendError`, never silent corruption), the
straddling batch is split at the mark, and everything above is appended.
A batch is therefore applied exactly once no matter where the kill
landed — including between the commit's renames and the manifest write
(detected as an already-committed session and finished idempotently).

Fault points: `stream.append` fires at every journal write and at
commit (kill/transient/latency rules); the staged shard bytes flow
through the existing `ingest.write_shard` point (corrupt rules apply).
"""

from __future__ import annotations

import json
import os
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from tpusvm import faults
from tpusvm.status import StreamStatus
from tpusvm.utils.durable import fsync_replace
from tpusvm.stream.format import (
    JOURNAL_NAME,
    MANIFEST_NAME,
    Manifest,
    ShardError,
    ShardInfo,
    ShardWriter,
    shard_checksum,
)

APPEND_JOURNAL_VERSION = 2


class AppendError(ValueError):
    """An append session cannot proceed safely (divergent replay,
    changed settings, dataset modified under the journal)."""


def batch_crc(X: np.ndarray, Y: np.ndarray) -> int:
    """Content CRC of one appended micro-batch (shape header + rows),
    computed on the canonical dtypes so a replay from any source that
    converts identically verifies identically."""
    X = np.ascontiguousarray(X, np.float64)
    Y = np.ascontiguousarray(Y, np.int32)
    c = zlib.crc32(f"{X.shape[0]},{X.shape[1]}".encode())
    c = zlib.crc32(X.tobytes(), c)
    return zlib.crc32(Y.tobytes(), c) & 0xFFFFFFFF


class AppendWriter(ShardWriter):
    """ShardWriter over an EXISTING committed dataset directory.

    Usage (one session; batches of any size):

        w = ShardWriter.open_append(dir)        # or resume=True
        for X, Y in micro_batches:              # replayed from the
            w.append(X, Y)                      #   session start on resume
        manifest = w.close()                    # atomic commit

    See the module docstring for the crash-safety contract.
    """

    def __init__(self, out_dir: str,
                 rows_per_shard: Optional[int] = None,
                 resume: bool = False):
        manifest_path = os.path.join(out_dir, MANIFEST_NAME)
        if not os.path.exists(manifest_path):
            raise FileNotFoundError(
                f"{out_dir!r} is not a committed sharded dataset (no "
                f"{MANIFEST_NAME}); append reopens an existing dataset — "
                "create one with `tpusvm ingest` first"
            )
        with open(manifest_path) as f:
            base = Manifest.from_json(json.load(f))
        rps = self._resolve_rows_per_shard(base, rows_per_shard)
        super().__init__(out_dir, rows_per_shard=rps, binary=base.binary,
                         positive_label=base.positive_label, resume=False)
        self._base_manifest = base
        self._n_features = base.n_features
        # adopt a short trailing shard into the pending buffer: its rows
        # are re-cut with the new data exactly as a one-shot ingest of
        # the concatenation would cut them (bit-identical shard layout)
        tail = base.shards[-1]
        if tail.n_rows < rps:
            keep = base.shards[:-1]
            self._tail_info: Optional[ShardInfo] = tail
            self._tail_adopted = tail.n_rows
        else:
            keep = list(base.shards)
            self._tail_info = None
            self._tail_adopted = 0
        self._shards = list(keep)
        self._row_start = sum(s.n_rows for s in keep)
        self._session_start = len(keep)
        # per-batch exactly-once ledger (seq -> record); _new_skip is the
        # durable high-water mark in NEW-row coordinates
        self._batches: Dict[int, dict] = {}
        self._batch_seq = 0
        self._rows_seen = 0
        self._new_skip = 0
        self._already_committed = False
        self._append_retry = faults.Retry(faults.DEFAULT_IO_POLICY,
                                          op="stream.append")
        if resume:
            self._resume_session()
        elif os.path.exists(self._journal_path()):
            raise AppendError(
                f"{out_dir!r} has an append journal from a crashed "
                "session; reopen with resume=True and replay the same "
                "batch stream (or delete the journal to abandon it)"
            )
        if self._tail_info is not None and not self._already_committed \
                and self._tail_covered < self._tail_adopted:
            self._adopt_tail_rows()

    # ----------------------------------------------------------- opening
    @staticmethod
    def _resolve_rows_per_shard(base: Manifest,
                                rows_per_shard: Optional[int]) -> int:
        sizes = [s.n_rows for s in base.shards]
        if rows_per_shard is None:
            if len(sizes) > 1:
                rows_per_shard = sizes[0]
            else:
                # a single (possibly short) shard under-determines the
                # original cut; the library default keeps parity with
                # the default one-shot ingest
                from tpusvm.stream.format import DEFAULT_ROWS_PER_SHARD

                rows_per_shard = max(DEFAULT_ROWS_PER_SHARD, sizes[0])
        bad = [i for i, n in enumerate(sizes[:-1]) if n != rows_per_shard]
        if bad or sizes[-1] > rows_per_shard:
            raise AppendError(
                f"rows_per_shard={rows_per_shard} does not match the "
                f"dataset's shard layout (shard sizes {sizes}); pass the "
                "value the dataset was ingested with"
            )
        return rows_per_shard

    def _adopt_tail_rows(self) -> None:
        info = self._tail_info
        path = os.path.join(self.out_dir, info.filename)
        try:
            with np.load(path, allow_pickle=False) as z:
                X, Y = z["X"], z["Y"]
        except OSError as e:
            raise ShardError(info.filename, StreamStatus.MISSING_FILE,
                             f"tail shard unreadable on append: {e}") from e
        if shard_checksum(X, Y) != info.sha256:
            raise ShardError(info.filename, StreamStatus.CHECKSUM_MISMATCH,
                             "tail shard fails its checksum on append")
        skip = min(len(X), self._tail_covered)
        if skip < len(X):
            self._pending.append((X[skip:], Y[skip:]))
            self._pending_rows += len(X) - skip

    @property
    def _tail_covered(self) -> int:
        """Adopted tail rows already inside durable session shards."""
        flushed = sum(s.n_rows for s in self._shards[self._session_start:])
        return min(self._tail_adopted, flushed)

    # ----------------------------------------------------------- journal
    def _write_journal(self) -> None:
        """v2 append journal: durable session shard table + batch CRC
        ledger, written atomically after every flush, under the shared
        I/O retry policy (the injection point sits inside the retried
        body: transients re-run the whole write, kills leave the
        previous journal — and the shard it described — intact)."""
        self._append_retry(self._write_journal_once)

    def _write_journal_once(self) -> None:
        faults.point("stream.append",
                     shards=len(self._shards) - self._session_start)
        obj = {
            "journal_version": APPEND_JOURNAL_VERSION,
            "mode": "append",
            "rows_per_shard": self.rows_per_shard,
            "binary": self.binary,
            "positive_label": self.positive_label,
            "n_features": self._n_features,
            "base_shards": self._session_start,
            "base_manifest_rows": self._base_manifest.n_rows,
            "tail_adopted": self._tail_adopted,
            "tail_filename": (self._tail_info.filename
                              if self._tail_info is not None else None),
            "shards": [s.to_json()
                       for s in self._shards[self._session_start:]],
            "batches": [self._batches[k] for k in sorted(self._batches)],
        }
        tmp = self._journal_path() + ".tmp"
        with open(tmp, "w") as f:
            json.dump(obj, f, indent=1)
            f.write("\n")
        fsync_replace(tmp, self._journal_path())

    def _load_append_journal(self) -> Optional[dict]:
        jp = self._journal_path()
        if not os.path.exists(jp):
            return None
        with open(jp) as f:
            obj = json.load(f)
        v = obj.get("journal_version")
        if v != APPEND_JOURNAL_VERSION or obj.get("mode") != "append":
            raise AppendError(
                f"{jp!r} is not an append-session journal "
                f"(journal_version {v!r}, mode {obj.get('mode')!r}); a "
                "v1 journal belongs to a crashed FRESH ingest — resume "
                "it with `tpusvm ingest --resume` instead"
            )
        for key, have in (("rows_per_shard", self.rows_per_shard),
                          ("binary", self.binary),
                          ("positive_label", self.positive_label),
                          ("n_features", self._n_features)):
            if obj[key] != have:
                raise AppendError(
                    f"append journal was written with {key}={obj[key]!r}, "
                    f"this resume passes {have!r}; reopen with the "
                    "original settings"
                )
        return obj

    def _resume_session(self) -> None:
        obj = self._load_append_journal()
        if obj is None:
            return  # nothing to resume: a fresh session (house semantics)
        session = [ShardInfo.from_json(s) for s in obj["shards"]]
        if obj["base_shards"] != self._session_start \
                or obj["tail_adopted"] != self._tail_adopted:
            # the on-disk manifest no longer matches the journal's view
            # of the base dataset — either the session already committed
            # (manifest replaced, journal delete lost to the kill) or
            # someone mutated the dataset underneath us
            if self._is_committed_session(obj, session):
                self._finish_committed(obj, session)
                return
            raise AppendError(
                f"dataset {self.out_dir!r} changed under the append "
                f"journal (journal saw {obj['base_shards']} base shards / "
                f"{obj['base_manifest_rows']} rows, manifest now has "
                f"{len(self._base_manifest.shards)} shards / "
                f"{self._base_manifest.n_rows} rows)"
            )
        for info in session:
            self._verify_session_shard(info)
        self._shards.extend(session)
        self._row_start += sum(s.n_rows for s in session)
        flushed = sum(s.n_rows for s in session)
        self._new_skip = max(0, flushed - self._tail_adopted)
        self._batches = {int(b["seq"]): b for b in obj["batches"]}

    def _verify_session_shard(self, info: ShardInfo) -> None:
        """A journaled session shard must exist (staged, or final after
        a crashed commit) and match its checksum."""
        for suffix in (".stage", ""):
            path = os.path.join(self.out_dir, info.filename + suffix)
            if not os.path.exists(path):
                continue
            try:
                with np.load(path, allow_pickle=False) as z:
                    X, Y = z["X"], z["Y"]
            except Exception as e:  # noqa: BLE001 — classified below
                raise ShardError(
                    info.filename, StreamStatus.CHECKSUM_MISMATCH,
                    f"journaled append shard unreadable on resume: {e}"
                ) from e
            if shard_checksum(X, Y) != info.sha256:
                raise ShardError(info.filename,
                                 StreamStatus.CHECKSUM_MISMATCH,
                                 "journaled append shard fails its "
                                 "checksum on resume")
            return
        raise ShardError(info.filename, StreamStatus.MISSING_FILE,
                         "journaled append shard lost before resume")

    def _is_committed_session(self, obj: dict,
                              session: List[ShardInfo]) -> bool:
        """True when the CURRENT manifest already carries the journaled
        session: committed rows = the journal's base rows, minus its
        adopted tail (those rows were re-cut into the session shards),
        plus every session shard — and each session shard must appear in
        the manifest under its journaled name and checksum."""
        m = self._base_manifest
        expected = (obj["base_manifest_rows"] - obj["tail_adopted"]
                    + sum(s.n_rows for s in session))
        by_name = {s.filename: s.sha256 for s in m.shards}
        return (bool(session) and m.n_rows == expected
                and all(by_name.get(s.filename) == s.sha256
                        for s in session))

    def _finish_committed(self, obj: dict,
                          session: List[ShardInfo]) -> None:
        """The manifest already carries the whole session (the kill
        landed between the manifest write and the journal delete):
        everything is durable, the replay skips every row, and close()
        just re-deletes the journal."""
        self._already_committed = True
        self._shards = list(self._base_manifest.shards)
        self._session_start = len(self._shards)
        self._row_start = self._base_manifest.n_rows
        self._tail_adopted = 0
        self._tail_info = None
        flushed = sum(s.n_rows for s in session)
        self._new_skip = max(0, flushed - int(obj["tail_adopted"]))
        self._batches = {int(b["seq"]): b for b in obj["batches"]}

    # ------------------------------------------------------------ append
    def _write_shard_atomic(self, filename: str, X: np.ndarray,
                            Y: np.ndarray) -> None:
        # session shards stage under <name>.stage: the files the
        # committed manifest points at are never touched mid-session
        super()._write_shard_atomic(filename + ".stage", X, Y)

    def append(self, X: np.ndarray, Y: np.ndarray) -> None:
        """Append one micro-batch. On a resumed session the SAME batch
        stream must be replayed from the session start: durable rows are
        skipped (CRC-verified against the journal ledger), the batch
        straddling the high-water mark is split, everything above is
        appended — exactly once regardless of where the kill landed."""
        X = np.ascontiguousarray(X, np.float64)
        Y = np.ascontiguousarray(Y, np.int32)
        if X.ndim != 2 or Y.ndim != 1 or len(X) != len(Y):
            raise ValueError(
                f"append expects (n, d) X and (n,) Y, got {X.shape} / "
                f"{Y.shape}"
            )
        if X.shape[1] != self._n_features:
            raise ValueError(
                f"append feature count {X.shape[1]} != dataset's "
                f"{self._n_features}"
            )
        seq = self._batch_seq
        rec = {"seq": seq, "row_start": self._rows_seen,
               "n_rows": int(len(X)), "crc32": batch_crc(X, Y)}
        old = self._batches.get(seq)
        if old is not None and old != rec:
            raise AppendError(
                f"replayed batch {seq} differs from the journaled append "
                f"(journal {old}, replay {rec}) — duplicate or divergent "
                "append rejected; replay the original session's batch "
                "stream in order"
            )
        self._batches[seq] = rec
        self._batch_seq += 1
        span_start = self._rows_seen
        self._rows_seen += len(X)
        skip = min(len(X), max(0, self._new_skip - span_start))
        super().append(X[skip:], Y[skip:])

    # ------------------------------------------------------------- close
    def close(self) -> Manifest:
        if self._closed:
            return self.manifest
        self._closed = True
        if self._pending_rows:
            self._flush_shard(self._pending_rows)
        session = self._shards[self._session_start:]
        jp = self._journal_path()
        if not session:
            # nothing appended (and no tail was adopted): the dataset is
            # already exactly its manifest
            self.manifest = self._base_manifest
            if os.path.exists(jp):
                os.remove(jp)
            return self.manifest
        # COMMIT, under the shared I/O retry (every step is idempotent:
        # a rename of an already-renamed stage is skipped, the manifest
        # write replaces like-for-like, the journal delete tolerates
        # absence). The injection points make the rename/manifest and
        # manifest/journal-delete transitions killable; a death anywhere
        # in here is recovered by the resume path (staged-or-final shard
        # verification + the already-committed detection).
        def _commit():
            faults.point("stream.append", commit=True)
            for info in session:
                staged = os.path.join(self.out_dir,
                                      info.filename + ".stage")
                if os.path.exists(staged):
                    fsync_replace(staged,
                                  os.path.join(self.out_dir, info.filename))
            manifest = Manifest(
                n_rows=self._row_start,
                n_features=int(self._n_features),
                shards=self._shards,
                binary=self.binary,
                positive_label=self.positive_label,
            )
            tmp = os.path.join(self.out_dir, MANIFEST_NAME + ".tmp")
            with open(tmp, "w") as f:
                json.dump(manifest.to_json(), f, indent=1)
                f.write("\n")
            fsync_replace(tmp, os.path.join(self.out_dir, MANIFEST_NAME))
            # manifest durable, journal not yet removed — a kill exactly
            # here is what the resume path's already-committed detection
            # recovers (idempotent re-close)
            faults.point("stream.append", committed=True)
            if os.path.exists(jp):
                os.remove(jp)
            return manifest

        self.manifest = self._append_retry(_commit)
        return self.manifest


def append_blocks(out_dir: str,
                  blocks,
                  rows_per_shard: Optional[int] = None,
                  resume: bool = False) -> Manifest:
    """Append an (X, Y)-block iterator to a committed dataset (the
    generic append core, mirroring `ingest_blocks`). On resume the
    SOURCE must replay the same blocks in the same order."""
    w = AppendWriter(out_dir, rows_per_shard=rows_per_shard, resume=resume)
    for X, Y in blocks:
        w.append(X, Y)
    return w.close()
