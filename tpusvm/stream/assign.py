"""Deterministic global row -> cascade-leaf assignment from a manifest.

data.partition materialises the reference's MPI scatter
(mpi_svm_main3.cpp:463-518) by slicing a monolithic in-memory array. This
module computes the SAME assignment — contiguous ceil(n/P) chunks, or the
stratified per-class round-robin deal — as a pure function of (row count,
labels, P), so each cascade leaf (or tune fold) can be filled by streaming
shards one at a time and scattering their rows to (leaf, slot) positions.
The resulting Partition is BIT-IDENTICAL to make_partition on the
concatenated array: same rows, same per-leaf order, same padding, same
global IDs — so the cascade's dedup-by-ID merges, its ID-set convergence
test, and the solved model are unchanged by where the bytes came from.

Labels for the stratified deal come from a Y-only manifest pass
(ShardedDataset.load_labels — 4 bytes/row of IO); X is only ever resident
one shard at a time.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Sequence

import numpy as np

from tpusvm.data.partition import Partition
from tpusvm.stream.format import ShardedDataset


class RowAssignment(NamedTuple):
    """Where every global row lands: leaf `part[i]`, padded slot `slot[i]`.

    cap is the padded per-leaf width (make_partition's cap for the same
    inputs); count[p] the realised rows of leaf p (trailing leaves can be
    short or empty under the contiguous scatter).
    """

    part: np.ndarray   # (n,) int32
    slot: np.ndarray   # (n,) int32
    count: np.ndarray  # (P,) int32
    cap: int


def assign_rows(n_rows: int, n_parts: int,
                Y: Optional[np.ndarray] = None,
                stratified: bool = False) -> RowAssignment:
    """Replicates data.partition's shard_rows as a row->(part, slot) map.

    Contiguous (default): row i -> part i // cap, slot i % cap with
    cap = ceil(n/P) — the reference's scatter; needs no labels.

    stratified=True: class ci's rows (original order) are dealt round-robin
    starting at part ci — row j of class ci -> part (ci + j) % P, slot =
    that part's running fill at deal time. Requires Y (one labels pass).
    """
    if n_parts < 1:
        raise ValueError(f"n_parts must be >= 1, got {n_parts}")
    if not stratified:
        cap = -(-n_rows // n_parts)  # ceil, as make_partition
        rows = np.arange(n_rows, dtype=np.int64)
        part = (rows // cap).astype(np.int32)
        slot = (rows % cap).astype(np.int32)
        count = np.zeros((n_parts,), np.int32)
        np.add.at(count, part, 1)
        return RowAssignment(part, slot, count, int(cap))

    if Y is None:
        raise ValueError("stratified assignment needs the labels Y")
    Y = np.asarray(Y)
    if len(Y) != n_rows:
        raise ValueError(f"len(Y)={len(Y)} != n_rows={n_rows}")
    part = np.zeros((n_rows,), np.int32)
    slot = np.zeros((n_rows,), np.int32)
    fill = np.zeros((n_parts,), np.int64)
    for ci, c in enumerate(np.unique(Y)):
        idx = np.flatnonzero(Y == c)
        j = np.arange(len(idx), dtype=np.int64)
        t = (ci + j) % n_parts
        # the k-th row of this class dealt to part p arrived at j = j0 + kP,
        # so j // P counts this class's earlier arrivals at the same part
        part[idx] = t.astype(np.int32)
        slot[idx] = (fill[t] + j // n_parts).astype(np.int32)
        np.add.at(fill, t, 1)
    count = fill.astype(np.int32)
    cap = max(1, int(count.max()))
    return RowAssignment(part, slot, count, cap)


def partition_from_dataset(dataset: ShardedDataset, n_parts: int,
                           stratified: bool = False, scaler=None,
                           prefetch_depth: int = 2) -> Partition:
    """Build the cascade's padded Partition by streaming shards.

    Bit-identical to data.partition(scaler.transform(X_full), Y_full,
    n_parts, stratified) without ever materialising X_full: the assignment
    is computed from the manifest (plus a Y-only pass when stratified),
    then each shard is loaded once — prefetched on a background thread —
    optionally scaled (pass the manifest-fitted scaler for the reference's
    global-min/max-before-scatter semantics), and scattered into its
    (leaf, slot) positions. Peak X residency: the (P, cap, d) partition
    buffer plus prefetch_depth + 1 shards.
    """
    from tpusvm.stream.reader import ShardReader

    n, d = dataset.n_rows, dataset.n_features
    Y_all = dataset.load_labels() if stratified else None
    asg = assign_rows(n, n_parts, Y=Y_all, stratified=stratified)

    Xp = np.zeros((n_parts, asg.cap, d), np.float64)
    Yp = np.zeros((n_parts, asg.cap), np.int32)
    ids = np.full((n_parts, asg.cap), -1, np.int32)
    valid = np.zeros((n_parts, asg.cap), bool)

    reader = ShardReader(dataset, prefetch_depth=prefetch_depth,
                         scaler=scaler)
    row = 0
    for X, Y in reader:
        g = np.arange(row, row + len(X))
        p, s = asg.part[g], asg.slot[g]
        Xp[p, s] = X
        Yp[p, s] = Y
        ids[p, s] = g.astype(np.int32)
        valid[p, s] = True
        row += len(X)
    if row != n:
        raise ValueError(
            f"dataset yielded {row} rows, manifest says {n} (corrupt shard?)"
        )
    return Partition(Xp, Yp, ids, valid, asg.count)


def gather_rows(dataset: ShardedDataset,
                indices: Sequence[int]) -> np.ndarray:
    """X rows at the given global indices, in the given ORDER, loading only
    the shards that contain them (one at a time).

    The tune-fold primitive: a fold's shuffled train_idx / sorted val_idx
    gather into exactly the arrays the in-memory path would have sliced,
    with peak memory = output + one shard.
    """
    indices = np.asarray(indices, np.int64)
    if indices.size and (indices.min() < 0
                         or indices.max() >= dataset.n_rows):
        raise IndexError(
            f"row indices out of range [0, {dataset.n_rows})"
        )
    out = np.empty((len(indices), dataset.n_features), np.float64)
    for i, info in enumerate(dataset.manifest.shards):
        a, b = info.row_start, info.row_start + info.n_rows
        sel = np.flatnonzero((indices >= a) & (indices < b))
        if not sel.size:
            continue  # this shard's bytes are never read
        X, _ = dataset.load_shard(i)
        out[sel] = X[indices[sel] - a]
    return out
