"""One retry/backoff primitive for every I/O-shaped call in the stack.

Before this module each subsystem's failure handling was "re-raise and
hope": a flaky shard read killed a training run, a transient scoring
hiccup failed a whole serve batch. ``Retry(policy)`` is the single
primitive they all adopt — exponential backoff with seeded jitter,
a max-attempts budget, and per-CLASS retryability (an injected
TransientIOError or a real OSError is worth retrying; a checksum
mismatch is deterministic and is not).

Determinism: jitter comes from ``np.random.default_rng(seed)`` owned by
the Retry instance, so a chaos test's sleep schedule — like its fault
schedule — is reproducible. :class:`SimulatedKill` (BaseException) is
never caught: a killed process does not get to retry.

Exhaustion is loud and specific: :class:`RetryExhaustedError` carries
the operation name, attempt count and the last error (chained), and the
adopters map it to their own status vocabulary — the stream reader to
``StreamStatus.READ_FAILED``, serve's worker to a failed batch the
circuit breaker counts.

Every attempt/recovery/exhaustion lands in the obs default registry
(``retry.attempts`` / ``retry.recovered`` / ``retry.exhausted``,
labelled by op) and as ``retry.*`` events through faults.emit.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Optional, Tuple, Type

import numpy as np

from tpusvm.faults.injection import TransientIOError, emit


class RetryExhaustedError(RuntimeError):
    """All attempts failed; `last` is the final exception (also chained)."""

    def __init__(self, op: str, attempts: int, last: BaseException):
        self.op = op
        self.attempts = attempts
        self.last = last
        super().__init__(
            f"{op}: retry budget exhausted after {attempts} attempts "
            f"(last error: {type(last).__name__}: {last})"
        )


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Backoff shape + retryability classification.

    Delay before attempt k (k >= 2) is
    ``min(max_delay_s, base_delay_s * multiplier**(k-2))`` scaled by a
    uniform jitter in [1-jitter, 1+jitter]. Defaults are sized for local
    file I/O — milliseconds, not the seconds a remote store would want.
    """

    max_attempts: int = 4
    base_delay_s: float = 0.005
    max_delay_s: float = 0.25
    multiplier: float = 2.0
    jitter: float = 0.5
    retryable: Tuple[Type[BaseException], ...] = (TransientIOError,)
    seed: int = 0

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError(
                f"max_attempts must be >= 1, got {self.max_attempts}"
            )
        if not (0.0 <= self.jitter < 1.0):
            raise ValueError(f"jitter must be in [0, 1), got {self.jitter}")

    def delay_for(self, attempt: int, rng) -> float:
        """Sleep before attempt `attempt` (2-based; attempt 1 never waits)."""
        raw = min(self.max_delay_s,
                  self.base_delay_s * self.multiplier ** (attempt - 2))
        if self.jitter:
            raw *= 1.0 + self.jitter * (2.0 * float(rng.random()) - 1.0)
        return raw


#: Default policy for shard/manifest I/O (stream reads, ingest writes,
#: solver-checkpoint writes): retry injected transients AND real OSErrors
#: except a missing file, which no amount of waiting conjures back.
DEFAULT_IO_POLICY = RetryPolicy(
    retryable=(TransientIOError, InterruptedError, BlockingIOError,
               TimeoutError),
)


class Retry:
    """Callable retry executor: ``Retry(policy, op="x")(fn, *args)``.

    One instance per call site (it owns the jitter RNG and the op label);
    thread-safe only in the sense that concurrent calls share the RNG —
    adopters that care (the stream reader's single producer thread, the
    batcher's single worker) are single-threaded at the call site anyway.
    """

    def __init__(self, policy: RetryPolicy = RetryPolicy(), op: str = "op",
                 metrics=None, sleep: Callable[[float], None] = time.sleep,
                 on_retry: Optional[Callable[[], None]] = None):
        if metrics is None:
            from tpusvm.obs.registry import default_registry

            metrics = default_registry()
        self.policy = policy
        self.op = op
        self.sleep = sleep
        self.on_retry = on_retry
        self._rng = np.random.default_rng(policy.seed)
        self._attempts = metrics.counter("retry.attempts", op=op)
        self._recovered = metrics.counter("retry.recovered", op=op)
        self._exhausted = metrics.counter("retry.exhausted", op=op)

    def __call__(self, fn: Callable, *args, **kwargs):
        p = self.policy
        last: Optional[BaseException] = None
        for attempt in range(1, p.max_attempts + 1):
            if attempt > 1:
                if self.on_retry is not None:
                    self.on_retry()
                self.sleep(p.delay_for(attempt, self._rng))
            self._attempts.inc()
            try:
                out = fn(*args, **kwargs)
            except p.retryable as e:
                last = e
                emit("retry.failed_attempt", op=self.op, attempt=attempt,
                     error=f"{type(e).__name__}: {e}")
                continue
            # any non-retryable exception (and SimulatedKill, which as a
            # BaseException never matches `retryable`) propagates here
            if attempt > 1:
                self._recovered.inc()
                emit("retry.recovered", op=self.op, attempts=attempt)
            return out
        self._exhausted.inc()
        emit("retry.exhausted", op=self.op, attempts=p.max_attempts,
             error=f"{type(last).__name__}: {last}")
        raise RetryExhaustedError(self.op, p.max_attempts, last) from last
