"""tpusvm.faults — deterministic fault injection + the hardening it forces.

Four pieces (ISSUE 7):

  injection.py  named injection points at real call sites + a seeded,
                JSON-configured FaultPlan (``--faults plan.json`` /
                ``TPUSVM_FAULTS``) that raises transients, injects
                latency, corrupts bytes, or simulates kills — every
                chaos run reproducible.
  retry.py      the one Retry(policy) primitive (exponential backoff,
                seeded jitter, per-class retryability) adopted by shard
                reads, ingest writes, checkpoint writes and serve's
                scoring path.
  breaker.py    the per-model circuit breaker behind degraded-mode
                serving (trip on consecutive failures, half-open probe
                recovery).
  (solver/checkpoint.py holds the crash-safe-training side: periodic
  bit-exact solver checkpoints this package's kills are aimed at.)

``python -m tpusvm.faults kill-resume-smoke`` is the CI chaos gate for
crash-safe training: kill at a checkpoint, resume, assert the model is
bit-identical to an uninterrupted run.
"""

from tpusvm.faults.breaker import BreakerOpenError, CircuitBreaker
from tpusvm.faults.injection import (
    KINDS,
    PLAN_FORMAT_VERSION,
    POINTS,
    FaultError,
    FaultPlan,
    FaultRule,
    SimulatedKill,
    TransientIOError,
    activate,
    active,
    active_plan,
    deactivate,
    emit,
    load_plan,
    point,
    set_event_sink,
)
from tpusvm.faults.retry import (
    DEFAULT_IO_POLICY,
    Retry,
    RetryExhaustedError,
    RetryPolicy,
)

__all__ = [
    "BreakerOpenError",
    "CircuitBreaker",
    "DEFAULT_IO_POLICY",
    "FaultError",
    "FaultPlan",
    "FaultRule",
    "KINDS",
    "PLAN_FORMAT_VERSION",
    "POINTS",
    "Retry",
    "RetryExhaustedError",
    "RetryPolicy",
    "SimulatedKill",
    "TransientIOError",
    "activate",
    "active",
    "active_plan",
    "deactivate",
    "emit",
    "load_plan",
    "point",
    "set_event_sink",
]
