"""Deterministic, seeded fault injection at named points.

The production story (ROADMAP: pod-scale training, traffic-scale serving)
needs failure paths that are TESTED, not paths that merely re-raise. This
module is the test harness for them: real call sites invoke
``faults.point("stream.read_shard")`` on their hot I/O and scoring paths,
and a seeded :class:`FaultPlan` — loaded from JSON, activated by
``--faults plan.json`` or the ``TPUSVM_FAULTS`` env var — decides per hit
whether to raise a :class:`TransientIOError`, inject latency, corrupt a
byte payload, or simulate a process kill. With no plan active a point is
a single ``is None`` check, so production code pays nothing.

Determinism is the whole design: every rule draws from its own
``np.random.default_rng(seed ^ crc32(point))`` stream and counts hits
under a lock, so the same plan against the same workload fires the same
faults in the same order on every platform — a chaos test is an ordinary
reproducible test.

Registered points (the canonical list; a plan naming anything else is
rejected at load time):

  ``stream.read_shard``       ShardedDataset.load_shard (stream/format.py)
  ``ingest.write_shard``      ShardWriter's atomic shard write; carries
                              the npz byte payload, so ``corrupt`` rules
                              apply here (stream/format.py)
  ``serve.score``             _ModelWorker's batched scoring path
                              (serve/server.py)
  ``serve.swap``              the hot-swap STAGE path — load, compile,
                              probe-verify happen behind this point, so
                              kill/latency rules die or stall a swap
                              mid-stage while the old generation keeps
                              serving (serve/server.py)
  ``registry.load``           the model-artifact read feeding a load or
                              a staged swap; carries the raw .npz byte
                              payload, so ``corrupt`` rules apply here
                              (serve/registry.py)
  ``cache.read``              the persistent-compile-cache manifest
                              read at serve startup (serve/cache.py)
  ``cascade.round``           the host-side cascade round loop
                              (parallel/cascade.py)
  ``solver.outer_checkpoint`` the solver-state checkpoint write
                              (solver/checkpoint.py)
  ``stream.append``           the append-session journal write and the
                              close() commit transition — kills here
                              exercise the exactly-once tail-append
                              resume (stream/append.py)
  ``autopilot.tick``          the supervisor's per-tick entry
                              (autopilot/loop.py)
  ``autopilot.refresh``       the supervisor's refresh stage — fit,
                              save, swap happen behind this point
                              (autopilot/loop.py)
  ``stream.journal``          the fresh-ingest v1 journal write and the
                              close() manifest/journal commit
                              transitions (stream/format.py)
  ``models.save``             the model-artifact atomic save
                              (models/serialization.py)
  ``serve.state_write``       the serve-registry manifest and compile-
                              cache manifest commits (serve/cache.py)
  ``autopilot.state``         the supervisor's CRC-fingerprinted state
                              commit (autopilot/state.py)
  ``cascade.checkpoint``      the cascade inter-round checkpoint write
                              (parallel/cascade.py)
  ``router.forward``          the routing tier's per-replica forward
                              attempt — transient/latency rules here
                              exercise failover to the next placement
                              under client load (router/proxy.py)
  ``tenants.tick``            the multi-tenant supervisor's per-tick
                              entry (tenants/loop.py)
  ``tenants.store``           the tenant registry's CRC-fingerprinted
                              state commit AND the coalesced fleet
                              refresh's segment-checkpoint write — kill
                              rules here die mid-fleet-refresh with the
                              previous durable state intact
                              (tenants/store.py)
  ``pod.round``               the pod coordinator's per-round entry —
                              kills here die between rounds, and resume
                              must reproduce the uninterrupted cascade
                              (pod/coordinator.py)
  ``pod.merge``               the pod coordinator's durable round-state
                              commit (fsync_replace) — kills here leave
                              the previous complete checkpoint or none
                              (pod/state.py)
  ``pod.worker``              a pod worker's per-request entry — kill
                              rules here die mid-round on the WORKER
                              side (the worker escalates SimulatedKill
                              to a real SIGKILL on itself), and the
                              coordinator must revive it and finish the
                              round bit-identically (pod/worker.py)

Kill semantics: :class:`SimulatedKill` subclasses ``BaseException`` (like
``KeyboardInterrupt``), so no ``except Exception`` recovery path — not
even the retry machinery this package ships — can swallow it. Whatever
survives a SimulatedKill escaping to the process boundary is exactly
what survives a real SIGKILL: bytes already durable on disk.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
import zlib
from typing import Callable, Dict, List, Optional

import numpy as np

PLAN_FORMAT_VERSION = 1

#: The canonical injection-point registry. Call sites use these literal
#: names; plan validation rejects typos against this set.
POINTS = frozenset({
    "stream.read_shard",
    "ingest.write_shard",
    "serve.score",
    "serve.swap",
    "registry.load",
    "cache.read",
    "cascade.round",
    "solver.outer_checkpoint",
    "stream.append",
    "autopilot.tick",
    "autopilot.refresh",
    "stream.journal",
    "models.save",
    "serve.state_write",
    "autopilot.state",
    "cascade.checkpoint",
    "router.forward",
    "tenants.tick",
    "tenants.store",
    "pod.round",
    "pod.merge",
    "pod.worker",
})

KINDS = ("transient", "latency", "corrupt", "kill")


class FaultError(Exception):
    """Base class for injected (recoverable) faults."""


class TransientIOError(FaultError, OSError):
    """An injected transient I/O failure — the retryable fault class.

    Subclasses OSError so call sites that already classify OSErrors as
    retryable treat the injected fault exactly like a real flaky disk."""


class SimulatedKill(BaseException):
    """Injected process death. BaseException on purpose: retry loops and
    ``except Exception`` recovery must NOT catch it — only state already
    durable on disk survives, which is precisely what a chaos test wants
    to measure."""


@dataclasses.dataclass
class FaultRule:
    """One plan entry: WHAT fires at WHICH point, and how often.

    p:        per-hit fire probability (seeded; 1.0 = every hit).
    max_hits: total fires allowed (None = unbounded) — a transient rule
              with max_hits=2 fails a retried operation twice and then
              lets the third attempt through, the retry-to-success shape.
    at_hit:   fire EXACTLY on the Nth hit of the point (1-based),
              ignoring p — the deterministic "kill at the k-th
              checkpoint" primitive.
    delay_ms: sleep duration for kind="latency".
    """

    point: str
    kind: str
    p: float = 1.0
    max_hits: Optional[int] = None
    at_hit: Optional[int] = None
    delay_ms: float = 1.0
    # runtime state (not part of the JSON surface)
    fires: int = dataclasses.field(default=0, compare=False)

    def validate(self) -> None:
        if self.point not in POINTS:
            raise ValueError(
                f"fault plan names unknown injection point {self.point!r}; "
                f"registered points: {sorted(POINTS)}"
            )
        if self.kind not in KINDS:
            raise ValueError(
                f"fault rule for {self.point!r} has unknown kind "
                f"{self.kind!r}; kinds: {KINDS}"
            )
        if not (0.0 <= self.p <= 1.0):
            raise ValueError(f"fault rule p must be in [0, 1], got {self.p}")
        if self.max_hits is not None and self.max_hits < 1:
            raise ValueError(f"max_hits must be >= 1, got {self.max_hits}")
        if self.at_hit is not None and self.at_hit < 1:
            raise ValueError(f"at_hit must be >= 1, got {self.at_hit}")


class FaultPlan:
    """A seeded, deterministic set of fault rules.

    Thread-safe: hit counts and each rule's RNG stream are guarded by one
    lock (injection sits on I/O paths where a lock is noise)."""

    def __init__(self, rules: List[FaultRule], seed: int = 0,
                 source: str = "<inline>"):
        for r in rules:
            r.validate()
        self.rules = rules
        self.seed = int(seed)
        self.source = source
        self._lock = threading.Lock()
        self._hits: Dict[str, int] = {}
        # one independent, platform-stable stream per rule: seed mixed
        # with a CRC of the point name and the rule's index, so adding a
        # rule never perturbs another rule's draw sequence
        self._rngs = [
            np.random.default_rng(
                (self.seed ^ zlib.crc32(f"{i}:{r.point}".encode()))
                & 0xFFFFFFFF
            )
            for i, r in enumerate(rules)
        ]

    @classmethod
    def from_json(cls, obj: dict, source: str = "<inline>") -> "FaultPlan":
        if not isinstance(obj, dict) or "format_version" not in obj:
            raise ValueError(
                "not a tpusvm fault plan (no format_version key)"
            )
        v = obj["format_version"]
        if v != PLAN_FORMAT_VERSION:
            raise ValueError(
                f"unsupported fault plan format_version {v!r} (this build "
                f"reads version {PLAN_FORMAT_VERSION})"
            )
        known = {"point", "kind", "p", "max_hits", "at_hit", "delay_ms"}
        rules = []
        for i, r in enumerate(obj.get("rules", [])):
            bad = set(r) - known
            if bad:
                raise ValueError(
                    f"fault plan rule {i} has unknown keys {sorted(bad)}; "
                    f"known: {sorted(known)}"
                )
            if "point" not in r or "kind" not in r:
                raise ValueError(
                    f"fault plan rule {i} needs 'point' and 'kind'"
                )
            rules.append(FaultRule(
                point=str(r["point"]),
                kind=str(r["kind"]),
                p=float(r.get("p", 1.0)),
                max_hits=(None if r.get("max_hits") is None
                          else int(r["max_hits"])),
                at_hit=(None if r.get("at_hit") is None
                        else int(r["at_hit"])),
                delay_ms=float(r.get("delay_ms", 1.0)),
            ))
        return cls(rules, seed=int(obj.get("seed", 0)), source=source)

    def hits(self, point: str) -> int:
        with self._lock:
            return self._hits.get(point, 0)

    def _decide(self, point: str):
        """(hit_number, [rules that fire this hit]) under the lock."""
        with self._lock:
            n = self._hits.get(point, 0) + 1
            self._hits[point] = n
            firing = []
            for rule, rng in zip(self.rules, self._rngs):
                if rule.point != point:
                    continue
                if rule.at_hit is not None:
                    fire = n == rule.at_hit
                else:
                    if rule.max_hits is not None \
                            and rule.fires >= rule.max_hits:
                        continue
                    fire = rule.p >= 1.0 or rng.random() < rule.p
                if fire:
                    rule.fires += 1
                    firing.append((rule, rng))
            return n, firing


def load_plan(path: str) -> FaultPlan:
    """Read + validate a JSON fault plan file."""
    with open(path) as f:
        try:
            obj = json.load(f)
        except json.JSONDecodeError as e:
            raise ValueError(f"fault plan {path!r} is not valid JSON: {e}")
    return FaultPlan.from_json(obj, source=path)


# ------------------------------------------------------------- activation
_active: Optional[FaultPlan] = None
_sink: Optional[Callable] = None


def activate(plan: FaultPlan) -> FaultPlan:
    """Install a plan process-wide (CLI --faults / TPUSVM_FAULTS)."""
    global _active
    _active = plan
    return plan


def deactivate() -> None:
    global _active
    _active = None


def active_plan() -> Optional[FaultPlan]:
    return _active


class active:
    """Context manager: activate a plan for a with-block (tests)."""

    def __init__(self, plan: FaultPlan):
        self.plan = plan

    def __enter__(self) -> FaultPlan:
        return activate(self.plan)

    def __exit__(self, *exc) -> None:
        deactivate()


def set_event_sink(fn: Optional[Callable]) -> None:
    """Route fault/retry/breaker events somewhere (the CLI passes
    ``tracer.event`` when --trace is on); None = drop them. Counters in
    the obs default registry are emitted regardless of the sink."""
    global _sink
    _sink = fn


def emit(name: str, **attrs) -> None:
    """Emit one fault-lifecycle event to the installed sink (if any)."""
    if _sink is not None:
        _sink(name, **attrs)


def _counter(name: str, **labels):
    from tpusvm.obs.registry import default_registry

    return default_registry().counter(name, **labels)


def point(name: str, payload: Optional[bytes] = None, **attrs):
    """An injection point. Returns `payload` (possibly corrupted).

    With no active plan this is a single global read. With a plan, the
    hit is counted and every matching rule that fires is applied in rule
    order:

      transient -> raise TransientIOError (retryable)
      latency   -> time.sleep(delay_ms)
      corrupt   -> flip one payload byte at a seeded offset (requires a
                   bytes payload; a corrupt rule firing on a payload-less
                   point is a plan bug and raises ValueError)
      kill      -> raise SimulatedKill (BaseException — uncatchable by
                   retry/except-Exception paths)
    """
    plan = _active
    if plan is None:
        return payload
    if name not in POINTS:
        raise ValueError(f"unregistered injection point {name!r}")
    hit, firing = plan._decide(name)
    for rule, rng in firing:
        _counter("faults.injected", point=name, kind=rule.kind).inc()
        emit("fault.injected", point=name, kind=rule.kind, hit=hit,
             **attrs)
        if rule.kind == "transient":
            raise TransientIOError(
                f"injected transient fault at {name} (hit {hit}, "
                f"plan {plan.source})"
            )
        if rule.kind == "latency":
            time.sleep(rule.delay_ms / 1e3)
        elif rule.kind == "corrupt":
            if payload is None:
                raise ValueError(
                    f"corrupt rule fired at {name!r}, which carries no "
                    "byte payload to corrupt (corrupt applies to "
                    "ingest.write_shard and registry.load)"
                )
            buf = bytearray(payload)
            # seeded offset keeps the corruption reproducible; skip the
            # first 64 bytes so the zip header stays parseable and the
            # damage lands in DATA (the checksum's job to catch)
            lo = min(64, len(buf) - 1)
            idx = int(rng.integers(lo, len(buf)))
            buf[idx] ^= 0xFF
            payload = bytes(buf)
        elif rule.kind == "kill":
            raise SimulatedKill(
                f"injected process kill at {name} (hit {hit}, "
                f"plan {plan.source})"
            )
    return payload
