"""Chaos CLI: ``python -m tpusvm.faults <command>``.

Commands:

  kill-resume-smoke   The crash-safe-training CI gate. Trains a tiny
                      deterministic problem three ways — uninterrupted
                      plain solve, checkpointed solve, and checkpointed
                      solves KILLED at every checkpoint in turn and then
                      resumed — and asserts every variant produces
                      bit-identical model state (alpha bytes, SV ids, b).
                      Also proves transient checkpoint-write faults are
                      retried to success. Non-zero exit on any failure.
  validate PLAN.json  Parse + validate a fault plan (rule points/kinds
                      checked against the registry); prints the rules.
  swap-chaos-smoke    The resilient-serving CI gate: client threads
                      stream requests while hot-swaps flip between two
                      models with distinct outputs, under a fault plan
                      that kills a swap mid-stage, corrupts a staged
                      artifact's bytes, and injects compile/stage
                      latency. Asserts: every scored response bitwise-
                      matches exactly one of the two models (no torn
                      generation), zero requests lost or errored beyond
                      the injected causes, every failed stage rolled
                      back to a serving old generation with /healthz
                      degraded, and a subsequent clean swap recovers.
  router-chaos-smoke  The routing-tier CI gate: N REAL `tpusvm serve`
                      replica PROCESSES (spawned on ephemeral ports,
                      discovered through serve_state.json) behind an
                      in-process Router front door, under multi-threaded
                      client load — while replicas are SIGKILLed and
                      revived on their recorded ports (keeping their
                      persisted replica identity) and router.forward
                      faults inject transients/latency into the fabric
                      itself. Asserts: zero lost responses (every client
                      request ends 200 with a score bitwise-equal to one
                      of the two live generations; 429 backpressure is
                      retried, nothing else tolerated), a staggered
                      rollout completes skew-free to a uniform
                      generation vector, placement tables are
                      byte-identical per seed, revived replicas keep
                      their replica_id, and the injected faults fired.
  autopilot-chaos-smoke
                      The closed-loop online-learning CI gate (kill at
                      EVERY stage): while client threads stream
                      requests, micro-batches append to the dataset and
                      the autopilot supervisor retrains/swaps — under a
                      seeded plan that kills the append journal/commit,
                      kills the refresh stage and the solver
                      checkpoint, corrupts a staged swap artifact, and
                      delays scoring/ticks. Every kill is "recovered"
                      by rebuilding the writer/supervisor with
                      resume=True, exactly as a restarted process
                      would. Asserts: the final dataset is
                      row-for-row, manifest-byte identical to an
                      uninterrupted control (zero rows lost or
                      duplicated — the journal audit), every served
                      response bitwise-matches a complete generation,
                      the corrupt staged swap rolled back with healthz
                      degraded, and the post-recovery refreshed model
                      is BIT-IDENTICAL (alpha bytes / SV ids / b) to
                      the uninterrupted control run's.
  pod-chaos-smoke     The pod-cascade CI gate (tpusvm.pod): an
                      out-of-core multiprocess cascade trains from a
                      sharded dataset three ways — an uninterrupted
                      control, a run whose worker 1 REALLY SIGKILLs
                      itself mid-round (revived by the coordinator, the
                      round re-run from coordinator-held state), and a
                      run whose COORDINATOR is killed entering round 2
                      then resumed from its fsync'd per-round
                      checkpoint. Asserts: both recovery arms are
                      BIT-IDENTICAL to the control (SV-ID set, alpha
                      bytes, b), the worker kill actually fired (>= 1
                      revive) and the coordinator kill left a durable
                      checkpoint behind, no stale pre-kill reply leaks
                      into the re-run round, and every dataset row is
                      accounted for across the workers in every arm.
  tenant-chaos-smoke  The multi-tenant platform CI gate: 64 tenants
                      (one shared corpus, per-tenant label/row-subset
                      views) provisioned in ONE cold fleet launch and
                      served; the coalescing supervisor is SIGKILLed
                      mid-fleet-refresh at a segment-checkpoint write
                      and rebuilt with resume=True, while client
                      threads stream per-tenant requests. Asserts:
                      a durable fleet checkpoint existed at the kill
                      and the recovered refit is BIT-IDENTICAL (alpha
                      bytes / SV ids / b / n_iter) per tenant to an
                      uninterrupted control arm, every served response
                      bitwise-matches one of that tenant's two
                      complete generations, every tenant's dataset
                      view fingerprint equals the control's (no rows
                      lost), fleet checkpoints are reaped after the
                      swapping-stage commit, and a corrupted swap
                      artifact pins exactly one tenant on its previous
                      generation (serving bitwise) before a solo
                      recovery refresh lands.
"""

from __future__ import annotations

import sys
import tempfile


def _kill_resume_smoke() -> int:
    import os

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp
    import numpy as np

    from tpusvm import faults
    from tpusvm.data import MinMaxScaler, rings
    from tpusvm.oracle.smo import get_sv_indices
    from tpusvm.solver.blocked import blocked_smo_solve
    from tpusvm.solver.checkpoint import checkpointed_blocked_solve
    from tpusvm.status import Status

    EVERY = 4
    X, Y = rings(n=400, seed=11)
    Xs = jnp.asarray(MinMaxScaler().fit_transform(X), jnp.float32)
    Yd = jnp.asarray(Y)
    kw = dict(C=10.0, gamma=10.0, q=16, accum_dtype=jnp.float64)

    plain = blocked_smo_solve(Xs, Yd, **kw)
    if Status(int(plain.status)) != Status.CONVERGED:
        print(f"KILL-RESUME SMOKE FAILED: reference solve ended "
              f"{Status(int(plain.status)).name}")
        return 1
    ref_alpha = np.asarray(plain.alpha)
    ref_sv = get_sv_indices(ref_alpha, 1e-8)
    n_ckpts = max(1, int(plain.n_outer) // EVERY)
    failures = []

    def run(ck, resume=False):
        return checkpointed_blocked_solve(
            Xs, Yd, checkpoint_path=ck, checkpoint_every=EVERY,
            resume=resume, **kw,
        )

    def identical(res):
        a = np.asarray(res.alpha)
        return (a.tobytes() == ref_alpha.tobytes()
                and np.array_equal(get_sv_indices(a, 1e-8), ref_sv)
                and float(res.b) == float(plain.b))

    with tempfile.TemporaryDirectory() as td:
        # 1. checkpointed-but-never-killed == plain, bit for bit
        ck = os.path.join(td, "ck.npz")
        if not identical(run(ck)):
            failures.append("uninterrupted checkpointed solve diverged "
                            "from the plain solve")

        # 2. kill at EVERY checkpoint, resume, still bit-identical
        for k in range(1, n_ckpts + 1):
            ckk = os.path.join(td, f"ck{k}.npz")
            plan = faults.FaultPlan(
                [faults.FaultRule(point="solver.outer_checkpoint",
                                  kind="kill", at_hit=k)], seed=0)
            died = False
            try:
                with faults.active(plan):
                    run(ckk)
            except faults.SimulatedKill:
                died = True
            if not died:
                failures.append(f"kill rule at checkpoint {k} never fired")
                continue
            if not identical(run(ckk, resume=True)):
                failures.append(
                    f"resume after kill at checkpoint {k} is not "
                    "bit-identical")

        # 3. transient write faults are retried to success
        ckt = os.path.join(td, "ckt.npz")
        plan = faults.FaultPlan(
            [faults.FaultRule(point="solver.outer_checkpoint",
                              kind="transient", max_hits=2)], seed=0)
        with faults.active(plan):
            if not identical(run(ckt)):
                failures.append("solve under transient checkpoint-write "
                                "faults diverged")

    if failures:
        for f in failures:
            print(f"KILL-RESUME SMOKE FAILED: {f}")
        return 1
    print(f"kill-resume smoke ok: {n_ckpts} kill points, "
          f"{int(plain.n_outer)} outer rounds, {len(ref_sv)} SVs — every "
          "resumed solve bit-identical to the uninterrupted run")
    return 0


def _pod_chaos_smoke() -> int:
    import json
    import os

    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from tpusvm import faults
    from tpusvm.config import CascadeConfig, SVMConfig
    from tpusvm.data.synthetic import rings
    from tpusvm.pod import pod_fit
    from tpusvm.stream.format import ingest_arrays

    import warnings

    warnings.filterwarnings("ignore", category=UserWarning)

    X, Y = rings(n=192, seed=3)
    cfg = SVMConfig(C=10.0, gamma=10.0, max_rounds=12)
    cc = CascadeConfig(n_shards=4, sv_capacity=128, topology="tree")
    failures = []
    with tempfile.TemporaryDirectory() as td:
        ds = os.path.join(td, "ds")
        ingest_arrays(ds, X, Y, rows_per_shard=24)

        ctrl = pod_fit(ds, cfg, cc)
        ctrl_ids = set(np.asarray(ctrl.sv_ids).tolist())
        ctrl_alpha = np.asarray(ctrl.sv_alpha).tobytes()
        if not ctrl.converged:
            print("POD CHAOS SMOKE FAILED: control run did not converge")
            return 1

        def check(arm, res):
            if set(np.asarray(res.sv_ids).tolist()) != ctrl_ids:
                failures.append(f"{arm}: SV-ID set diverges from control")
            elif np.asarray(res.sv_alpha).tobytes() != ctrl_alpha:
                failures.append(f"{arm}: alpha bytes diverge from control")
            if res.b != ctrl.b:
                failures.append(f"{arm}: b diverges "
                                f"({res.b!r} vs {ctrl.b!r})")
            if sum(res.worker_rows) != len(Y):
                failures.append(f"{arm}: rows lost — workers hold "
                                f"{sum(res.worker_rows)} of {len(Y)}")

        # arm 1: worker 1 REALLY SIGKILLs itself on its 2nd request
        # (mid round 2, after its round-1 result already merged); the
        # coordinator revives it and re-runs the round from its own
        # held state — any stale pre-kill reply a surviving worker
        # queued must be discarded, or alpha bytes diverge here
        plan = os.path.join(td, "plan.json")
        tmp = plan + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"format_version": 1, "seed": 0, "rules": [
                {"point": "pod.worker", "kind": "kill", "at_hit": 2}]}, f)
        os.replace(tmp, plan)
        r1 = pod_fit(ds, cfg, cc, worker_faults={1: plan})
        if r1.revives < 1:
            failures.append("worker-kill arm: the kill never fired "
                            "(zero revives)")
        check("worker-kill arm", r1)

        # arm 2: the COORDINATOR dies entering round 2, leaving the
        # round-1 checkpoint (fsync_replace'd) behind; a fresh
        # coordinator resumes from it — workers reload their leaves
        # from the manifest and the merged trajectory replays
        ck = os.path.join(td, "ck.npz")
        killed = False
        try:
            with faults.active(faults.FaultPlan(
                    [faults.FaultRule(point="pod.round", kind="kill",
                                      at_hit=2)])):
                pod_fit(ds, cfg, cc, checkpoint_path=ck)
        except faults.SimulatedKill:
            killed = True
        if not killed:
            failures.append("coordinator-kill arm: the kill never fired")
        elif not os.path.exists(ck):
            failures.append("coordinator-kill arm: no durable checkpoint "
                            "at the kill")
        else:
            r2 = pod_fit(ds, cfg, cc, checkpoint_path=ck, resume=True)
            check("coordinator-resume arm", r2)

        # arm 3: the worker-kill arm again, TRACED — chaos must not
        # break the distributed trace fabric. The killed worker's file
        # truncates at its last request (SIGKILL leaves no torn span
        # line), the REVIVED worker opens a fresh per-pid file whose
        # root spans re-parent under the coordinator via the propagated
        # context, and the fit stays bit-identical to control
        from tpusvm.obs import Tracer
        from tpusvm.obs.report import merge_trace_files, render_report, \
            reparent_stats

        tdir = os.path.join(td, "trace")
        os.makedirs(tdir)
        tracer = Tracer(os.path.join(tdir, "coordinator.jsonl"),
                        role="pod-coordinator", argv=["pod-chaos"])
        faults.set_event_sink(tracer.event)
        try:
            r3 = pod_fit(ds, cfg, cc, worker_faults={1: plan},
                         tracer=tracer, trace_dir=tdir)
        finally:
            faults.set_event_sink(None)
            tracer.close()
        if r3.revives < 1:
            failures.append("traced arm: the kill never fired "
                            "(zero revives)")
        check("traced arm", r3)
        tfiles = sorted(
            os.path.join(tdir, f) for f in os.listdir(tdir)
            if f.endswith(".jsonl"))
        # 1 coordinator + 4 workers + >=1 revived worker (fresh pid,
        # fresh file): the kill must be VISIBLE in the file census
        if len(tfiles) < 6:
            failures.append(
                f"traced arm: expected >=6 trace files (coordinator + "
                f"4 workers + revived worker), found {len(tfiles)}")
        try:
            recs = merge_trace_files(tfiles)
            stats = reparent_stats(recs)
            if "pod-worker" not in stats["roles"]:
                failures.append("traced arm: no pod-worker spans in "
                                "the merged timeline")
            if stats["unresolved"]:
                failures.append(
                    f"traced arm: {stats['unresolved']} root span(s) "
                    "failed to re-parent (revived worker's context "
                    "broken?)")
            if not stats["reparented"]:
                failures.append("traced arm: zero spans re-parented "
                                "across processes")
            render_report(recs)  # the merged timeline must render
        except (ValueError, KeyError) as e:
            failures.append(f"traced arm: merged trace unusable: {e}")

    if failures:
        for f in failures:
            print(f"POD CHAOS SMOKE FAILED: {f}")
        return 1
    print(f"pod chaos smoke ok: {ctrl.rounds} rounds, "
          f"{len(ctrl_ids)} SVs, worker SIGKILL revived "
          f"({r1.revives} revive) and coordinator kill resumed — both "
          "bit-identical to the uninterrupted control, zero rows lost; "
          f"traced re-run stitched {stats['files']} files "
          f"({stats['reparented']} spans re-parented, 0 unresolved) "
          "while staying bit-identical")
    return 0


def _validate(path: str) -> int:
    from tpusvm import faults

    plan = faults.load_plan(path)
    print(f"fault plan ok: {path} (seed {plan.seed}, "
          f"{len(plan.rules)} rules)")
    for r in plan.rules:
        extra = ""
        if r.at_hit is not None:
            extra = f" at_hit={r.at_hit}"
        elif r.max_hits is not None:
            extra = f" p={r.p:g} max_hits={r.max_hits}"
        else:
            extra = f" p={r.p:g}"
        if r.kind == "latency":
            extra += f" delay_ms={r.delay_ms:g}"
        print(f"  {r.point}: {r.kind}{extra}")
    return 0


def _swap_chaos_smoke() -> int:
    import os
    import threading

    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from tpusvm import faults
    from tpusvm.config import SVMConfig
    from tpusvm.data import rings
    from tpusvm.models import BinarySVC
    from tpusvm.serve import ModelLoadError, ServeConfig, Server

    failures = []
    Xa, Ya = rings(n=240, seed=2)
    Xb, Yb = rings(n=240, seed=9)
    A = BinarySVC(SVMConfig(C=10.0, gamma=10.0),
                  dtype=jnp.float32).fit(Xa, Ya)
    B = BinarySVC(SVMConfig(C=10.0, gamma=5.0),
                  dtype=jnp.float32).fit(Xb, Yb)
    Xq, _ = rings(n=32, seed=3)

    import tempfile

    with tempfile.TemporaryDirectory() as td, \
            Server(ServeConfig(max_batch=8), dtype=jnp.float32) as srv:
        pa = os.path.join(td, "a.npz")
        pb = os.path.join(td, "b.npz")
        A.save(pa)
        B.save(pb)
        srv.load_model("m", pa)
        srv.warmup()
        refA, _ = srv.predict_direct("m", Xq)
        srv.swap("m", pb)
        refB, _ = srv.predict_direct("m", Xq)
        srv.swap("m", pa)
        if np.array_equal(refA, refB):
            print("SWAP CHAOS SMOKE FAILED: the two models are not "
                  "distinguishable — the torn-read check is vacuous")
            return 1

        # the chaos plan: kill one stage mid-swap, corrupt one staged
        # artifact's bytes, latency on the others — all seeded
        plan = faults.FaultPlan([
            faults.FaultRule(point="serve.swap", kind="kill", at_hit=2),
            faults.FaultRule(point="registry.load", kind="corrupt",
                             at_hit=4),
            faults.FaultRule(point="serve.swap", kind="latency",
                             p=0.5, delay_ms=5.0),
        ], seed=20260805)

        stop = threading.Event()
        bad = []
        bad_lock = threading.Lock()

        def client(t):
            i = t
            while not stop.is_set():
                r = srv.submit("m", Xq[i % 32], timeout_s=10.0)
                if not r.ok:
                    with bad_lock:
                        bad.append(("status", r.status))
                else:
                    s = np.asarray(r.scores)
                    if s != refA[i % 32] and s != refB[i % 32]:
                        with bad_lock:
                            bad.append(("torn", i % 32, float(s)))
                i += 1

        threads = [threading.Thread(target=client, args=(t,))
                   for t in range(4)]
        killed = corrupted = ok_swaps = 0
        with faults.active(plan):
            for t in threads:
                t.start()
            for k in range(8):
                target = pb if k % 2 == 0 else pa
                try:
                    srv.swap("m", target)
                    ok_swaps += 1
                except faults.SimulatedKill:
                    killed += 1  # mid-stage death: nothing flipped
                except ModelLoadError:
                    corrupted += 1
                    h = srv.health()
                    if h["status"] != "degraded":
                        failures.append(
                            "healthz not degraded after a corrupt "
                            f"staged artifact (got {h['status']})")
                # old generation must still answer, bitwise
                s, _ = srv.predict_direct("m", Xq)
                if not (np.array_equal(s, refA)
                        or np.array_equal(s, refB)):
                    failures.append(
                        f"scores after swap attempt {k} match neither "
                        "generation")
            stop.set()
            for t in threads:
                t.join(10.0)
        if bad:
            failures.append(f"client anomalies under chaos: {bad[:5]} "
                            f"({len(bad)} total)")
        if killed == 0:
            failures.append("the kill rule never fired")
        if corrupted == 0:
            failures.append("the corrupt rule never produced a "
                            "classified load failure")
        # recovery: a clean swap clears the degraded flag
        faults.deactivate()
        srv.swap("m", pb)
        h = srv.health()
        if h["status"] != "ok":
            failures.append(f"clean swap did not recover health: {h}")
        gen = h["swap"]["m"]["generation"]
    if failures:
        for f in failures:
            print(f"SWAP CHAOS SMOKE FAILED: {f}")
        return 1
    print(f"swap chaos smoke ok: {ok_swaps} swaps flipped, {killed} "
          f"killed mid-stage, {corrupted} corrupt stages rolled back, "
          f"0 torn/lost responses, final generation {gen}, health ok")
    return 0


def _autopilot_chaos_smoke() -> int:
    import os
    import threading

    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from tpusvm import faults
    from tpusvm.autopilot import Autopilot, AutopilotConfig, DriftThresholds
    from tpusvm.config import SVMConfig
    from tpusvm.data import rings
    from tpusvm.models import BinarySVC
    from tpusvm.serve import ServeConfig, Server
    from tpusvm.status import AutopilotStatus
    from tpusvm.stream import ShardWriter, ingest_arrays, open_dataset

    failures = []
    X, Y = rings(n=400, seed=11)
    BATCHES = [(s, s + 40) for s in range(240, 400, 40)]
    Xq = X[:24]

    def setup(td):
        """One complete closed loop: dataset, deployed artifact, server,
        supervisor config. Identical for control and chaos arms."""
        data = os.path.join(td, "data")
        ingest_arrays(data, X[:240], Y[:240], rows_per_shard=64)
        deployed = os.path.join(td, "deployed.npz")
        BinarySVC(SVMConfig(C=10.0, gamma=10.0),
                  dtype=jnp.float32).fit(X[:240], Y[:240]).save(deployed)
        srv = Server(ServeConfig(max_batch=8), dtype=jnp.float32)
        srv.load_model("m", deployed)
        srv.warmup()
        cfg = AutopilotConfig(
            data_dir=data, model_path=deployed,
            out_path=os.path.join(td, "m.refresh.npz"),
            name="m",
            thresholds=DriftThresholds(growth=0.55, feature=None,
                                       score=None, jitter_frac=0.0),
            hysteresis=1, cooldown_s=0.0,
            checkpoint_path=os.path.join(td, "refresh_ck.npz"),
            checkpoint_every=1,
            breaker_threshold=5, breaker_cooldown_s=0.05,
            seed=20260805,
        )
        return data, deployed, srv, cfg

    import tempfile

    with tempfile.TemporaryDirectory() as td:
        # ---------------- control arm: uninterrupted closed loop
        cdir = os.path.join(td, "control")
        os.makedirs(cdir)
        data_c, deployed_c, srv_c, cfg_c = setup(cdir)
        with srv_c:
            refA, _ = srv_c.predict_direct("m", Xq)
            # the supervisor deploys BEFORE the data grows: its state
            # records the deployed model's 240-row provenance
            pilot = Autopilot(cfg_c, server=srv_c, log_fn=lambda m: None)
            w = ShardWriter.open_append(data_c)
            for a, b in BATCHES:
                w.append(X[a:b], Y[a:b])
            w.close()
            out = pilot.tick()
            if out["status"] != AutopilotStatus.REFRESHED:
                print(f"AUTOPILOT CHAOS SMOKE FAILED: control arm did "
                      f"not refresh ({out['status'].name})")
                return 1
            # the served scores of BOTH complete generations: the chaos
            # arm's torn-read oracle (its refit is gated bit-identical
            # to this control artifact, so these are the only two score
            # vectors any chaos response may bitwise-match)
            refB, _ = srv_c.predict_direct("m", Xq)
        control = BinarySVC.load(cfg_c.out_path)
        ds_c = open_dataset(data_c)
        control_manifest = ds_c.manifest.to_json()

        # ---------------- chaos arm: same loop, kills at every stage
        hdir = os.path.join(td, "chaos")
        os.makedirs(hdir)
        data_h, deployed_h, srv_h, cfg_h = setup(hdir)
        # one fault on every stage of the closed loop: the append's
        # journal commit, the raw shard write, the refresh entry, the
        # solver checkpoint (kill at its FIRST write — the warm fit
        # converges within a couple of segments), a staged-swap failure
        # (transient — deterministic rollback; corrupt staged BYTES are
        # swap-chaos-smoke's dedicated gate), a corrupt artifact read on
        # the retry, and latency on scoring and ticks
        plan = faults.FaultPlan([
            faults.FaultRule(point="stream.append", kind="kill",
                             at_hit=2),
            faults.FaultRule(point="ingest.write_shard", kind="kill",
                             at_hit=3),
            faults.FaultRule(point="autopilot.refresh", kind="kill",
                             at_hit=1),
            faults.FaultRule(point="solver.outer_checkpoint",
                             kind="kill", at_hit=1),
            faults.FaultRule(point="serve.swap", kind="transient",
                             at_hit=1),
            faults.FaultRule(point="registry.load", kind="corrupt",
                             at_hit=2),
            faults.FaultRule(point="serve.score", kind="latency",
                             p=0.3, delay_ms=2.0, max_hits=16),
            faults.FaultRule(point="autopilot.tick", kind="latency",
                             p=0.5, delay_ms=1.0, max_hits=8),
        ], seed=20260805)

        with srv_h:
            refA_h, _ = srv_h.predict_direct("m", Xq)
            if not np.array_equal(refA_h, refA):
                failures.append("chaos deployed generation does not "
                                "serve the control's scores — arms are "
                                "not comparable")
            if np.array_equal(refA, refB):
                failures.append("deployed and refreshed models are "
                                "indistinguishable — the torn-"
                                "generation check is vacuous")
            stop = threading.Event()
            bad = []
            bad_lock = threading.Lock()

            def client(t):
                i = t
                while not stop.is_set():
                    r = srv_h.submit("m", Xq[i % 24], timeout_s=10.0)
                    if r.ok:
                        s = np.asarray(r.scores)
                        if s != refA[i % 24] and s != refB[i % 24]:
                            with bad_lock:
                                bad.append(("torn", i % 24, float(s)))
                    elif r.status.name not in ("TIMEOUT",):
                        with bad_lock:
                            bad.append(("status", r.status.name))
                    i += 1

            threads = [threading.Thread(target=client, args=(t,))
                       for t in range(4)]
            kills = 0
            degraded_seen = False
            # deploy the supervisor before the data grows (and before
            # the chaos starts): its crash-safe state file is what every
            # restarted incarnation resumes from
            pilot = Autopilot(cfg_h, server=srv_h, log_fn=lambda m: None)
            with faults.active(plan):
                for t in threads:
                    t.start()
                # appends with restart-on-kill (the killed "process" is
                # rebuilt with resume=True and replays its batch stream)
                for attempt in range(12):
                    try:
                        w = ShardWriter.open_append(
                            data_h, resume=attempt > 0)
                        for a, b in BATCHES:
                            w.append(X[a:b], Y[a:b])
                        w.close()
                        break
                    except faults.SimulatedKill:
                        kills += 1
                else:
                    failures.append("append never completed within the "
                                    "restart budget")
                # supervise with restart-on-kill until the refresh lands
                statuses = []
                for attempt in range(24):
                    try:
                        out = pilot.tick()
                    except faults.SimulatedKill:
                        kills += 1
                        pilot = Autopilot(cfg_h, server=srv_h,
                                          resume=True,
                                          log_fn=lambda m: None)
                        continue
                    statuses.append(out["status"])
                    if out["status"] == AutopilotStatus.REFRESH_FAILED \
                            and srv_h.health()["status"] == "degraded":
                        degraded_seen = True
                    if out["status"] == AutopilotStatus.REFRESHED:
                        s, _ = srv_h.predict_direct("m", Xq)
                        if not np.array_equal(s, refB):
                            failures.append(
                                "post-swap served scores do not "
                                "bitwise-match the control "
                                "generation")
                        break
                else:
                    failures.append(
                        "no refresh landed within the tick budget: "
                        f"{[s.name for s in statuses]}")
                stop.set()
                for t in threads:
                    t.join(10.0)
            faults.deactivate()

            # ---------------- the gates
            if kills == 0:
                failures.append("no kill rule ever fired — the chaos "
                                "arm degenerated to the control arm")
            if not degraded_seen:
                failures.append(
                    "the failed staged swap never rolled back to a "
                    "degraded-health old generation "
                    f"(serve.swap hits {plan.hits('serve.swap')}, "
                    f"registry.load hits {plan.hits('registry.load')})")
            if bad:
                failures.append(f"client anomalies under chaos: "
                                f"{bad[:5]} ({len(bad)} total)")
            if srv_h.health()["status"] != "ok":
                failures.append(
                    f"health did not recover: {srv_h.health()}")

        # journal audit: zero rows lost or duplicated
        ds_h = open_dataset(data_h)
        if ds_h.manifest.to_json() != control_manifest:
            failures.append("chaos dataset manifest differs from the "
                            "uninterrupted control (rows lost, "
                            "duplicated, or mis-sharded)")
        if os.path.exists(os.path.join(data_h, "ingest.journal.json")):
            failures.append("append journal survived the commit")
        Xc, Yc = ds_c.load_arrays()
        Xh, Yh = ds_h.load_arrays()
        if not (np.array_equal(Xc, Xh) and np.array_equal(Yc, Yh)):
            failures.append("chaos dataset rows differ from control")

        # the refit is bit-identical to the uninterrupted control
        if os.path.exists(cfg_h.out_path):
            chaos = BinarySVC.load(cfg_h.out_path)
            if chaos.sv_alpha_.tobytes() != control.sv_alpha_.tobytes() \
                    or not np.array_equal(chaos.sv_ids_,
                                          control.sv_ids_) \
                    or chaos.b_ != control.b_:
                failures.append(
                    "post-recovery refreshed model is NOT bit-identical "
                    f"to the control run ({len(chaos.sv_ids_)} vs "
                    f"{len(control.sv_ids_)} SVs, b {chaos.b_!r} vs "
                    f"{control.b_!r})")
        else:
            failures.append("chaos arm never produced a refreshed "
                            "artifact")

    if failures:
        for f in failures:
            print(f"AUTOPILOT CHAOS SMOKE FAILED: {f}")
        return 1
    print(f"autopilot chaos smoke ok: {kills} kills absorbed "
          "(append journal / shard write / refresh entry / solver "
          "checkpoint), failed staged swap rolled back degraded then "
          "recovered, 0 torn/lost responses, dataset journal-audited "
          "equal, refreshed model bit-identical to the uninterrupted "
          "control")
    return 0


def _router_chaos_smoke() -> int:
    import json
    import os
    import subprocess
    import threading
    import time
    import urllib.error
    import urllib.request

    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from tpusvm import faults
    from tpusvm.config import SVMConfig
    from tpusvm.data import rings
    from tpusvm.models import BinarySVC
    from tpusvm.router import (
        Router,
        RouterConfig,
        make_router_http,
        placement_table,
        table_bytes,
    )
    from tpusvm.serve import ServeConfig, Server
    from tpusvm.serve.http import start_http_thread
    from tpusvm.status import RouterStatus

    N_REPLICAS = 3
    N_CLIENTS = 4
    failures = []

    Xa, Ya = rings(n=240, seed=2)
    Xb, Yb = rings(n=240, seed=9)
    A = BinarySVC(SVMConfig(C=10.0, gamma=10.0),
                  dtype=jnp.float32).fit(Xa, Ya)
    Bm = BinarySVC(SVMConfig(C=10.0, gamma=5.0),
                   dtype=jnp.float32).fit(Xb, Yb)
    Xq, _ = rings(n=16, seed=3)
    rows = [np.asarray(Xq[i], float).tolist() for i in range(len(Xq))]

    with tempfile.TemporaryDirectory() as td:
        pa = os.path.join(td, "v1.npz")
        pb = os.path.join(td, "v2.npz")
        A.save(pa)
        Bm.save(pb)
        # the bitwise oracles: the SAME scoring arithmetic the replica
        # processes run, via the sequential in-process path
        with Server(ServeConfig(max_batch=8), dtype=jnp.float32) as orc:
            orc.load_model("m", pa)
            ra, _ = orc.predict_direct("m", Xq)
            orc.swap("m", pb)
            rb, _ = orc.predict_direct("m", Xq)
        refA = [float(v) for v in np.asarray(ra).ravel()]
        refB = [float(v) for v in np.asarray(rb).ravel()]
        if refA == refB:
            print("ROUTER CHAOS SMOKE FAILED: the two generations are "
                  "not distinguishable — the bitwise oracle is vacuous")
            return 1

        def state_path(i):
            return os.path.join(td, f"rep{i}", "serve_state.json")

        logs = []

        def spawn(i, port=0):
            """One REAL replica process. port=0 first boot (ephemeral,
            satellite: the bound port is discovered from the state
            file); a revive passes the recorded port back in and
            restores the model set + replica identity from --state."""
            os.makedirs(os.path.dirname(state_path(i)), exist_ok=True)
            log = open(os.path.join(td, f"rep{i}.log"), "ab")
            logs.append(log)
            cmd = [sys.executable, "-m", "tpusvm", "serve",
                   "--platform", "cpu", "--host", "127.0.0.1",
                   "--port", str(port), "--state", state_path(i),
                   "--max-batch", "8", "--no-warmup"]
            if port == 0:
                cmd += ["--model", f"m={pa}"]
            return subprocess.Popen(cmd, stdout=log,
                                    stderr=subprocess.STDOUT)

        def wait_ready(i, deadline_s=120.0):
            """Discover the replica's bound address from its state file,
            then wait for /healthz ok; (url, replica_id)."""
            t0 = time.monotonic()
            while time.monotonic() - t0 < deadline_s:
                try:
                    with open(state_path(i)) as f:
                        st = json.load(f)
                    addr = st.get("address")
                    if addr and st.get("models"):
                        url = f"http://{addr}"
                        with urllib.request.urlopen(
                                url + "/healthz", timeout=2.0) as r:
                            payload = json.loads(r.read())
                        if payload.get("status") == "ok":
                            return url, st.get("replica_id")
                except (OSError, ValueError):
                    pass
                time.sleep(0.2)
            raise RuntimeError(f"replica {i} not serving within "
                               f"{deadline_s:g}s (see rep{i}.log)")

        procs = [spawn(i) for i in range(N_REPLICAS)]
        router = None
        stop = threading.Event()
        try:
            ready = [wait_ready(i) for i in range(N_REPLICAS)]
            urls = [u for u, _ in ready]
            ids0 = dict(ready)

            # placement byte-reproducibility: two independent
            # constructions of the same (keys, replicas, k, seed)
            keys = ["m", "m-shadow", "m-canary"]
            if table_bytes(placement_table(keys, urls, k=2, seed=7)) \
                    != table_bytes(placement_table(list(keys),
                                                   tuple(urls),
                                                   k=2, seed=7)):
                failures.append("placement tables for one seed are not "
                                "byte-identical")

            router = Router(RouterConfig(
                replicas=tuple(urls), replication=2, seed=7,
                poll_interval_s=0.2, down_after=2,
                forward_timeout_s=30.0), log_fn=lambda m: None)
            router.start()
            httpd = make_router_http(router, port=0)
            router.attach_http(httpd, start_http_thread(httpd))
            rhost, rport = httpd.server_address[:2]
            router_url = f"http://{rhost}:{rport}"

            # chaos INSIDE the fabric: two deterministic transient
            # forwards (each consumes one failover) + forward latency
            plan = faults.FaultPlan([
                faults.FaultRule(point="router.forward",
                                 kind="transient", at_hit=5),
                faults.FaultRule(point="router.forward",
                                 kind="transient", at_hit=23),
                faults.FaultRule(point="router.forward", kind="latency",
                                 p=0.2, delay_ms=1.0, max_hits=16),
            ], seed=20260806)

            bad = []
            bad_lock = threading.Lock()
            counts = [0] * N_CLIENTS

            def client(t):
                i = t
                while not stop.is_set():
                    idx = i % len(rows)
                    body = json.dumps({"instances": [rows[idx]]}).encode()
                    req = urllib.request.Request(
                        router_url + "/v1/models/m:predict", data=body,
                        headers={"Content-Type": "application/json"},
                        method="POST")
                    try:
                        with urllib.request.urlopen(req,
                                                    timeout=30.0) as r:
                            code, raw = r.status, r.read()
                    except urllib.error.HTTPError as e:
                        code, raw = e.code, e.read()
                    except Exception as e:  # noqa: BLE001 — transport
                        # failure to the ROUTER itself = a lost response
                        with bad_lock:
                            bad.append(("transport",
                                        f"{type(e).__name__}: {e}"))
                        i += 1
                        continue
                    if code == 429:
                        time.sleep(0.1)  # backpressure: same row again
                        continue
                    if code != 200:
                        with bad_lock:
                            bad.append(("code", code, raw[:160]))
                    else:
                        s = json.loads(raw)["scores"][0]
                        if isinstance(s, list):
                            s = s[0]
                        if s != refA[idx] and s != refB[idx]:
                            with bad_lock:
                                bad.append(("torn", idx, s))
                        counts[t] += 1
                    i += 1

            threads = [threading.Thread(target=client, args=(t,))
                       for t in range(N_CLIENTS)]
            kills = revives = 0
            with faults.active(plan):
                for t in threads:
                    t.start()
                time.sleep(1.0)
                # kill + revive two replicas, one at a time, under load
                for i in (0, 1):
                    procs[i].kill()  # real SIGKILL, nothing flushed
                    procs[i].wait()
                    kills += 1
                    time.sleep(1.0)  # clients keep scoring via failover
                    with open(state_path(i)) as f:
                        st = json.load(f)
                    port = int(st["address"].rsplit(":", 1)[1])
                    procs[i] = spawn(i, port=port)
                    url, rid = wait_ready(i)
                    revives += 1
                    if url != urls[i]:
                        failures.append(
                            f"replica {i} revived on {url}, not its "
                            f"recorded address {urls[i]}")
                    if rid != ids0[urls[i]]:
                        failures.append(
                            f"replica {i} lost its identity across the "
                            f"revive ({ids0[urls[i]]} -> {rid})")
                time.sleep(0.8)  # poller re-admits the revived replicas
                # staggered rollout v1 -> v2 across the fleet, under load
                out = router.rollout("m", pb)
                if out["status"] != RouterStatus.OK.name:
                    failures.append(f"rollout did not complete: {out}")
                if out["failed"]:
                    failures.append(f"rollout swaps failed: "
                                    f"{out['failed']}")
                if len(out["swapped"]) != N_REPLICAS:
                    failures.append(
                        f"rollout reached {len(out['swapped'])}/"
                        f"{N_REPLICAS} replicas "
                        f"(skipped {out['skipped']})")
                rep = out["report"]
                gens = set(rep["vector"].values())
                if rep["skew"] != 0 or rep["unknown"] or len(gens) != 1 \
                        or None in gens:
                    failures.append("final generation vector is not "
                                    f"uniform/skew-free: {rep}")
                time.sleep(0.5)  # post-rollout traffic on the new gen
                stop.set()
                for t in threads:
                    t.join(30.0)
            faults.deactivate()

            if bad:
                failures.append(f"lost/torn responses under chaos: "
                                f"{bad[:5]} ({len(bad)} total)")
            if min(counts) == 0:
                failures.append(f"a client thread scored nothing: "
                                f"{counts}")
            if plan.hits("router.forward") == 0:
                failures.append("no router.forward fault ever fired")
            h = router.health()
            if h["router"] != RouterStatus.OK.name:
                failures.append(f"router health did not recover: {h}")
        finally:
            stop.set()
            if router is not None:
                router.close()
            for p in procs:
                p.kill()
            for p in procs:
                try:
                    p.wait(timeout=10.0)
                except Exception:  # noqa: BLE001 — teardown best-effort
                    pass
            for log in logs:
                log.close()

    if failures:
        for f in failures:
            print(f"ROUTER CHAOS SMOKE FAILED: {f}")
        return 1
    print(f"router chaos smoke ok: {N_REPLICAS} replica processes, "
          f"{N_CLIENTS} client threads ({sum(counts)} responses, 0 "
          f"lost/torn), {kills} SIGKILLs absorbed with identity-"
          f"preserving revives, staggered rollout skew-free to a "
          f"uniform generation vector, placement bytes reproducible, "
          f"{plan.hits('router.forward')} router.forward fault-point "
          f"hits")
    return 0


def _tenant_chaos_smoke() -> int:
    import glob
    import os
    import threading

    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from tpusvm import faults
    from tpusvm.autopilot import DriftThresholds
    from tpusvm.models import BinarySVC
    from tpusvm.serve import ServeConfig, Server
    from tpusvm.status import TenantsStatus
    from tpusvm.stream import ShardWriter, ingest_arrays, open_dataset
    from tpusvm.tenants import (
        TenantRecord,
        TenantsConfig,
        TenantsSupervisor,
        provision_tenants,
        tenant_labels,
        view_fingerprint,
    )

    failures = []
    N_T = 64
    D, K = 6, 8
    N0, N1, N2 = 320, 160, 160
    rng = np.random.default_rng(20260806)
    labels_all = rng.integers(0, K, size=N0 + N1 + N2).astype(np.int32)
    means = rng.normal(0.0, 2.0, size=(K, D))
    # f64 host rows: the serve tier validates/scales queries in f64
    # (registry.validate_rows), so the bitwise served-vs-offline oracle
    # contract is stated for f64 inputs — exactly what clients POST
    X_all = means[labels_all] + rng.normal(0.0, 1.0,
                                           size=(N0 + N1 + N2, D))
    # the appended batches are distribution-shifted so every tenant's
    # refreshed solution genuinely differs from its donor (the
    # torn-generation oracle needs two DISTINGUISHABLE generations)
    X_all[N0:] += 0.75
    Xq = X_all[:8]
    C_PAL, G_PAL = (1.0, 3.0, 10.0), (0.5, 1.5, 5.0)

    def mk_records():
        recs = []
        for i in range(N_T):
            recs.append(TenantRecord(
                tenant_id=f"t{i:02d}", positive_label=i % K,
                C=C_PAL[i % 3], gamma=G_PAL[(i // 3) % 3],
                row_mod=2 if i % 8 == 7 else None,
                row_ofs=(i // 8) % 2 if i % 8 == 7 else 0))
        return recs

    def setup(td):
        """One complete platform: shared dataset, 64 provisioned donors
        (ONE cold fleet launch), supervisor config. Identical for
        control and chaos arms."""
        data = os.path.join(td, "data")
        donors = os.path.join(td, "donors")
        arts = os.path.join(td, "artifacts")
        os.makedirs(donors)
        ingest_arrays(data, X_all[:N0], labels_all[:N0],
                      rows_per_shard=64)
        recs = mk_records()
        provision_tenants(X_all[:N0], labels_all[:N0], recs,
                          artifacts_dir=donors)
        cfg = TenantsConfig(
            data_dir=data,
            store_path=os.path.join(td, "tenants_store.json"),
            artifacts_dir=arts,
            thresholds=DriftThresholds(growth=0.25, feature=None,
                                       score=None, jitter_frac=0.0),
            hysteresis=1, cooldown_s=0.0,
            checkpoint_every=2, min_fleet=2,
            breaker_threshold=5, breaker_cooldown_s=0.05,
            seed=20260806,
            solver_opts={"q": 32, "max_inner": 8},
        )
        return data, recs, cfg

    def append(data, a, b):
        w = ShardWriter.open_append(data)
        w.append(X_all[a:b], labels_all[a:b])
        w.close()

    import tempfile

    with tempfile.TemporaryDirectory() as td:
        # ---------------- control arm: uninterrupted platform
        cdir = os.path.join(td, "control")
        os.makedirs(cdir)
        data_c, recs_c, cfg_c = setup(cdir)
        sup = TenantsSupervisor(cfg_c, log_fn=lambda m: None)
        for rec in recs_c:
            sup.register(rec)
        out = sup.tick()
        if out["status"] != TenantsStatus.WATCHING:
            print("TENANT CHAOS SMOKE FAILED: control arm drifted "
                  "before any append")
            return 1
        append(data_c, N0, N0 + N1)
        out = sup.tick()
        if out["status"] != TenantsStatus.REFRESHED:
            print(f"TENANT CHAOS SMOKE FAILED: control arm did not "
                  f"refresh ({out['status'].name})")
            return 1
        append(data_c, N0 + N1, N0 + N1 + N2)
        tids = sorted(st.tenant_id for st in recs_c)
        # both complete generations of every tenant, as OFFLINE oracles
        # (serving is bitwise-equal to offline f32 decision_function —
        # the serve-tier contract): the chaos arm's torn-read reference
        refOld, refNew, control_art = {}, {}, {}
        for tid in tids:
            refOld[tid] = np.asarray(BinarySVC.load(
                os.path.join(cdir, "donors", tid + ".npz"),
                dtype=jnp.float32).decision_function(Xq))
            m = BinarySVC.load(os.path.join(cdir, "artifacts",
                                            tid + ".npz"),
                               dtype=jnp.float32)
            refNew[tid] = np.asarray(m.decision_function(Xq))
            control_art[tid] = m
        distinct = sum(not np.array_equal(refOld[t], refNew[t])
                       for t in tids)
        if distinct < N_T // 2:
            failures.append(
                f"only {distinct}/{N_T} tenants changed across the "
                "refresh — the torn-generation check is vacuous")
        ds_c = open_dataset(data_c)
        control_manifest = ds_c.manifest.to_json()
        Xc, Yc = ds_c.load_arrays()

        # ---------------- chaos arm: same platform, killed mid-fleet
        hdir = os.path.join(td, "chaos")
        os.makedirs(hdir)
        data_h, recs_h, cfg_h = setup(hdir)
        srv = Server(ServeConfig(max_batch=8), dtype=jnp.float32)
        for rec in recs_h:
            srv.load_model(rec.tenant_id, rec.model_path)
        with srv:
            sup_h = TenantsSupervisor(cfg_h, server=srv,
                                      log_fn=lambda m: None)
            for rec in recs_h:
                sup_h.register(rec)
            out = sup_h.tick()
            if out["status"] != TenantsStatus.WATCHING:
                failures.append("chaos arm drifted before any append")
            for tid in tids:
                s, _ = srv.predict_direct(tid, Xq)
                if not np.array_equal(np.asarray(s), refOld[tid]):
                    failures.append(
                        f"chaos donor generation of {tid} does not "
                        "serve the control's scores — arms are not "
                        "comparable")
                    break
            append(data_h, N0, N0 + N1)

            # the kill plan counts tenants.store hits WITHIN the
            # refresh tick (activated only now, so registration writes
            # don't shift the count): hit 1 is the stage="fitting"
            # store commit, hits 2.. are the fleet segment checkpoints
            # — at_hit=3 dies at the SECOND checkpoint write, i.e. with
            # a durable first-segment checkpoint on disk
            plan = faults.FaultPlan([
                faults.FaultRule(point="tenants.store", kind="kill",
                                 at_hit=3),
                faults.FaultRule(point="tenants.tick", kind="latency",
                                 p=0.5, delay_ms=1.0, max_hits=8),
                faults.FaultRule(point="serve.score", kind="latency",
                                 p=0.3, delay_ms=2.0, max_hits=16),
            ], seed=20260806)
            stop = threading.Event()
            bad = []
            bad_lock = threading.Lock()

            def client(t):
                i = t
                while not stop.is_set():
                    tid = tids[(7 * t + i) % N_T]
                    r = srv.submit(tid, Xq[i % 8], timeout_s=10.0)
                    if r.ok:
                        s = np.asarray(r.scores)
                        if s != refOld[tid][i % 8] \
                                and s != refNew[tid][i % 8]:
                            with bad_lock:
                                bad.append(("torn", tid, i % 8,
                                            float(s)))
                    elif r.status.name not in ("TIMEOUT",):
                        with bad_lock:
                            bad.append(("status", r.status.name))
                    i += 1

            threads = [threading.Thread(target=client, args=(t,))
                       for t in range(3)]
            kills = 0
            ck_at_kill = False
            with faults.active(plan):
                for t in threads:
                    t.start()
                statuses = []
                for attempt in range(16):
                    try:
                        out = sup_h.tick()
                    except faults.SimulatedKill:
                        kills += 1
                        # the evidence that recovery RESUMES rather
                        # than restarts: a durable fleet checkpoint
                        # exists at the moment of death
                        if glob.glob(os.path.join(
                                hdir, "artifacts", "fleet_*.ck.npz")):
                            ck_at_kill = True
                        sup_h = TenantsSupervisor(
                            cfg_h, server=srv, resume=True,
                            log_fn=lambda m: None)
                        continue
                    statuses.append(out["status"])
                    if out["status"] == TenantsStatus.REFRESHED:
                        break
                else:
                    failures.append(
                        "no coalesced refresh landed within the tick "
                        f"budget: {[s.name for s in statuses]}")
                stop.set()
                for t in threads:
                    t.join(10.0)
            faults.deactivate()

            # ---------------- phase-1 gates
            if kills == 0:
                failures.append("the kill rule never fired — the chaos "
                                "arm degenerated to the control arm")
            if kills and not ck_at_kill:
                failures.append(
                    "killed mid-fleet-refresh with NO durable segment "
                    "checkpoint on disk — recovery would re-fit from "
                    "scratch")
            if bad:
                failures.append(f"client anomalies under chaos: "
                                f"{bad[:5]} ({len(bad)} total)")
            if glob.glob(os.path.join(hdir, "artifacts",
                                      "fleet_*.ck.npz")):
                failures.append("fleet checkpoints survived the "
                                "swapping-stage commit")
            for tid in tids:
                rec = sup_h.state.tenants[tid]
                if rec.generation != 1:
                    failures.append(
                        f"{tid} generation {rec.generation} != 1 after "
                        "the recovered refresh")
                    continue
                s, _ = srv.predict_direct(tid, Xq)
                s = np.asarray(s)
                if not np.array_equal(s, refNew[tid]):
                    still_old = np.array_equal(s, refOld[tid])
                    failures.append(
                        f"post-recovery served scores of {tid} do not "
                        "bitwise-match the control generation "
                        f"(max |delta| "
                        f"{float(np.max(np.abs(s - refNew[tid])))!r}, "
                        f"still the donor generation: {still_old})")
                chaos = BinarySVC.load(os.path.join(
                    hdir, "artifacts", tid + ".npz"))
                ctrl = control_art[tid]
                if chaos.sv_alpha_.tobytes() != ctrl.sv_alpha_.tobytes() \
                        or not np.array_equal(chaos.sv_ids_,
                                              ctrl.sv_ids_) \
                        or chaos.b_ != ctrl.b_ \
                        or chaos.n_iter_ != ctrl.n_iter_:
                    failures.append(
                        f"recovered refit of {tid} is NOT bit-identical "
                        f"to the uninterrupted control "
                        f"({len(chaos.sv_ids_)} vs {len(ctrl.sv_ids_)} "
                        f"SVs, b {chaos.b_!r} vs {ctrl.b_!r}, n_iter "
                        f"{chaos.n_iter_} vs {ctrl.n_iter_})")

            # ---------------- phase 2: corrupt one swap's bytes
            append(data_h, N0 + N1, N0 + N1 + N2)
            plan2 = faults.FaultPlan([
                faults.FaultRule(point="registry.load", kind="corrupt",
                                 at_hit=1),
            ], seed=20260806)
            with faults.active(plan2):
                out = sup_h.tick()
            faults.deactivate()
            if out["status"] != TenantsStatus.PARTIAL:
                failures.append(
                    "the corrupted swap did not surface as a PARTIAL "
                    f"generation (got {out['status'].name})")
            stuck = [tid for tid in tids
                     if sup_h.state.tenants[tid].generation == 1]
            if len(stuck) != 1:
                failures.append(
                    f"expected exactly one tenant pinned on its "
                    f"previous generation, got {stuck}")
            else:
                s, _ = srv.predict_direct(stuck[0], Xq)
                if not np.array_equal(np.asarray(s), refNew[stuck[0]]):
                    failures.append(
                        f"{stuck[0]}'s failed swap did not keep its "
                        "previous generation serving bitwise")
                out = sup_h.tick()
                if out["status"] != TenantsStatus.REFRESHED \
                        or out["drifted"] != stuck:
                    failures.append(
                        "the corrupted tenant did not stay armed and "
                        f"recover solo (status {out['status'].name}, "
                        f"drifted {out['drifted']})")
                else:
                    want = np.asarray(BinarySVC.load(
                        os.path.join(hdir, "artifacts",
                                     stuck[0] + ".npz"),
                        dtype=jnp.float32).decision_function(Xq))
                    s, _ = srv.predict_direct(stuck[0], Xq)
                    if not np.array_equal(np.asarray(s), want):
                        failures.append(
                            f"{stuck[0]}'s recovery swap does not "
                            "serve its refreshed artifact bitwise")

        # ---------------- gate (a): no tenant lost rows
        ds_h = open_dataset(data_h)
        if ds_h.manifest.to_json() != control_manifest:
            failures.append("chaos dataset manifest differs from the "
                            "uninterrupted control (rows lost, "
                            "duplicated, or mis-sharded)")
        Xh, Yh = ds_h.load_arrays()
        if not (np.array_equal(Xc, Xh) and np.array_equal(Yc, Yh)):
            failures.append("chaos dataset rows differ from control")
        else:
            for rc, rh in zip(recs_c, recs_h):
                if view_fingerprint(*tenant_labels(Yc, rc)) != \
                        view_fingerprint(*tenant_labels(Yh, rh)):
                    failures.append(
                        f"tenant {rc.tenant_id} view fingerprint "
                        "differs between arms")
                    break

    if failures:
        for f in failures:
            print(f"TENANT CHAOS SMOKE FAILED: {f}")
        return 1
    print(f"tenant chaos smoke ok: {N_T} tenants, supervisor killed "
          f"mid-fleet-refresh ({kills} kills) resumed from a durable "
          "segment checkpoint to artifacts bit-identical to the "
          "uninterrupted control, 0 torn responses, every view "
          "fingerprint equal, corrupted swap pinned one tenant on its "
          "previous generation then recovered solo")
    return 0


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__)
        return 0 if argv else 2
    cmd, rest = argv[0], argv[1:]
    if cmd == "kill-resume-smoke":
        return _kill_resume_smoke()
    if cmd == "swap-chaos-smoke":
        return _swap_chaos_smoke()
    if cmd == "router-chaos-smoke":
        return _router_chaos_smoke()
    if cmd == "autopilot-chaos-smoke":
        return _autopilot_chaos_smoke()
    if cmd == "tenant-chaos-smoke":
        return _tenant_chaos_smoke()
    if cmd == "pod-chaos-smoke":
        return _pod_chaos_smoke()
    if cmd == "validate":
        if len(rest) != 1:
            print("usage: python -m tpusvm.faults validate PLAN.json")
            return 2
        return _validate(rest[0])
    print(f"unknown command {cmd!r}; see --help")
    return 2


if __name__ == "__main__":
    sys.exit(main())
