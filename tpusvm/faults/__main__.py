"""Chaos CLI: ``python -m tpusvm.faults <command>``.

Commands:

  kill-resume-smoke   The crash-safe-training CI gate. Trains a tiny
                      deterministic problem three ways — uninterrupted
                      plain solve, checkpointed solve, and checkpointed
                      solves KILLED at every checkpoint in turn and then
                      resumed — and asserts every variant produces
                      bit-identical model state (alpha bytes, SV ids, b).
                      Also proves transient checkpoint-write faults are
                      retried to success. Non-zero exit on any failure.
  validate PLAN.json  Parse + validate a fault plan (rule points/kinds
                      checked against the registry); prints the rules.
"""

from __future__ import annotations

import sys
import tempfile


def _kill_resume_smoke() -> int:
    import os

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp
    import numpy as np

    from tpusvm import faults
    from tpusvm.data import MinMaxScaler, rings
    from tpusvm.oracle.smo import get_sv_indices
    from tpusvm.solver.blocked import blocked_smo_solve
    from tpusvm.solver.checkpoint import checkpointed_blocked_solve
    from tpusvm.status import Status

    EVERY = 4
    X, Y = rings(n=400, seed=11)
    Xs = jnp.asarray(MinMaxScaler().fit_transform(X), jnp.float32)
    Yd = jnp.asarray(Y)
    kw = dict(C=10.0, gamma=10.0, q=16, accum_dtype=jnp.float64)

    plain = blocked_smo_solve(Xs, Yd, **kw)
    if Status(int(plain.status)) != Status.CONVERGED:
        print(f"KILL-RESUME SMOKE FAILED: reference solve ended "
              f"{Status(int(plain.status)).name}")
        return 1
    ref_alpha = np.asarray(plain.alpha)
    ref_sv = get_sv_indices(ref_alpha, 1e-8)
    n_ckpts = max(1, int(plain.n_outer) // EVERY)
    failures = []

    def run(ck, resume=False):
        return checkpointed_blocked_solve(
            Xs, Yd, checkpoint_path=ck, checkpoint_every=EVERY,
            resume=resume, **kw,
        )

    def identical(res):
        a = np.asarray(res.alpha)
        return (a.tobytes() == ref_alpha.tobytes()
                and np.array_equal(get_sv_indices(a, 1e-8), ref_sv)
                and float(res.b) == float(plain.b))

    with tempfile.TemporaryDirectory() as td:
        # 1. checkpointed-but-never-killed == plain, bit for bit
        ck = os.path.join(td, "ck.npz")
        if not identical(run(ck)):
            failures.append("uninterrupted checkpointed solve diverged "
                            "from the plain solve")

        # 2. kill at EVERY checkpoint, resume, still bit-identical
        for k in range(1, n_ckpts + 1):
            ckk = os.path.join(td, f"ck{k}.npz")
            plan = faults.FaultPlan(
                [faults.FaultRule(point="solver.outer_checkpoint",
                                  kind="kill", at_hit=k)], seed=0)
            died = False
            try:
                with faults.active(plan):
                    run(ckk)
            except faults.SimulatedKill:
                died = True
            if not died:
                failures.append(f"kill rule at checkpoint {k} never fired")
                continue
            if not identical(run(ckk, resume=True)):
                failures.append(
                    f"resume after kill at checkpoint {k} is not "
                    "bit-identical")

        # 3. transient write faults are retried to success
        ckt = os.path.join(td, "ckt.npz")
        plan = faults.FaultPlan(
            [faults.FaultRule(point="solver.outer_checkpoint",
                              kind="transient", max_hits=2)], seed=0)
        with faults.active(plan):
            if not identical(run(ckt)):
                failures.append("solve under transient checkpoint-write "
                                "faults diverged")

    if failures:
        for f in failures:
            print(f"KILL-RESUME SMOKE FAILED: {f}")
        return 1
    print(f"kill-resume smoke ok: {n_ckpts} kill points, "
          f"{int(plain.n_outer)} outer rounds, {len(ref_sv)} SVs — every "
          "resumed solve bit-identical to the uninterrupted run")
    return 0


def _validate(path: str) -> int:
    from tpusvm import faults

    plan = faults.load_plan(path)
    print(f"fault plan ok: {path} (seed {plan.seed}, "
          f"{len(plan.rules)} rules)")
    for r in plan.rules:
        extra = ""
        if r.at_hit is not None:
            extra = f" at_hit={r.at_hit}"
        elif r.max_hits is not None:
            extra = f" p={r.p:g} max_hits={r.max_hits}"
        else:
            extra = f" p={r.p:g}"
        if r.kind == "latency":
            extra += f" delay_ms={r.delay_ms:g}"
        print(f"  {r.point}: {r.kind}{extra}")
    return 0


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__)
        return 0 if argv else 2
    cmd, rest = argv[0], argv[1:]
    if cmd == "kill-resume-smoke":
        return _kill_resume_smoke()
    if cmd == "validate":
        if len(rest) != 1:
            print("usage: python -m tpusvm.faults validate PLAN.json")
            return 2
        return _validate(rest[0])
    print(f"unknown command {cmd!r}; see --help")
    return 2


if __name__ == "__main__":
    sys.exit(main())
