"""Chaos CLI: ``python -m tpusvm.faults <command>``.

Commands:

  kill-resume-smoke   The crash-safe-training CI gate. Trains a tiny
                      deterministic problem three ways — uninterrupted
                      plain solve, checkpointed solve, and checkpointed
                      solves KILLED at every checkpoint in turn and then
                      resumed — and asserts every variant produces
                      bit-identical model state (alpha bytes, SV ids, b).
                      Also proves transient checkpoint-write faults are
                      retried to success. Non-zero exit on any failure.
  validate PLAN.json  Parse + validate a fault plan (rule points/kinds
                      checked against the registry); prints the rules.
  swap-chaos-smoke    The resilient-serving CI gate: client threads
                      stream requests while hot-swaps flip between two
                      models with distinct outputs, under a fault plan
                      that kills a swap mid-stage, corrupts a staged
                      artifact's bytes, and injects compile/stage
                      latency. Asserts: every scored response bitwise-
                      matches exactly one of the two models (no torn
                      generation), zero requests lost or errored beyond
                      the injected causes, every failed stage rolled
                      back to a serving old generation with /healthz
                      degraded, and a subsequent clean swap recovers.
"""

from __future__ import annotations

import sys
import tempfile


def _kill_resume_smoke() -> int:
    import os

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp
    import numpy as np

    from tpusvm import faults
    from tpusvm.data import MinMaxScaler, rings
    from tpusvm.oracle.smo import get_sv_indices
    from tpusvm.solver.blocked import blocked_smo_solve
    from tpusvm.solver.checkpoint import checkpointed_blocked_solve
    from tpusvm.status import Status

    EVERY = 4
    X, Y = rings(n=400, seed=11)
    Xs = jnp.asarray(MinMaxScaler().fit_transform(X), jnp.float32)
    Yd = jnp.asarray(Y)
    kw = dict(C=10.0, gamma=10.0, q=16, accum_dtype=jnp.float64)

    plain = blocked_smo_solve(Xs, Yd, **kw)
    if Status(int(plain.status)) != Status.CONVERGED:
        print(f"KILL-RESUME SMOKE FAILED: reference solve ended "
              f"{Status(int(plain.status)).name}")
        return 1
    ref_alpha = np.asarray(plain.alpha)
    ref_sv = get_sv_indices(ref_alpha, 1e-8)
    n_ckpts = max(1, int(plain.n_outer) // EVERY)
    failures = []

    def run(ck, resume=False):
        return checkpointed_blocked_solve(
            Xs, Yd, checkpoint_path=ck, checkpoint_every=EVERY,
            resume=resume, **kw,
        )

    def identical(res):
        a = np.asarray(res.alpha)
        return (a.tobytes() == ref_alpha.tobytes()
                and np.array_equal(get_sv_indices(a, 1e-8), ref_sv)
                and float(res.b) == float(plain.b))

    with tempfile.TemporaryDirectory() as td:
        # 1. checkpointed-but-never-killed == plain, bit for bit
        ck = os.path.join(td, "ck.npz")
        if not identical(run(ck)):
            failures.append("uninterrupted checkpointed solve diverged "
                            "from the plain solve")

        # 2. kill at EVERY checkpoint, resume, still bit-identical
        for k in range(1, n_ckpts + 1):
            ckk = os.path.join(td, f"ck{k}.npz")
            plan = faults.FaultPlan(
                [faults.FaultRule(point="solver.outer_checkpoint",
                                  kind="kill", at_hit=k)], seed=0)
            died = False
            try:
                with faults.active(plan):
                    run(ckk)
            except faults.SimulatedKill:
                died = True
            if not died:
                failures.append(f"kill rule at checkpoint {k} never fired")
                continue
            if not identical(run(ckk, resume=True)):
                failures.append(
                    f"resume after kill at checkpoint {k} is not "
                    "bit-identical")

        # 3. transient write faults are retried to success
        ckt = os.path.join(td, "ckt.npz")
        plan = faults.FaultPlan(
            [faults.FaultRule(point="solver.outer_checkpoint",
                              kind="transient", max_hits=2)], seed=0)
        with faults.active(plan):
            if not identical(run(ckt)):
                failures.append("solve under transient checkpoint-write "
                                "faults diverged")

    if failures:
        for f in failures:
            print(f"KILL-RESUME SMOKE FAILED: {f}")
        return 1
    print(f"kill-resume smoke ok: {n_ckpts} kill points, "
          f"{int(plain.n_outer)} outer rounds, {len(ref_sv)} SVs — every "
          "resumed solve bit-identical to the uninterrupted run")
    return 0


def _validate(path: str) -> int:
    from tpusvm import faults

    plan = faults.load_plan(path)
    print(f"fault plan ok: {path} (seed {plan.seed}, "
          f"{len(plan.rules)} rules)")
    for r in plan.rules:
        extra = ""
        if r.at_hit is not None:
            extra = f" at_hit={r.at_hit}"
        elif r.max_hits is not None:
            extra = f" p={r.p:g} max_hits={r.max_hits}"
        else:
            extra = f" p={r.p:g}"
        if r.kind == "latency":
            extra += f" delay_ms={r.delay_ms:g}"
        print(f"  {r.point}: {r.kind}{extra}")
    return 0


def _swap_chaos_smoke() -> int:
    import os
    import threading

    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from tpusvm import faults
    from tpusvm.config import SVMConfig
    from tpusvm.data import rings
    from tpusvm.models import BinarySVC
    from tpusvm.serve import ModelLoadError, ServeConfig, Server

    failures = []
    Xa, Ya = rings(n=240, seed=2)
    Xb, Yb = rings(n=240, seed=9)
    A = BinarySVC(SVMConfig(C=10.0, gamma=10.0),
                  dtype=jnp.float32).fit(Xa, Ya)
    B = BinarySVC(SVMConfig(C=10.0, gamma=5.0),
                  dtype=jnp.float32).fit(Xb, Yb)
    Xq, _ = rings(n=32, seed=3)

    import tempfile

    with tempfile.TemporaryDirectory() as td, \
            Server(ServeConfig(max_batch=8), dtype=jnp.float32) as srv:
        pa = os.path.join(td, "a.npz")
        pb = os.path.join(td, "b.npz")
        A.save(pa)
        B.save(pb)
        srv.load_model("m", pa)
        srv.warmup()
        refA, _ = srv.predict_direct("m", Xq)
        srv.swap("m", pb)
        refB, _ = srv.predict_direct("m", Xq)
        srv.swap("m", pa)
        if np.array_equal(refA, refB):
            print("SWAP CHAOS SMOKE FAILED: the two models are not "
                  "distinguishable — the torn-read check is vacuous")
            return 1

        # the chaos plan: kill one stage mid-swap, corrupt one staged
        # artifact's bytes, latency on the others — all seeded
        plan = faults.FaultPlan([
            faults.FaultRule(point="serve.swap", kind="kill", at_hit=2),
            faults.FaultRule(point="registry.load", kind="corrupt",
                             at_hit=4),
            faults.FaultRule(point="serve.swap", kind="latency",
                             p=0.5, delay_ms=5.0),
        ], seed=20260805)

        stop = threading.Event()
        bad = []
        bad_lock = threading.Lock()

        def client(t):
            i = t
            while not stop.is_set():
                r = srv.submit("m", Xq[i % 32], timeout_s=10.0)
                if not r.ok:
                    with bad_lock:
                        bad.append(("status", r.status))
                else:
                    s = np.asarray(r.scores)
                    if s != refA[i % 32] and s != refB[i % 32]:
                        with bad_lock:
                            bad.append(("torn", i % 32, float(s)))
                i += 1

        threads = [threading.Thread(target=client, args=(t,))
                   for t in range(4)]
        killed = corrupted = ok_swaps = 0
        with faults.active(plan):
            for t in threads:
                t.start()
            for k in range(8):
                target = pb if k % 2 == 0 else pa
                try:
                    srv.swap("m", target)
                    ok_swaps += 1
                except faults.SimulatedKill:
                    killed += 1  # mid-stage death: nothing flipped
                except ModelLoadError:
                    corrupted += 1
                    h = srv.health()
                    if h["status"] != "degraded":
                        failures.append(
                            "healthz not degraded after a corrupt "
                            f"staged artifact (got {h['status']})")
                # old generation must still answer, bitwise
                s, _ = srv.predict_direct("m", Xq)
                if not (np.array_equal(s, refA)
                        or np.array_equal(s, refB)):
                    failures.append(
                        f"scores after swap attempt {k} match neither "
                        "generation")
            stop.set()
            for t in threads:
                t.join(10.0)
        if bad:
            failures.append(f"client anomalies under chaos: {bad[:5]} "
                            f"({len(bad)} total)")
        if killed == 0:
            failures.append("the kill rule never fired")
        if corrupted == 0:
            failures.append("the corrupt rule never produced a "
                            "classified load failure")
        # recovery: a clean swap clears the degraded flag
        faults.deactivate()
        srv.swap("m", pb)
        h = srv.health()
        if h["status"] != "ok":
            failures.append(f"clean swap did not recover health: {h}")
        gen = h["swap"]["m"]["generation"]
    if failures:
        for f in failures:
            print(f"SWAP CHAOS SMOKE FAILED: {f}")
        return 1
    print(f"swap chaos smoke ok: {ok_swaps} swaps flipped, {killed} "
          f"killed mid-stage, {corrupted} corrupt stages rolled back, "
          f"0 torn/lost responses, final generation {gen}, health ok")
    return 0


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__)
        return 0 if argv else 2
    cmd, rest = argv[0], argv[1:]
    if cmd == "kill-resume-smoke":
        return _kill_resume_smoke()
    if cmd == "swap-chaos-smoke":
        return _swap_chaos_smoke()
    if cmd == "validate":
        if len(rest) != 1:
            print("usage: python -m tpusvm.faults validate PLAN.json")
            return 2
        return _validate(rest[0])
    print(f"unknown command {cmd!r}; see --help")
    return 2


if __name__ == "__main__":
    sys.exit(main())
