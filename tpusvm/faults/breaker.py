"""Per-model circuit breaker for degraded-mode serving.

The failure mode this prevents: a model whose scoring path is broken
(bad weights hot-swapped in, a device wedged, persistent injected
faults) keeps absorbing queue slots and kernel time, and every caller
pays a full scoring attempt to learn the model is down. The breaker is
the classic three-state machine:

  CLOSED     healthy; failures are counted, successes reset the count.
             `threshold` CONSECUTIVE failures trip it.
  OPEN       every allow() is refused instantly (callers get
             ServeStatus.UNAVAILABLE without paying kernel time) until
             `cooldown_s` has elapsed.
  HALF_OPEN  after the cooldown, exactly ONE probe is admitted; its
             success closes the breaker (recovery), its failure reopens
             it for another cooldown.

The clock is injectable so recovery tests are deterministic. Trips and
recoveries are emitted through faults.emit and counted by the listener
callback (serve wires it to the model's metrics).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from tpusvm.faults.injection import emit

CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"


class BreakerOpenError(RuntimeError):
    """Raised by guarded paths when the breaker refuses the call."""

    def __init__(self, name: str):
        self.name = name
        super().__init__(
            f"circuit breaker for {name!r} is open (scoring is failing); "
            "retry after the cooldown"
        )


class CircuitBreaker:
    """Consecutive-failure trip + half-open probe recovery."""

    def __init__(self, threshold: int = 5, cooldown_s: float = 30.0,
                 name: str = "", clock: Callable[[], float] = time.monotonic,
                 listener: Optional[Callable[[str], None]] = None):
        if threshold < 1:
            raise ValueError(f"threshold must be >= 1, got {threshold}")
        self.threshold = threshold
        self.cooldown_s = cooldown_s
        self.name = name
        self._clock = clock
        self._listener = listener
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive = 0
        self._opened_at = 0.0
        self._probe_out = False
        self.trips = 0
        self.recoveries = 0

    @property
    def state(self) -> str:
        with self._lock:
            return self._effective_state()

    def _effective_state(self) -> str:
        # lazy OPEN -> HALF_OPEN transition on inspection: no timer thread
        if self._state == OPEN and \
                self._clock() - self._opened_at >= self.cooldown_s:
            return HALF_OPEN
        return self._state

    def _notify(self, event: str) -> None:
        if self._listener is not None:
            self._listener(event)
        emit(f"breaker.{event}", model=self.name, state=self._state,
             consecutive=self._consecutive)

    def allow(self) -> bool:
        """May a call proceed right now? HALF_OPEN admits one probe."""
        with self._lock:
            st = self._effective_state()
            if st == CLOSED:
                return True
            if st == OPEN:
                return False
            # HALF_OPEN: one probe in flight at a time
            if self._state == OPEN:
                self._state = HALF_OPEN
                self._probe_out = False
                self._notify("half_open")
            if self._probe_out:
                return False
            self._probe_out = True
            return True

    def record_success(self) -> None:
        with self._lock:
            self._consecutive = 0
            if self._state == HALF_OPEN:
                self._state = CLOSED
                self._probe_out = False
                self.recoveries += 1
                self._notify("recovered")

    def record_failure(self) -> None:
        with self._lock:
            self._consecutive += 1
            if self._state == HALF_OPEN:
                # failed probe: back to a full cooldown
                self._state = OPEN
                self._opened_at = self._clock()
                self._probe_out = False
                self._notify("reopened")
            elif self._state == CLOSED \
                    and self._consecutive >= self.threshold:
                self._state = OPEN
                self._opened_at = self._clock()
                self.trips += 1
                self._notify("tripped")

    def describe(self) -> dict:
        with self._lock:
            return {
                "state": self._effective_state(),
                "consecutive_failures": self._consecutive,
                "trips": self.trips,
                "recoveries": self.recoveries,
                "threshold": self.threshold,
                "cooldown_s": self.cooldown_s,
            }

    # ------------------------------------------------------- persistence
    def snapshot(self) -> dict:
        """Raw machine state for crash-safe supervisors (the autopilot's
        `autopilot_state.json`): unlike describe(), this captures the
        STORED state (not the lazily-advanced effective one) plus the
        open timestamp in the breaker's own clock domain, so restore()
        replays cooldown arithmetic exactly."""
        with self._lock:
            return {
                "state": self._state,
                "consecutive": self._consecutive,
                "opened_at": self._opened_at,
                "trips": self.trips,
                "recoveries": self.recoveries,
            }

    def restore(self, snap: dict) -> None:
        """Reinstall a snapshot() — the resumed supervisor's breaker
        makes the same allow() decisions the killed one would have (the
        caller supplies the same injectable clock domain)."""
        state = snap["state"]
        if state not in (CLOSED, OPEN, HALF_OPEN):
            raise ValueError(f"unknown breaker state {state!r}")
        with self._lock:
            self._state = state
            self._consecutive = int(snap["consecutive"])
            self._opened_at = float(snap["opened_at"])
            self._probe_out = False
            self.trips = int(snap.get("trips", 0))
            self.recoveries = int(snap.get("recoveries", 0))
