"""Structured run logging.

The reference's logging is bare cout/cerr prints guarded by `if (rank==0)`
(per-round headers `=== Round k ===`, merged SV counts, convergence
messages, b at 15 dp — mpi_svm_main2.cpp:441, 610, 681-744; SURVEY.md §5.5),
captured to text files by SLURM `--output`. RunLogger is the framework
replacement: the same human-readable summary lines for parity checking,
plus machine-readable JSONL event records for tooling.

Process-0 semantics: JAX SPMD programs run one Python process per host;
`RunLogger(primary=jax.process_index() == 0)` reproduces the rank-0-only
printing pattern on multi-host meshes. Single-host runs are always primary.
"""

from __future__ import annotations

import json
import sys
import time
from typing import Any, IO, Optional


class RunLogger:
    """Human-readable log lines + optional JSONL event stream.

    >>> log = RunLogger()
    >>> log.info("n = %d, n_features = %d", 100, 4)
    n = 100, n_features = 4
    >>> log.event("round", round=1, sv_count=10)   # silent without jsonl_path
    """

    def __init__(
        self,
        stream: Optional[IO[str]] = None,
        jsonl_path: Optional[str] = None,
        primary: bool = True,
    ) -> None:
        # None = "current sys.stdout", resolved per call so stream
        # redirection (pytest capsys, contextlib.redirect_stdout) works
        self.stream = stream
        self.primary = primary
        self._jsonl: Optional[IO[str]] = (
            open(jsonl_path, "a") if (jsonl_path and primary) else None
        )

    def info(self, fmt: str, *args: Any) -> None:
        if self.primary:
            out = self.stream if self.stream is not None else sys.stdout
            print(fmt % args if args else fmt, file=out, flush=True)

    def round_header(self, rnd: int) -> None:
        """The reference's per-round banner (mpi_svm_main2.cpp:441)."""
        self.info("=== Round %d ===", rnd)

    def event(self, kind: str, **fields: Any) -> None:
        """Append one structured JSONL record (timestamped)."""
        if self._jsonl is None:
            return
        rec = {"ts": time.time(), "event": kind, **fields}
        self._jsonl.write(json.dumps(rec, default=_jsonable) + "\n")
        self._jsonl.flush()

    def close(self) -> None:
        if self._jsonl is not None:
            self._jsonl.close()
            self._jsonl = None

    def __enter__(self) -> "RunLogger":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()


def _jsonable(x: Any) -> Any:
    import numpy as np

    if isinstance(x, np.ndarray):
        return x.tolist()
    if isinstance(x, (np.integer, np.floating, np.bool_)):
        return x.item()
    raise TypeError(f"not JSON-serialisable: {type(x)}")
