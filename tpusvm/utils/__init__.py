from tpusvm.utils.durable import fsync_replace
from tpusvm.utils.logging import RunLogger
from tpusvm.utils.timing import PhaseTimer, trace

__all__ = ["PhaseTimer", "RunLogger", "fsync_replace", "trace"]
