from tpusvm.utils.logging import RunLogger
from tpusvm.utils.timing import PhaseTimer, trace

__all__ = ["PhaseTimer", "RunLogger", "trace"]
