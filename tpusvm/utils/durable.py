"""The one sanctioned spelling of a flushed atomic commit.

``os.replace`` alone makes a write atomic with respect to READERS — they
see old bytes or new bytes, never a torn file — but not with respect to
POWER LOSS: most filesystems may commit the rename to the journal before
the staged file's data blocks reach disk, so a crash can leave the final
name pointing at a hollow or truncated file. The journal/commit hot
paths (ingest journals, append commits, solver checkpoints, autopilot
state) promise kill-safety, which needs the full sequence:

    flush stream -> fsync(staged fd) -> os.replace(staged, final)

`fsync_replace` is that sequence; the JXD306 lint rule names it as the
fix, and the dura static model recognises the call as an
already-fsynced rename-commit.

Directory-entry durability (fsync of the parent dir) is deliberately
NOT included: the recovery journals tolerate a vanished rename (it
replays), what they cannot tolerate is a *committed name with torn
bytes* — exactly what the data fsync closes.
"""

from __future__ import annotations

import os


def fsync_replace(tmp_path: str, final_path: str) -> None:
    """Atomically commit `tmp_path` over `final_path`, durably.

    The staged file's bytes are fsync'd before the rename so the commit
    can never outrun its data. Callers write + flush + close the staged
    file first; this reopens it read-only to fsync, which keeps the
    helper droppable into every existing `os.replace(tmp, path)` site
    without restructuring the write above it."""
    fd = os.open(tmp_path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)
    # tpusvm: durable-by=callers stage the temp beside its target; the helper's opaque params carry no directory to compare
    os.replace(tmp_path, final_path)
