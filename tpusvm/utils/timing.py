"""Phase timing and profiling hooks (compatibility shim).

The reference's observability is three coarse wall-clock phase timers
printed at the end of every run — training / prediction / total —
implemented three different ways (chrono in main3.cpp:335-414, cudaEvent
triplet in gpu_svm_main3.cu:516-694, chrono-on-rank-0 in
mpi_svm_main2.cpp:408-409, 771-782; SURVEY.md §5.1). PhaseTimer is the
single framework replacement; since the unified-telemetry round it lives
in tpusvm.obs.trace as a span adapter (each phase also lands in an
attached JSONL Tracer), and this module re-exports it so every
`from tpusvm.utils import PhaseTimer` import keeps working.

trace() wraps jax.profiler for real kernel-level traces — the idiomatic
deep-profiling path the reference lacks entirely (`--profile/--xprof` on
the CLI).
"""

from __future__ import annotations

import contextlib
from typing import Iterator, Optional

from tpusvm.obs.trace import PhaseTimer  # noqa: F401 — re-export

__all__ = ["PhaseTimer", "trace"]


@contextlib.contextmanager
def trace(log_dir: Optional[str]) -> Iterator[None]:
    """Optional jax.profiler trace: `with trace("/tmp/trace"):` profiles the
    body; `with trace(None):` is a no-op. View with TensorBoard/Perfetto."""
    if log_dir is None:
        yield
        return
    import jax

    with jax.profiler.trace(log_dir):
        yield
