"""Phase timing and profiling hooks.

The reference's observability is three coarse wall-clock phase timers printed
at the end of every run — training / prediction / total — implemented three
different ways (chrono in main3.cpp:335-414, cudaEvent triplet in
gpu_svm_main3.cu:516-694, chrono-on-rank-0 in mpi_svm_main2.cpp:408-409,
771-782; SURVEY.md §5.1). PhaseTimer is the single framework replacement:
named phases measured with perf_counter, reported in the same
three-line contract, plus arbitrary extra phases (data loading, scaling,
compilation) the reference never measured.

On-device timing caveat: JAX dispatch is asynchronous, so a phase that ends
while device work is still in flight under-reports. Callers must close each
phase only after host materialisation of the phase's result (np.asarray),
which is how the solvers already synchronise (models/svm.py fit). On this
environment's TPU runtime `jax.block_until_ready` is not a reliable barrier
(see .claude/skills/verify/SKILL.md) — materialisation is.

trace() wraps jax.profiler for real kernel-level traces — the idiomatic
deep-profiling path the reference lacks entirely.
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict, Iterator, Optional


class PhaseTimer:
    """Accumulating named phase timer.

    >>> t = PhaseTimer()
    >>> with t.phase("train"):
    ...     pass
    >>> t["train"] >= 0
    True

    Phases accumulate across repeated entries (the cascade enters "train"
    once per round). `report()` returns the human-readable summary lines in
    the reference's output contract (SURVEY.md Appendix A: three phase
    timings), listing phases in first-entry order and ending with the total.
    """

    def __init__(self) -> None:
        self._acc: Dict[str, float] = {}
        self._t0 = time.perf_counter()

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        start = time.perf_counter()
        try:
            yield
        finally:
            self._acc[name] = self._acc.get(name, 0.0) + (
                time.perf_counter() - start
            )

    def add(self, name: str, seconds: float) -> None:
        """Accumulate an externally-measured duration (e.g. a per-round time
        already captured by cascade_fit's history)."""
        self._acc[name] = self._acc.get(name, 0.0) + seconds

    def __getitem__(self, name: str) -> float:
        return self._acc[name]

    def __contains__(self, name: str) -> bool:
        return name in self._acc

    @property
    def total(self) -> float:
        """Wall-clock since construction (the reference's 'elapsed time')."""
        return time.perf_counter() - self._t0

    def asdict(self) -> Dict[str, float]:
        d = dict(self._acc)
        d["total"] = self.total
        return d

    def report(self) -> str:
        lines = [
            f"{name} time: {secs:.3f} s" for name, secs in self._acc.items()
        ]
        lines.append(f"elapsed time: {self.total:.3f} s")
        return "\n".join(lines)


@contextlib.contextmanager
def trace(log_dir: Optional[str]) -> Iterator[None]:
    """Optional jax.profiler trace: `with trace("/tmp/trace"):` profiles the
    body; `with trace(None):` is a no-op. View with TensorBoard/Perfetto."""
    if log_dir is None:
        yield
        return
    import jax

    with jax.profiler.trace(log_dir):
        yield
