"""Per-tenant (Y, valid) views over ONE shared sharded dataset.

The platform's data model: the append-grown corpus (stream/append.py)
is the single shared X; a tenant never owns rows, it owns a VIEW — a
label-column mapping (its positive class against the rest) and
optionally a row subset. Views are pure functions of (raw labels,
TenantRecord), so the fleet launch materialises per-tenant state as
two cheap arrays per tenant — a (n,) ±1 label vector and an optional
(n,) valid mask — while X is loaded, scaled and device-resident exactly
once for the whole bucket.

Contract with the solver: a row OUTSIDE the tenant's subset is masked
invalid, never given a zero label on a live row (a live y=0 belongs to
neither Keerthi index set and would silently freeze — the
fleet/batch.py packing validation enforces this, the view construction
makes it true by construction).
"""

from __future__ import annotations

import zlib
from typing import Optional, Tuple

import numpy as np

from tpusvm.tenants.store import TenantRecord

__all__ = ["tenant_labels", "view_fingerprint"]


def tenant_labels(labels: np.ndarray, rec: TenantRecord,
                  ) -> Tuple[np.ndarray, Optional[np.ndarray]]:
    """Materialise one tenant's view: (Y ±1 int32, valid mask or None).

    Y is +1 on rows carrying the tenant's positive label, -1 elsewhere;
    the optional row-subset view (`row_mod`/`row_ofs`) comes back as a
    boolean valid mask (None = all rows live). Raises if the view is
    degenerate — a tenant whose live rows are all one class has no
    binary problem to solve, and silently training it would deadlock
    the working-set selection."""
    labels = np.asarray(labels)
    n = labels.shape[0]
    Y = np.where(labels == rec.positive_label, 1, -1).astype(np.int32)
    valid = None
    if rec.row_mod is not None:
        valid = (np.arange(n) % rec.row_mod) == rec.row_ofs
    live = Y if valid is None else Y[valid]
    if live.size == 0 or (live == 1).all() or (live == -1).all():
        raise ValueError(
            f"tenant {rec.tenant_id!r}: degenerate view — its "
            f"{live.size} live rows carry "
            f"{'only' if live.size else 'no'} "
            f"{'positive' if live.size and (live == 1).all() else 'negative'} "
            f"labels (positive_label={rec.positive_label}, "
            f"row_mod={rec.row_mod}, row_ofs={rec.row_ofs})"
        )
    return Y, valid


def view_fingerprint(Y: np.ndarray,
                     valid: Optional[np.ndarray]) -> int:
    """CRC32 of a materialised view's bytes — the chaos gates' "no
    tenant lost rows" currency: a tenant's view over the recovered
    dataset must fingerprint identically to the uninterrupted
    control's."""
    crc = zlib.crc32(np.ascontiguousarray(Y).tobytes())
    if valid is not None:
        crc = zlib.crc32(np.ascontiguousarray(valid).tobytes(), crc)
    return crc & 0xFFFFFFFF
