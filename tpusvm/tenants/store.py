"""Crash-safe tenant registry + the coalesced refresh's fleet checkpoint.

One file holds the WHOLE platform's decision memory: every tenant's
view spec, hyperparameters, deployed artifact, drift counters and
refresh provenance, plus the supervisor's stage machine and the
in-flight coalesced launch. The autopilot/state.py discipline applies
unchanged — format-versioned, CRC-fingerprinted canonical JSON, atomic
temp + fsync_replace write behind the ``tenants.store`` injection
point — so a torn or hand-edited store is a named error, never a
silently wrong fleet of decisions, and a killed supervisor resumes with
exactly the record set the last durable commit left.

The second durable artifact here is the coalesced refresh's
fleet-segment checkpoint: the batched outer-loop carry (every lane's
alpha/f/counters, solver/blocked._OuterState with a leading problem
axis) snapshotted between fleet_smo_solve segments. It rides the
solver-checkpoint format (np.savez + fingerprint + atomic replace) so
a supervisor SIGKILLed mid-fleet-refresh re-enters the SAME batched
solve from the last segment boundary — bit-identical per lane, the
checkpointed_blocked_solve argument applied to the whole fleet. Both
writes share the one injection point: a kill rule on ``tenants.store``
dies exactly where a real crash would, before the rename.

Stage machine (persisted in the store, validated both ways):

  "idle"      no coalesced refresh in flight;
  "fitting"   a launch is in flight — `inflight` names the EXACT tenant
              set, row count and checkpoint path, so a resumed
              supervisor finishes THAT launch (not a re-planned one,
              which later appends could have changed);
  "swapping"  every in-flight artifact is saved (atomically); only the
              staggered swap roll-out remains.
"""

from __future__ import annotations

import dataclasses
import json
import os
import zlib
from typing import Dict, Optional

import numpy as np

from tpusvm import faults
from tpusvm.utils.durable import fsync_replace

STORE_VERSION = 1

STAGES = ("idle", "fitting", "swapping")

FLEET_CKPT_VERSION = 1


@dataclasses.dataclass
class TenantRecord:
    """One tenant's slice of the platform: its view over the shared
    corpus, its hyperparameters, its deployed artifact and its drift
    state. `positive_label` defines the label-column view (Y = +1 on
    rows carrying it, -1 elsewhere); `row_mod`/`row_ofs` optionally
    restrict the tenant to the row subset ``idx % row_mod == row_ofs``
    (threaded through the fleet's per-problem valid mask — X itself is
    never copied per tenant)."""

    tenant_id: str
    positive_label: int
    C: float
    gamma: float
    row_mod: Optional[int] = None
    row_ofs: int = 0
    model_path: str = ""              # current warm-start donor artifact
    generation: int = 0
    rows_at_refresh: int = 0
    last_refresh_t: float = 0.0       # supervisor clock domain
    consecutive_triggered: int = 0
    refreshes: int = 0
    failures: int = 0

    def to_json(self) -> dict:
        return {f.name: getattr(self, f.name)
                for f in dataclasses.fields(self)}

    def validate(self) -> None:
        if not self.tenant_id:
            raise ValueError("tenant_id must be non-empty")
        if not (np.isfinite(self.C) and self.C > 0):
            raise ValueError(
                f"tenant {self.tenant_id!r}: C must be positive finite, "
                f"got {self.C!r}")
        if not (np.isfinite(self.gamma) and self.gamma > 0):
            raise ValueError(
                f"tenant {self.tenant_id!r}: gamma must be positive "
                f"finite, got {self.gamma!r}")
        if self.row_mod is not None:
            if self.row_mod < 1:
                raise ValueError(
                    f"tenant {self.tenant_id!r}: row_mod must be >= 1, "
                    f"got {self.row_mod}")
            if not (0 <= self.row_ofs < self.row_mod):
                raise ValueError(
                    f"tenant {self.tenant_id!r}: row_ofs {self.row_ofs} "
                    f"outside [0, row_mod={self.row_mod})")


@dataclasses.dataclass
class TenantsState:
    """The platform's whole decision memory: tenant records keyed by id
    plus the supervisor's stage machine and fleet-level counters."""

    seed: int
    tick: int = 0
    stage: str = "idle"
    # the in-flight coalesced refresh: {"tenant_ids": [...], "plan":
    # CoalescePlan.to_json(), "stage_rows": int, "outcomes": {...}} —
    # persisted BEFORE the launch starts so a resumed supervisor
    # finishes the same launch over the same row prefix
    inflight: Optional[dict] = None
    generation: int = 0               # completed coalesced refresh rounds
    refreshes: int = 0                # per-tenant refreshes landed, total
    failures: int = 0
    breaker: Optional[dict] = None    # faults.CircuitBreaker.snapshot()
    tenants: Dict[str, TenantRecord] = dataclasses.field(
        default_factory=dict)

    def to_json(self) -> dict:
        out = {
            "store_version": STORE_VERSION,
            **{f.name: getattr(self, f.name)
               for f in dataclasses.fields(self) if f.name != "tenants"},
            "tenants": {tid: rec.to_json()
                        for tid, rec in sorted(self.tenants.items())},
        }
        return out


def _canonical(payload: dict) -> bytes:
    return json.dumps(payload, sort_keys=True,
                      separators=(",", ":")).encode()


def save_store(path: str, state: TenantsState) -> None:
    """Atomic write (temp + fsync_replace) with a CRC32 fingerprint of
    the canonical payload — a kill mid-write leaves the previous
    store."""
    if state.stage not in STAGES:
        raise ValueError(f"unknown tenants stage {state.stage!r}")
    for rec in state.tenants.values():
        rec.validate()
    payload = state.to_json()
    obj = {"crc32": zlib.crc32(_canonical(payload)) & 0xFFFFFFFF,
           **payload}
    faults.point("tenants.store", path=path, stage=state.stage,
                 tenants=len(state.tenants))
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f, indent=1, sort_keys=True)
        f.write("\n")
    fsync_replace(tmp, path)


def is_tenant_store(path: str) -> bool:
    """Cheap sniff for `tpusvm info`: a JSON file carrying
    store_version."""
    if not os.path.isfile(path):
        return False
    try:
        with open(path) as f:
            head = json.load(f)
    except (OSError, ValueError):
        return False
    return isinstance(head, dict) and "store_version" in head \
        and "tenants" in head


def load_store(path: str) -> TenantsState:
    """Version gate + CRC verification first; corruption and version
    skew are named errors, not wrong replays."""
    with open(path) as f:
        try:
            obj = json.load(f)
        except json.JSONDecodeError as e:
            raise ValueError(
                f"tenant store {path!r} is not valid JSON ({e}); "
                "delete it to start fresh"
            ) from e
    if "store_version" not in obj:
        raise ValueError(
            f"{path!r} is not a tpusvm tenant store (no store_version)"
        )
    v = obj["store_version"]
    if v != STORE_VERSION:
        raise ValueError(
            f"unsupported tenant store version {v!r} in {path!r} "
            f"(this build reads version {STORE_VERSION})"
        )
    crc = obj.pop("crc32", None)
    want = zlib.crc32(_canonical(obj)) & 0xFFFFFFFF
    if crc != want:
        raise ValueError(
            f"tenant store {path!r} fails its CRC fingerprint "
            f"(stored {crc!r}, computed {want}) — torn write or manual "
            "edit; delete it to start fresh"
        )
    obj.pop("store_version")
    raw_tenants = obj.pop("tenants", {})
    fields = {f.name for f in dataclasses.fields(TenantsState)} - {
        "tenants"}
    unknown = set(obj) - fields
    if unknown:
        raise ValueError(
            f"tenant store {path!r} carries unknown fields "
            f"{sorted(unknown)} (written by a newer tpusvm?)"
        )
    rec_fields = {f.name for f in dataclasses.fields(TenantRecord)}
    tenants = {}
    for tid, rec in raw_tenants.items():
        bad = set(rec) - rec_fields
        if bad:
            raise ValueError(
                f"tenant store {path!r}: tenant {tid!r} carries unknown "
                f"fields {sorted(bad)} (written by a newer tpusvm?)"
            )
        tenants[tid] = TenantRecord(**rec)
        tenants[tid].validate()
        if tenants[tid].tenant_id != tid:
            raise ValueError(
                f"tenant store {path!r}: key {tid!r} names record "
                f"{tenants[tid].tenant_id!r}"
            )
    st = TenantsState(tenants=tenants, **obj)
    if st.stage not in STAGES:
        raise ValueError(
            f"tenant store {path!r} names unknown stage {st.stage!r}"
        )
    if st.stage != "idle" and not st.inflight:
        raise ValueError(
            f"tenant store {path!r}: stage {st.stage!r} with no "
            "inflight launch record"
        )
    return st


# ------------------------------------------------- fleet checkpointing
def save_fleet_checkpoint(path: str, states, fingerprint: dict) -> None:
    """Atomically persist a BATCHED outer-loop carry + its fingerprint.

    `states` is the solver/blocked._OuterState the fleet launch returned
    with return_state=True — every field carries the leading problem
    axis; numpy round-trips the float arrays bit-exact, which is the
    whole resume-bit-identical argument. The injection point fires
    before the write, so a kill rule dies with the PREVIOUS checkpoint
    (or none) intact — exactly a real mid-refresh crash."""
    faults.point("tenants.store", path=path, stage="fleet_checkpoint")
    tmp = path + ".tmp"
    arrays = {f: np.asarray(getattr(states, f))
              for f in type(states)._fields}
    np.savez(tmp, fleet_ckpt_version=FLEET_CKPT_VERSION,
             fingerprint=json.dumps(fingerprint, sort_keys=True),
             **arrays)
    fsync_replace(tmp + ".npz", path)  # np.savez appends .npz


def load_fleet_checkpoint(path: str, fingerprint: dict):
    """Load a batched carry; refuse (with the differing fields named)
    any checkpoint that does not belong to this exact launch."""
    from tpusvm.solver.blocked import _OuterState

    with np.load(path, allow_pickle=False) as z:
        if "fleet_ckpt_version" not in z.files:
            raise ValueError(
                f"{path!r} is not a tpusvm fleet checkpoint "
                "(no fleet_ckpt_version)"
            )
        v = int(z["fleet_ckpt_version"])
        if v != FLEET_CKPT_VERSION:
            raise ValueError(
                f"unsupported fleet checkpoint version {v} (this build "
                f"reads version {FLEET_CKPT_VERSION})"
            )
        saved = json.loads(str(z["fingerprint"]))
        want = json.loads(json.dumps(fingerprint, sort_keys=True))
        if saved != want:
            diff = sorted(
                k for k in set(saved) | set(want)
                if saved.get(k) != want.get(k)
            )
            raise ValueError(
                "fleet checkpoint does not belong to this launch "
                f"(differing fields: {diff}); it was written for "
                f"{ {k: saved.get(k) for k in diff} }, this launch has "
                f"{ {k: want.get(k) for k in diff} }"
            )
        return _OuterState(*(np.asarray(z[f])
                             for f in _OuterState._fields))
