"""The refresh coalescer: drifted tenants -> power-of-two fleet launches.

The platform's core economics. N drifted tenants refreshed the PR 15
way cost N solo supervisor loops — N dataset loads, N cold jit caches,
N sequential solves. Coalescing instead packs the currently-drifted set
into power-of-two ``fleet_smo_solve`` launches: X is loaded, scaled and
device-resident ONCE for the whole bucket, per-tenant (C, gamma) enter
as arrays (one compiled program regardless of hyperparameter spread),
and each tenant's warm seed — ``tune.warm.deployed_seed`` of its deployed
artifact — rides the fleet's alpha0 lane, so a mixed warm/cold bucket
is exact (fleet/batch.py).

Coalescing rules (``coalesce_drifted``):

  * tenants group by their launch STATIC key — kernel family/shape,
    eps/tau/max_iter, sv_tol, scale policy. Everything jit-static is
    necessarily shared by one program (fleet/batch.py per-problem
    statics validation); per-problem axes are exactly
    (y, valid, alpha0, C, gamma);
  * a group of >= ``min_fleet`` tenants becomes one fleet launch,
    bucket-padded to the next power of two with inert zero-label lanes;
  * singletons and odd-corpus tenants (a static key nobody shares, or
    an approximate-family artifact whose refresh is rejected by the
    dual-seed contract) fall back to solo ``refresh_fit`` — the PR 15
    path, checkpointed per tenant.

Crash safety (``checkpointed_fleet_refresh``): the launch runs in
``checkpoint_every``-outer-round segments (the fleet's pause_at /
resume_states surface), and after each segment the BATCHED carry is
written durably (tenants/store.py:save_fleet_checkpoint, fingerprinted
against this exact launch). A supervisor SIGKILLed mid-refresh re-enters
the same batched solve from the last segment boundary — per-lane
BIT-IDENTICAL to an uninterrupted run, the checkpointed_blocked_solve
argument applied fleet-wide (each lane's carry is independent state;
segmenting is exact).

Parity discipline (tests/test_tenants.py): a tenant refreshed in a
fleet bucket matches its solo refresh_fit control on exact SV-ID set,
status and accuracy, with b/alpha inside the cross-engine band; bitwise
equality is reserved for same-program lane invariance (the PR 12
cross-program fma note).
"""

from __future__ import annotations

import dataclasses
import os
import zlib
from typing import Dict, List, Optional, Sequence

import numpy as np

from tpusvm.status import Status
from tpusvm.tenants.store import (
    TenantRecord,
    load_fleet_checkpoint,
    save_fleet_checkpoint,
)
from tpusvm.tenants.views import tenant_labels

__all__ = [
    "CoalescePlan",
    "coalesce_drifted",
    "checkpointed_fleet_refresh",
    "provision_tenants",
    "refresh_drifted",
]


@dataclasses.dataclass
class CoalescePlan:
    """The coalescer's decision, JSON-able so the supervisor can persist
    it in the store's inflight record and a resumed run finishes the
    SAME launches (not a re-planned set that later appends could have
    changed)."""

    launches: List[List[str]]   # each: tenant ids of one fleet launch
    solos: List[str]            # tenant ids refreshed solo

    def to_json(self) -> dict:
        return {"launches": [list(ids) for ids in self.launches],
                "solos": list(self.solos)}

    @classmethod
    def from_json(cls, obj: dict) -> "CoalescePlan":
        return cls(launches=[list(ids) for ids in obj["launches"]],
                   solos=list(obj["solos"]))


def _static_key(rec: TenantRecord, base) -> tuple:
    """The launch-compatibility key: everything one fleet program must
    share. Per-problem axes (y, valid, alpha0, C, gamma) are excluded
    by construction."""
    cfg = base.config
    return (cfg.kernel, cfg.degree, cfg.coef0, cfg.eps, cfg.tau,
            cfg.max_iter, cfg.sv_tol, bool(base.scale))


def coalesce_drifted(records: Sequence[TenantRecord], donors: Dict,
                     min_fleet: int = 2) -> CoalescePlan:
    """Group the drifted set by launch static key; groups of
    >= min_fleet become fleet launches (sorted tenant order inside each
    — deterministic lane assignment), the rest go solo. `donors` maps
    tenant_id -> its loaded donor estimator (the supervisor's cache)."""
    from tpusvm import kernels

    groups: Dict[tuple, List[str]] = {}
    solos: List[str] = []
    for rec in sorted(records, key=lambda r: r.tenant_id):
        base = donors[rec.tenant_id]
        if kernels.is_approx(base.config.kernel):
            # odd corpus: the approximate primal regime has no dual
            # warm seed and refresh_fit rejects it by name — surfaced
            # as a solo attempt so the failure is a counted per-tenant
            # outcome, not a dead launch
            solos.append(rec.tenant_id)
            continue
        groups.setdefault(_static_key(rec, base), []).append(
            rec.tenant_id)
    launches = []
    for key in sorted(groups, key=repr):
        ids = groups[key]
        if len(ids) >= max(2, min_fleet):
            launches.append(ids)
        else:
            solos.extend(ids)
    return CoalescePlan(launches=launches, solos=sorted(solos))


def _launch_fingerprint(Xs, batch, tenant_ids, opts) -> dict:
    """JSON-able identity of one coalesced launch: corpus bytes, packed
    per-problem axes, hyperparameter vectors, statics. A checkpoint
    from any other launch is refused with the differing fields named."""
    Xs = np.asarray(Xs)
    fp = {
        "n": int(Xs.shape[0]),
        "d": int(Xs.shape[1]),
        "x_dtype": str(Xs.dtype),
        "x_crc32": zlib.crc32(np.ascontiguousarray(Xs).tobytes()),
        "ys_crc32": zlib.crc32(
            np.ascontiguousarray(batch.Ys).tobytes()),
        "valids_crc32": (
            None if batch.valids is None
            else zlib.crc32(np.ascontiguousarray(batch.valids).tobytes())),
        "alpha0s_crc32": (
            None if batch.alpha0s is None
            else zlib.crc32(
                np.ascontiguousarray(batch.alpha0s).tobytes())),
        "Cs": [float(c) for c in batch.Cs],
        "gammas": [float(g) for g in batch.gammas],
        "bucket": int(batch.bucket),
        "tenant_ids": list(tenant_ids),
    }
    for k in sorted(opts):
        v = opts[k]
        fp[k] = str(v) if not isinstance(
            v, (int, float, str, bool, type(None))) else v
    return fp


def checkpointed_fleet_refresh(Xs, batch, *, checkpoint_path: str,
                               checkpoint_every: int = 64,
                               resume: bool = False,
                               fingerprint: dict,
                               dtype=None,
                               **opts):
    """One coalesced launch to convergence, durably checkpointed.

    Runs the packed FleetBatch through fleet_smo_solve in
    `checkpoint_every`-outer-round segments; after each segment the
    batched carry is persisted atomically. resume=True restarts from
    the file when it exists (missing file = fresh start); the
    fingerprint refuses a checkpoint from any other launch. Returns the
    batched SMOResult.

    The checkpoint is NOT deleted here — deliberately. Deleting at
    convergence would open a crash window between solve termination and
    the per-tenant artifact saves where a kill forces a full re-fit.
    The file stays until the CALLER has durably committed everything
    derived from it (the supervisor deletes after its swapping-stage
    store commit); re-entering a completed checkpoint is cheap — the
    carry has no RUNNING lane, so the solve returns it immediately.

    The segment schedule is an invariant of (checkpoint_every): an
    interrupted run resumes at the SAME boundaries an uninterrupted run
    pauses at, so the trajectory — and every lane's final alpha bytes —
    is bit-identical (numpy round-trips the carry exactly)."""
    import jax.numpy as jnp

    from tpusvm.fleet.solve import fleet_smo_solve
    from tpusvm.solver.blocked import _OuterState

    if checkpoint_every < 1:
        raise ValueError(
            f"checkpoint_every must be >= 1, got {checkpoint_every}")
    state = None
    if resume and os.path.exists(checkpoint_path):
        state = load_fleet_checkpoint(checkpoint_path, fingerprint)

    Xd = jnp.asarray(Xs, dtype if dtype is not None else jnp.float32)
    Ys_d = jnp.asarray(batch.Ys)
    valids_d = None if batch.valids is None else jnp.asarray(batch.valids)
    adt = opts.get("accum_dtype")
    alpha0s_d = (None if batch.alpha0s is None
                 else jnp.asarray(batch.alpha0s,
                                  adt if adt is not None else Xd.dtype))
    if batch.alpha0s is not None:
        opts.setdefault("warm_start", True)
    Cs_d = jnp.asarray(batch.Cs)
    gs_d = jnp.asarray(batch.gammas)

    while True:
        if state is None:
            start = 0
        else:
            running = np.asarray(state.status) == int(Status.RUNNING)
            start = int(np.max(np.asarray(state.n_outer)[running])) \
                if running.any() else int(np.max(np.asarray(state.n_outer)))
        res, st = fleet_smo_solve(
            Xd, Ys_d, valids_d, alpha0s_d, Cs=Cs_d, gammas=gs_d,
            resume_states=state,
            pause_at=jnp.int32(start + checkpoint_every),
            return_state=True, **opts,
        )
        # one host sync materialises the whole batched carry (the
        # checkpoint payload); segments make this a per-K-rounds cost
        state = _OuterState(*(np.asarray(x) for x in st))
        if not (np.asarray(state.status) == int(Status.RUNNING)).any():
            return res
        save_fleet_checkpoint(checkpoint_path, state, fingerprint)


def _lane_model(cfg, scale, scaler, Xs, Y, lane):
    """One tenant's refreshed estimator from its fleet lane result —
    the _fit_scaled SV-extraction recipe applied to a lane (the solo
    refresh's exact postprocessing, so a coalesced artifact has the
    same shape, provenance fields and scaled-SV layout a solo one
    has)."""
    import jax.numpy as jnp

    from tpusvm.models import BinarySVC
    from tpusvm.oracle.smo import get_sv_indices

    model = BinarySVC(config=cfg, dtype=jnp.float32, scale=scale,
                      accum_dtype="auto", solver="blocked")
    model.scaler_ = scaler if scale else None
    alpha = np.asarray(lane.alpha)
    sv = get_sv_indices(alpha, cfg.sv_tol)
    model.sv_X_ = np.asarray(Xs)[sv]
    model.sv_Y_ = np.asarray(Y)[sv].astype(np.int32)
    model.sv_alpha_ = alpha[sv]
    model.sv_ids_ = sv.astype(np.int32)
    model.b_ = float(lane.b)
    model.b_high_ = float(lane.b_high)
    model.b_low_ = float(lane.b_low)
    model.n_iter_ = int(lane.n_iter)
    model.status_ = Status(int(lane.status))
    return model


def refresh_drifted(X, labels, records: Sequence[TenantRecord], *,
                    artifacts_dir: str,
                    checkpoint_dir: Optional[str] = None,
                    checkpoint_every: int = 64,
                    resume: bool = False,
                    warm: bool = True,
                    plan: Optional[CoalescePlan] = None,
                    min_fleet: int = 2,
                    solver_opts: Optional[dict] = None,
                    log=None) -> dict:
    """Refresh the drifted tenant set: coalesced fleet launches + solo
    fallbacks, every artifact saved atomically.

    X/labels are the SHARED corpus arrays (one load for every tenant).
    Returns {tenant_id: {"out_path", "status", "n_iter", "sv_count",
    "mode", "error"?}} — a failed tenant is a counted outcome carrying
    its error, never a dead launch (the other lanes' artifacts still
    land). `plan` pins a previously-persisted coalescing decision
    (resume path); omitted, the plan is computed here."""
    import jax.numpy as jnp

    from tpusvm.config import resolve_accum_dtype
    from tpusvm.data.scaler import MinMaxScaler
    from tpusvm.fleet.batch import pack_problems
    from tpusvm.fleet.results import lane_result
    from tpusvm.models import BinarySVC
    from tpusvm.serve.refresh import refresh_fit
    from tpusvm.tune.warm import deployed_seed

    say = log or (lambda msg: None)
    X = np.asarray(X)
    labels = np.asarray(labels)
    n = int(X.shape[0])
    ckdir = checkpoint_dir or artifacts_dir
    os.makedirs(artifacts_dir, exist_ok=True)
    os.makedirs(ckdir, exist_ok=True)
    opts = dict(solver_opts or {})
    by_id = {r.tenant_id: r for r in records}
    donors = {r.tenant_id: BinarySVC.load(r.model_path)
              for r in records}
    if plan is None:
        plan = coalesce_drifted(records, donors, min_fleet=min_fleet)
    outcomes: dict = {}

    # scale ONCE: every scale=True tenant shares X, so the fitted
    # min/max — and therefore the scaled matrix — is identical to what
    # each solo fit would compute (BinarySVC._scale_fit)
    scaler = MinMaxScaler().fit(X)
    Xs_scaled = scaler.transform(X)

    for ids in plan.launches:
        recs = [by_id[t] for t in ids]
        bases = [donors[t] for t in ids]
        base0 = bases[0]
        cfg0 = base0.config
        Xs = Xs_scaled if base0.scale else X
        Ys, valids, seeds, Cs, gammas = [], [], [], [], []
        for rec, base in zip(recs, bases):
            Y, valid = tenant_labels(labels, rec)
            Ys.append(Y)
            valids.append(valid)
            a0 = None
            if warm:
                a0 = deployed_seed(base.sv_ids_, base.sv_alpha_, n,
                                   Y, rec.C)
                if not a0.any():
                    a0 = None
            seeds.append(a0)
            Cs.append(rec.C)
            gammas.append(rec.gamma)
        launch_opts = dict(
            eps=cfg0.eps, tau=cfg0.tau, max_iter=cfg0.max_iter,
            kernel=cfg0.kernel, degree=cfg0.degree, coef0=cfg0.coef0,
            accum_dtype=resolve_accum_dtype("auto"),
            **opts,
        )
        batch = pack_problems(
            Ys, Cs, gammas,
            valids=None if all(v is None for v in valids) else valids,
            alpha0s=None if all(a is None for a in seeds) else seeds,
        )
        ck = os.path.join(ckdir, "fleet_%s.ck.npz"
                          % zlib.crc32(",".join(ids).encode()))
        fp = _launch_fingerprint(Xs, batch, ids, launch_opts)
        say(f"tenants: fleet launch of {len(ids)} tenants "
            f"(bucket {batch.bucket}, warm "
            f"{sum(a is not None for a in seeds)}/{len(ids)})")
        res = checkpointed_fleet_refresh(
            Xs, batch, checkpoint_path=ck,
            checkpoint_every=checkpoint_every, resume=resume,
            fingerprint=fp, dtype=jnp.float32, **launch_opts,
        )
        for i, (rec, base) in enumerate(zip(recs, bases)):
            lane = lane_result(res, i)
            cfg = dataclasses.replace(base.config, C=rec.C,
                                      gamma=rec.gamma)
            out_path = os.path.join(artifacts_dir,
                                    rec.tenant_id + ".npz")
            try:
                model = _lane_model(cfg, base.scale, scaler, Xs,
                                    Ys[i], lane)
                model.save(out_path)
                outcomes[rec.tenant_id] = {
                    "out_path": out_path, "mode": "fleet",
                    "checkpoint": ck,
                    "status": model.status_,
                    "n_iter": model.n_iter_,
                    "sv_count": int(model.sv_ids_.shape[0]),
                }
            except Exception as e:  # noqa: BLE001 — one tenant's save
                # failure must not drop its bucket-mates' artifacts
                outcomes[rec.tenant_id] = {
                    "out_path": out_path, "mode": "fleet",
                    "checkpoint": ck,
                    "status": None, "n_iter": 0, "sv_count": 0,
                    "error": f"{type(e).__name__}: {e}",
                }

    for tid in plan.solos:
        rec = by_id[tid]
        out_path = os.path.join(artifacts_dir, tid + ".npz")
        try:
            Y, valid = tenant_labels(labels, rec)
            solo_opts = dict(opts)
            if valid is not None:
                solo_opts["valid"] = valid
            ck = os.path.join(ckdir, tid + ".solo_ck.npz")
            model = refresh_fit(
                rec.model_path, X, Y, out_path=out_path,
                checkpoint_path=ck, checkpoint_every=checkpoint_every,
                resume=resume, warm=warm, solver_opts=solo_opts,
            )
            outcomes[tid] = {
                "out_path": out_path, "mode": "solo",
                "status": model.status_,
                "n_iter": model.n_iter_,
                "sv_count": int(model.sv_ids_.shape[0]),
            }
        except Exception as e:  # noqa: BLE001 — counted per-tenant
            # outcome; the rest of the drifted set still refreshes
            outcomes[tid] = {
                "out_path": out_path, "mode": "solo",
                "status": None, "n_iter": 0, "sv_count": 0,
                "error": f"{type(e).__name__}: {e}",
            }
            say(f"tenants: solo refresh of {tid} FAILED "
                f"({type(e).__name__}: {e})")
    return outcomes


def provision_tenants(X, labels, records: Sequence[TenantRecord], *,
                      artifacts_dir: str, scale: bool = True,
                      config=None, solver_opts: Optional[dict] = None,
                      log=None) -> dict:
    """Cold-start a whole tenant fleet in ONE coalesced launch.

    The bootstrap analogue of refresh_drifted: every record's initial
    artifact is fitted from scratch in a single power-of-two
    fleet_smo_solve over the shared corpus (X scaled once, per-tenant
    C/gamma as per-problem axes) and saved atomically as
    artifacts_dir/<tenant_id>.npz; each record's model_path is filled
    in. `config` is the shared static template (kernel/eps/tau/...;
    default SVMConfig()); C and gamma always come from the records.
    Returns the refresh_drifted-shaped outcomes dict."""
    import jax.numpy as jnp

    from tpusvm.config import SVMConfig, resolve_accum_dtype
    from tpusvm.data.scaler import MinMaxScaler
    from tpusvm.fleet.batch import pack_problems
    from tpusvm.fleet.results import lane_result

    say = log or (lambda msg: None)
    X = np.asarray(X)
    labels = np.asarray(labels)
    os.makedirs(artifacts_dir, exist_ok=True)
    cfg0 = config if config is not None else SVMConfig()
    opts = dict(solver_opts or {})
    scaler = MinMaxScaler().fit(X) if scale else None
    Xs = scaler.transform(X) if scale else X

    Ys, valids, Cs, gammas = [], [], [], []
    for rec in records:
        rec.validate()
        Y, valid = tenant_labels(labels, rec)
        Ys.append(Y)
        valids.append(valid)
        Cs.append(rec.C)
        gammas.append(rec.gamma)
    launch_opts = dict(
        eps=cfg0.eps, tau=cfg0.tau, max_iter=cfg0.max_iter,
        kernel=cfg0.kernel, degree=cfg0.degree, coef0=cfg0.coef0,
        accum_dtype=resolve_accum_dtype("auto"),
        **opts,
    )
    batch = pack_problems(
        Ys, Cs, gammas,
        valids=None if all(v is None for v in valids) else valids,
    )
    say(f"tenants: provisioning {len(records)} tenants in one fleet "
        f"launch (bucket {batch.bucket})")
    from tpusvm.fleet.solve import fleet_smo_solve

    res = fleet_smo_solve(
        jnp.asarray(Xs, jnp.float32), jnp.asarray(batch.Ys),
        None if batch.valids is None else jnp.asarray(batch.valids),
        None, Cs=jnp.asarray(batch.Cs), gammas=jnp.asarray(batch.gammas),
        **launch_opts,
    )
    outcomes: dict = {}
    for i, rec in enumerate(records):
        cfg = dataclasses.replace(cfg0, C=rec.C, gamma=rec.gamma)
        out_path = os.path.join(artifacts_dir, rec.tenant_id + ".npz")
        model = _lane_model(cfg, scale, scaler, Xs, Ys[i],
                            lane_result(res, i))
        model.save(out_path)
        rec.model_path = out_path
        rec.rows_at_refresh = int(X.shape[0])
        outcomes[rec.tenant_id] = {
            "out_path": out_path, "mode": "fleet",
            "status": model.status_, "n_iter": model.n_iter_,
            "sv_count": int(model.sv_ids_.shape[0]),
        }
    return outcomes
