"""Multi-tenant platform tier: thousands of closed loops, one corpus.

A production platform serves thousands of SMALL tenant models, not one
big one. Every piece of that scenario already exists in isolation —
fleet trains B SVMs sharing X as one XLA program (tpusvm.fleet),
autopilot closes the loop for one model (tpusvm.autopilot), serve
hot-swaps atomically (tpusvm.serve) — and this package is their fusion:

  store.py     the tenant registry: per-tenant label/row-subset view
               spec, (C, gamma), deployed artifact, drift state — one
               crash-safe, format-versioned, CRC-fingerprinted file
               (the autopilot/state.py discipline at fleet scale), plus
               the coalesced refresh's durable fleet-segment checkpoint
  views.py     per-tenant (Y, valid) views over ONE shared append-grown
               sharded dataset — X is loaded and scaled exactly once
               per tick, never per tenant
  coalesce.py  the refresh coalescer: the currently-drifted tenant set
               becomes power-of-two fleet_smo_solve launches (per-tenant
               warm seeds via tune.warm.deployed_seed in the alpha0
               lane), checkpointed at segment boundaries so a killed
               supervisor resumes the SAME fleet solve bit-identically;
               singleton / odd-corpus tenants fall back to solo
               refresh_fit
  loop.py      the supervisor: per-tenant drift detection off the
               autopilot detectors, hysteresis + refresh breaker,
               staggered swap roll-out through the serve registry

CLI: `tpusvm tenants [--smoke]`. Chaos gate:
`python -m tpusvm.faults tenant-chaos-smoke` (kill mid-fleet-refresh +
corrupt one tenant artifact under client load — no tenant loses rows,
re-fits from scratch, or serves a torn generation).
"""

from tpusvm.tenants.coalesce import (
    CoalescePlan,
    checkpointed_fleet_refresh,
    coalesce_drifted,
    provision_tenants,
    refresh_drifted,
)
from tpusvm.tenants.loop import TenantsConfig, TenantsSupervisor
from tpusvm.tenants.store import (
    STORE_VERSION,
    TenantRecord,
    TenantsState,
    is_tenant_store,
    load_fleet_checkpoint,
    load_store,
    save_fleet_checkpoint,
    save_store,
)
from tpusvm.tenants.views import tenant_labels, view_fingerprint

__all__ = [
    "STORE_VERSION",
    "TenantRecord",
    "TenantsState",
    "TenantsConfig",
    "TenantsSupervisor",
    "CoalescePlan",
    "checkpointed_fleet_refresh",
    "coalesce_drifted",
    "provision_tenants",
    "refresh_drifted",
    "is_tenant_store",
    "load_fleet_checkpoint",
    "load_store",
    "save_fleet_checkpoint",
    "save_store",
    "tenant_labels",
    "view_fingerprint",
]
