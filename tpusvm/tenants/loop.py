"""The multi-tenant supervisor: thousands of closed loops, one tick.

The autopilot (autopilot/loop.py) closes the loop for ONE model; this
supervisor closes it for a fleet of tenants over one shared corpus and
amortises everything that the one-daemon-per-model deployment pays N
times: the dataset is opened once per tick, drift is evaluated per
tenant off the SAME manifest snapshot, and the currently-drifted set is
refreshed through the coalescer (tenants/coalesce.py) — power-of-two
fleet launches with per-tenant warm seeds instead of N sequential solo
refits.

Per-tenant loop semantics survive the coalescing:

  * each tenant keeps its own drift state — rows_at_refresh, hysteresis
    counter, cooldown window — in its TenantRecord, and its detectors
    run with a per-tenant seed offset (crc32 of the tenant id) so
    jittered thresholds de-synchronise across the fleet instead of
    herding every tenant into the same tick;
  * the per-tenant score-shift detector is structurally off here (a
    tenant record carries no score baseline); growth, feature-range and
    staleness drive the decision;
  * one refresh CircuitBreaker guards the whole refresh stage: a
    poisoned corpus fails every lane at once, and the breaker degrades
    the fleet to watch-only instead of hot-looping thousands of refits.

Crash safety: the store (tenants/store.py) persists the stage machine
and the EXACT in-flight plan (launch lane order, solo set, row count)
BEFORE the launch starts. A supervisor SIGKILLed mid-fleet-refresh
resumes with stage="fitting", replays the persisted plan over the
persisted row prefix (later appends cannot change what the refit
consumes), and the fleet checkpoint makes the resumed solve
bit-identical. Swaps roll out staggered (`stagger_s`) through the serve
registry so a thousand-tenant generation flip is a ramp, not a
stampede; a tenant whose artifact failed keeps serving its previous
generation and stays drift-armed.

Fault points: `tenants.tick` (per-tick entry), `tenants.store` (every
durable commit). Chaos-gated by
`python -m tpusvm.faults tenant-chaos-smoke`.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
import zlib
from typing import Dict, List, Optional

from tpusvm import faults
from tpusvm.autopilot.drift import DriftThresholds, evaluate
from tpusvm.status import TenantsStatus
from tpusvm.tenants.coalesce import CoalescePlan, refresh_drifted
from tpusvm.tenants.store import (
    TenantRecord,
    TenantsState,
    load_store,
    save_store,
)


def _registry():
    from tpusvm.obs.registry import default_registry

    return default_registry()


def _tenant_seed(base_seed: int, tenant_id: str) -> int:
    """Per-tenant detector seed: base + a crc32-derived offset, so
    jitter_frac de-synchronises thresholds ACROSS tenants while every
    individual tenant's decisions stay a pure replayable function of
    (its seed, its tick)."""
    return int(base_seed) + (zlib.crc32(tenant_id.encode()) & 0xFFFF)


@dataclasses.dataclass
class TenantsConfig:
    """The supervisor's knobs. `store_path` is the one durable file
    (registry + stage machine); `artifacts_dir` is where refreshed
    per-tenant models land (atomic replace, named <tenant_id>.npz —
    point a `serve --watch` directory at it for zero-coordination
    deploys)."""

    data_dir: str
    store_path: Optional[str] = None        # default: data_dir/tenants_store.json
    artifacts_dir: Optional[str] = None     # default: data_dir/tenant_models
    interval_s: float = 30.0
    thresholds: DriftThresholds = dataclasses.field(
        default_factory=DriftThresholds)
    hysteresis: int = 1
    cooldown_s: float = 0.0
    warm: bool = True
    checkpoint_every: int = 64
    min_fleet: int = 2
    stagger_s: float = 0.0                  # delay between tenant swaps
    breaker_threshold: int = 3
    breaker_cooldown_s: float = 60.0
    seed: int = 0
    solver_opts: Optional[dict] = None

    def resolved(self) -> "TenantsConfig":
        if self.hysteresis < 1:
            raise ValueError(
                f"hysteresis must be >= 1, got {self.hysteresis}")
        if self.checkpoint_every < 1:
            raise ValueError(
                f"checkpoint_every must be >= 1, got "
                f"{self.checkpoint_every}")
        if self.min_fleet < 2:
            raise ValueError(
                f"min_fleet must be >= 2 (a fleet of one is a solo "
                f"refresh), got {self.min_fleet}")
        return dataclasses.replace(
            self,
            store_path=(self.store_path
                        or os.path.join(self.data_dir,
                                        "tenants_store.json")),
            artifacts_dir=(self.artifacts_dir
                           or os.path.join(self.data_dir,
                                           "tenant_models")),
        )


class TenantsSupervisor:
    """The fleet tick loop. Deploy targets, pick exactly one:

      server=    an in-process serve.Server — each tenant is hosted
                 under its tenant_id and swapped via Server.swap;
      swap_url=  a running `tpusvm serve` frontend (POST /admin/swap
                 per tenant);
      neither    artifact-drop mode — refreshed .npz files land in
                 artifacts_dir and a `serve --watch` loop (one
                 os.scandir sweep per tick, PR-sized for thousands of
                 entries) picks them up.

    `clock` and `sleep` are injectable so tests pin cooldown/stagger
    arithmetic; the clock domain must persist across resumes (the
    default wall clock does)."""

    def __init__(self, config: TenantsConfig, server=None,
                 swap_url: Optional[str] = None,
                 resume: bool = False,
                 clock=time.time,
                 sleep=time.sleep,
                 log_fn=print):
        self.cfg = config.resolved()
        self.server = server
        self.swap_url = swap_url
        self._clock = clock
        self._sleep = sleep
        self.log = log_fn or (lambda msg: None)
        self._io_retry = faults.Retry(faults.DEFAULT_IO_POLICY,
                                      op="tenants.tick")
        # the store write is atomic, hence idempotent, hence retryable:
        # an injected/real transient on the commit edge is absorbed here
        # (a kill still dies pre-rename with the previous store intact)
        self._store_retry = faults.Retry(faults.DEFAULT_IO_POLICY,
                                         op="tenants.store")
        self._scaler_cache: Dict[str, object] = {}
        os.makedirs(self.cfg.artifacts_dir, exist_ok=True)
        if resume and os.path.exists(self.cfg.store_path):
            self.state = load_store(self.cfg.store_path)
            if self.state.seed != self.cfg.seed:
                raise ValueError(
                    f"tenant store {self.cfg.store_path!r} was written "
                    f"with seed {self.state.seed}, this run passes "
                    f"{self.cfg.seed}; per-tenant decisions would not "
                    "replay — resume with the original seed"
                )
        else:
            self.state = TenantsState(seed=self.cfg.seed)
        self.breaker = faults.CircuitBreaker(
            threshold=self.cfg.breaker_threshold,
            cooldown_s=self.cfg.breaker_cooldown_s,
            name="tenants.refresh",
            clock=clock,
        )
        if self.state.breaker is not None:
            self.breaker.restore(self.state.breaker)
        # persist immediately: a supervisor killed before its first tick
        # must resume with the registry it was launched with, not
        # re-register against data that grew in between
        self._save()

    # ------------------------------------------------------------ registry
    def register(self, rec: TenantRecord) -> None:
        """Admit a tenant: validated, baselined at the current corpus
        state, durably committed. `rec.model_path` must name its
        deployed (donor) artifact — the approximate families are
        rejected here, at admission, because their refresh has no dual
        warm seed (serve/refresh.py)."""
        from tpusvm import kernels
        from tpusvm.models import BinarySVC

        rec.validate()
        if rec.tenant_id in self.state.tenants:
            raise ValueError(
                f"tenant {rec.tenant_id!r} is already registered")
        base = BinarySVC.load(rec.model_path)
        if kernels.is_approx(base.config.kernel):
            raise ValueError(
                f"tenant {rec.tenant_id!r}: deployed artifact uses the "
                f"approximate {base.config.kernel!r} family — its "
                "refresh has no dual warm seed and refresh_fit rejects "
                "it; register an exact-family artifact"
            )
        if rec.last_refresh_t == 0.0:
            rec.last_refresh_t = float(self._clock())
        self.state.tenants[rec.tenant_id] = rec
        self._save()

    # ------------------------------------------------------------ helpers
    def _open_dataset(self):
        from tpusvm.stream import open_dataset

        return self._io_retry(open_dataset, self.cfg.data_dir)

    def _fitted_range(self, model_path: str):
        cached = self._scaler_cache.get(model_path)
        if cached is not None:
            return cached
        from tpusvm.models.serialization import load_model

        st, _ = load_model(model_path)
        rng = (None if "scaler_min" not in st
               else (st["scaler_min"], st["scaler_max"]))
        self._scaler_cache[model_path] = rng
        return rng

    def _save(self) -> None:
        self.state.breaker = self.breaker.snapshot()
        self._store_retry(save_store, self.cfg.store_path, self.state)

    # --------------------------------------------------------------- tick
    def tick(self) -> dict:
        """One fleet step; returns {"status": TenantsStatus, "drifted":
        [...], "tick": int, ...}. Refresh failures come back as status
        codes (breaker-counted), never exceptions; SimulatedKill and
        tick-edge I/O propagate to run()'s retry-next-tick policy."""
        st = self.state
        st.tick += 1
        faults.point("tenants.tick", tick=st.tick,
                     tenants=len(st.tenants))
        reg = _registry()
        reg.counter("tenants.ticks").inc()
        dataset = self._open_dataset()
        now = float(self._clock())
        thresholds = self.cfg.thresholds
        if thresholds.score is not None:
            # tenant records carry no score baseline; the detector would
            # never see data — disable it structurally rather than let
            # it report a permanent 0
            thresholds = dataclasses.replace(thresholds, score=None)

        if st.stage != "idle":
            # a persisted in-flight launch outranks fresh drift
            # decisions: finish THAT launch first (bit-identically, via
            # its checkpoint), then the next tick re-evaluates
            if not self.breaker.allow():
                reg.counter("tenants.refreshes_suppressed",
                            reason="breaker").inc()
                self._save()
                return {"status": TenantsStatus.SUPPRESSED_BREAKER,
                        "tick": st.tick, "drifted": [],
                        "rows": dataset.n_rows,
                        "generation": st.generation}
            status = self._refresh(dataset, resume_pending=True)
            self._save()
            return {"status": status, "tick": st.tick,
                    "drifted": list(st.inflight["tenant_ids"])
                    if st.inflight else [],
                    "rows": dataset.n_rows, "generation": st.generation}

        drifted: List[str] = []
        armed = 0
        for tid in sorted(st.tenants):
            rec = st.tenants[tid]
            rng = self._fitted_range(rec.model_path) \
                if rec.model_path else None
            t = thresholds
            if rng is None and t.feature is not None:
                t = dataclasses.replace(t, feature=None)
            report = evaluate(
                manifest=dataset.manifest,
                fitted_min=rng[0] if rng else None,
                fitted_max=rng[1] if rng else None,
                rows_at_refresh=rec.rows_at_refresh,
                since_refresh_s=max(0.0, now - rec.last_refresh_t),
                score_baseline=None,
                score_current=None,
                thresholds=t,
                seed=_tenant_seed(st.seed, tid),
                tick=st.tick,
            )
            rec.consecutive_triggered = (
                rec.consecutive_triggered + 1 if report.decision else 0)
            if not report.decision:
                continue
            if rec.consecutive_triggered < self.cfg.hysteresis:
                armed += 1
            elif now < rec.last_refresh_t + self.cfg.cooldown_s \
                    and rec.refreshes > 0:
                reg.counter("tenants.refreshes_suppressed",
                            reason="cooldown").inc()
            else:
                drifted.append(tid)
        reg.gauge("tenants.drifted").set(float(len(drifted)))
        reg.gauge("tenants.breaker_open").set(
            0.0 if self.breaker.state == "closed" else 1.0)
        faults.emit("tenants.drift", tick=st.tick, drifted=drifted,
                    armed=armed, tenants=len(st.tenants))

        status = TenantsStatus.WATCHING
        if drifted:
            if not self.breaker.allow():
                status = TenantsStatus.SUPPRESSED_BREAKER
                reg.counter("tenants.refreshes_suppressed",
                            reason="breaker").inc()
            else:
                status = self._refresh(dataset, drifted=drifted)
        elif armed:
            status = TenantsStatus.TRIGGERED_HYSTERESIS
            reg.counter("tenants.refreshes_suppressed",
                        reason="hysteresis").inc()
        self._save()
        return {"status": status, "tick": st.tick, "drifted": drifted,
                "rows": dataset.n_rows, "generation": st.generation}

    # ------------------------------------------------------------ refresh
    def _refresh(self, dataset, drifted: Optional[List[str]] = None,
                 resume_pending: bool = False) -> TenantsStatus:
        st, cfg = self.state, self.cfg
        reg = _registry()
        try:
            if resume_pending:
                # finish the persisted launch: same plan, same row
                # prefix — later appends cannot change what the
                # resumed refit consumes
                plan = CoalescePlan.from_json(st.inflight["plan"])
                rows = int(st.inflight["stage_rows"])
            else:
                from tpusvm.models import BinarySVC
                from tpusvm.tenants.coalesce import coalesce_drifted

                donors = {tid: BinarySVC.load(
                    st.tenants[tid].model_path) for tid in drifted}
                plan = coalesce_drifted(
                    [st.tenants[tid] for tid in drifted], donors,
                    min_fleet=cfg.min_fleet)
                rows = int(dataset.n_rows)
                st.stage = "fitting"
                st.inflight = {
                    "tenant_ids": sorted(drifted),
                    "plan": plan.to_json(),
                    "stage_rows": rows,
                }
                self._save()
            ids = list(st.inflight["tenant_ids"])
            if st.stage != "swapping":
                X, labels = dataset.load_arrays()
                X, labels = X[:rows], labels[:rows]
                outcomes = refresh_drifted(
                    X, labels, [st.tenants[tid] for tid in ids],
                    artifacts_dir=cfg.artifacts_dir,
                    checkpoint_every=cfg.checkpoint_every,
                    resume=True, warm=cfg.warm, plan=plan,
                    min_fleet=cfg.min_fleet,
                    solver_opts=cfg.solver_opts, log=self.log,
                )
                st.inflight["outcomes"] = {
                    tid: {"out_path": o["out_path"],
                          "ok": "error" not in o,
                          "n_iter": int(o["n_iter"]),
                          "checkpoint": o.get("checkpoint"),
                          "error": o.get("error")}
                    for tid, o in outcomes.items()
                }
                st.stage = "swapping"
                self._save()
            # the swapping-stage commit above is the point after which
            # the fleet checkpoints are dead weight: every artifact
            # derived from them is durably on disk and named by the
            # store. Deleting EARLIER (at solve convergence) would open
            # a crash window where a kill forces a full re-fit.
            cks = {o.get("checkpoint")
                   for o in st.inflight.get("outcomes", {}).values()}
            for ck in cks:
                if ck and os.path.exists(ck):
                    os.remove(ck)
        except faults.SimulatedKill:
            raise
        except Exception as e:  # noqa: BLE001 — a failed stage is a
            # counted, breaker-fed outcome; previous generations keep
            # serving and the in-flight checkpoint resumes next tick
            self.breaker.record_failure()
            st.failures += 1
            reg.counter("tenants.refreshes_failed", kind="error").inc()
            self.log(f"tenants: refresh stage FAILED "
                     f"({type(e).__name__}: {e}); previous generations "
                     "keep serving")
            faults.emit("tenants.refresh_failed", tick=st.tick,
                        error=f"{type(e).__name__}: {e}")
            self._save()
            return TenantsStatus.REFRESH_FAILED

        # swap roll-out: staggered, per-tenant, failure-isolated
        landed, failed = [], []
        now = float(self._clock())
        outcomes = st.inflight.get("outcomes", {})
        first = True
        for tid in sorted(outcomes):
            o = outcomes[tid]
            rec = st.tenants[tid]
            if not o["ok"]:
                failed.append(tid)
                rec.failures += 1
                continue
            if not first and cfg.stagger_s > 0:
                self._sleep(cfg.stagger_s)
            first = False
            try:
                self._swap(tid, o["out_path"])
            except faults.SimulatedKill:
                raise
            except Exception as e:  # noqa: BLE001 — one tenant's swap
                # failure must not block its bucket-mates' roll-out
                failed.append(tid)
                rec.failures += 1
                self.log(f"tenants: swap of {tid} FAILED "
                         f"({type(e).__name__}: {e}); its previous "
                         "generation keeps serving")
                continue
            rec.model_path = o["out_path"]
            rec.generation += 1
            rec.refreshes += 1
            rec.rows_at_refresh = int(st.inflight["stage_rows"])
            rec.last_refresh_t = now
            rec.consecutive_triggered = 0   # failed tenants stay armed
            self._scaler_cache.pop(o["out_path"], None)
            landed.append(tid)

        st.stage = "idle"
        st.inflight = None
        st.generation += 1
        st.refreshes += len(landed)
        st.failures += len(failed)
        reg.counter("tenants.refreshes_landed").inc(len(landed))
        reg.counter("tenants.refreshes_failed",
                    kind="tenant").inc(len(failed))
        reg.gauge("tenants.generation").set(float(st.generation))
        self._save()
        if landed:
            self.breaker.record_success()
        else:
            self.breaker.record_failure()
        self.log(f"tenants: generation {st.generation} — "
                 f"{len(landed)} refreshed, {len(failed)} failed")
        if not landed:
            return TenantsStatus.REFRESH_FAILED
        return TenantsStatus.PARTIAL if failed else \
            TenantsStatus.REFRESHED

    def _swap(self, tenant_id: str, out_path: str) -> None:
        if self.server is not None:
            self.server.swap(tenant_id, out_path)
        elif self.swap_url:
            from tpusvm.serve.refresh import swap_via_http

            swap_via_http(self.swap_url, tenant_id,
                          os.path.abspath(out_path))
        # else: artifact-drop mode — the atomic save already published
        # the artifact for a `serve --watch` poller

    # ---------------------------------------------------------------- run
    def run(self, max_ticks: Optional[int] = None,
            stop: Optional[threading.Event] = None) -> dict:
        """Tick until stopped (or max_ticks). Unexpected tick errors are
        logged and retried next tick — at fleet scale the supervisor is
        the LAST component allowed to die quietly."""
        stop = stop or threading.Event()
        done = 0
        last = {}
        while not stop.is_set():
            try:
                last = self.tick()
                self.log(f"tenants tick {last['tick']}: "
                         f"{last['status'].name} "
                         f"({len(last['drifted'])} drifted, rows "
                         f"{last['rows']}, generation "
                         f"{last['generation']})")
            except (faults.SimulatedKill, KeyboardInterrupt):
                raise
            except Exception as e:  # noqa: BLE001 — keep supervising
                self.log(f"tenants: tick error "
                         f"{type(e).__name__}: {e}")
                last = {"status": TenantsStatus.REFRESH_FAILED,
                        "error": str(e)}
            done += 1
            if max_ticks is not None and done >= max_ticks:
                break
            stop.wait(self.cfg.interval_s)
        return {"ticks": done, "generation": self.state.generation,
                "refreshes": self.state.refreshes,
                "failures": self.state.failures,
                "tenants": len(self.state.tenants), "last": last}
