"""Streaming primal trainer: linear SVM over mapped features, shard by shard.

The in-memory approx path runs the unchanged dual SMO solver over Phi(X)
(dispatch routes rff/nystrom through the linear primal fast path), which
still needs every mapped row resident. THIS module is the out-of-core
complement — the piece that actually opens the 100M-row class: a
deterministic mini-batch Pegasos solve (Shalev-Shwartz et al. 2007) of

    min_w  lambda/2 ||w||^2 + mean_i hinge(y_i (w.Phi(x_i) - b))

consuming (Phi(X_shard), Y_shard) blocks straight off a ShardReader whose
prefetch hook applies the map per shard — the (n, D) mapped matrix never
exists anywhere; peak residency stays the reader's prefetch_depth + 1
bound plus one fixed batch.

lambda = 1/(C*n) makes the regularised objective the standard C-form SVM,
so the (C, gamma) knobs keep their exact-path meaning. Determinism: shard
order, batch boundaries and the step schedule are pure functions of
(seed, epoch), so a rerun is bit-identical. Termination is an explicit
objective plateau — the epoch-mean regularised objective must improve by
less than `tol` RELATIVE (floored at 0.05 absolute scale, so near-zero
objectives do not turn the relative test into noise) between consecutive
epochs: the 1/t SGD tail makes per-epoch relative improvement shrink
monotonically, so this is the diminishing-returns stop, not a KKT
certificate (the exact path's Keerthi gap has no analogue here) —
reported as CONVERGED; exhausting `epochs` without a plateau reports
MAX_ITER, mirroring the solvers' honest-status discipline.

The result embeds in the standard model layout with NO new serving code:
f(x) = w.Phi(x) - bias is exactly a one-support-vector linear model over
mapped features (sv_X = w[None, :], alpha*y = [1], b = bias), so
serialization v4, the serve bucket cache, cascades of consumers of
decision_function, and `tpusvm predict` all work unchanged.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from tpusvm.ops.rbf import matmul_p
from tpusvm.status import Status


@functools.partial(jax.jit, donate_argnums=())
def _primal_batch_step(w, b, Z, y, mask, lam, t_ex, t_b):
    """One mini-batch subgradient step; returns (w, b, batch objective).

    Z is a FIXED-shape (batch, D) block (short tails are zero-padded with
    mask=False — inert rows), y in {+1,-1} as float, mask the valid-row
    mask. eta = 1/(lambda * t_ex) with t_ex the EXAMPLES-seen counter —
    Pegasos's schedule is derived for per-sample steps, so a mini-batch
    step must advance t by its batch size: counting BATCHES leaves eta
    ~batch-times too hot for the whole run, and at the large-n regime
    this solver exists for (lambda = 1/(C*n) tiny, few batch steps per
    epoch) the iterates just bounce on the projection sphere — measured
    chance accuracy at 512k rows with batch counting vs 0.92 with
    example counting, identical elsewhere. The projection onto the
    ||w|| <= 1/sqrt(lambda) ball is optional in the paper but NOT here:
    eta_1 = C*n/batch is still enormous, and the projection is what
    keeps the f32 iterates bounded. The unregularised bias takes its
    own bounded Robbins-Monro step (eta_b = 1/sqrt(t_b), the batch
    counter): the Pegasos rate applied to b is chaotic (measured: the
    f32 trajectory diverges to chance accuracy where f64 happens to
    recover), while the feature spaces are rich enough that b only
    fine-tunes the threshold. The returned objective is the batch's
    regularised value BEFORE the step (what the epoch plateau check
    averages).
    """
    k = jnp.maximum(mask.sum(), 1.0)
    # every contraction routes through the precision-safe home
    # (ops.rbf.matmul_p at the trust tier): a bare matmul's dot_general
    # carries jax's DEFAULT precision — raw single-pass bf16 on TPU MXUs
    margin = y * (matmul_p(Z, w) - b)
    hinge = jnp.where(mask, jnp.maximum(0.0, 1.0 - margin), 0.0)
    w_sq = matmul_p(w, w)
    obj = 0.5 * lam * w_sq + hinge.sum() / k
    viol = jnp.where(mask & (margin < 1.0), y, 0.0)
    eta = 1.0 / (lam * t_ex)
    w = (1.0 - eta * lam) * w + (eta / k) * matmul_p(viol, Z)
    radius = 1.0 / jnp.sqrt(jnp.asarray(lam, w.dtype))
    norm = jnp.sqrt(jnp.maximum(matmul_p(w, w), 1e-30))
    w = w * jnp.minimum(1.0, radius / norm)
    b = b - (1.0 / jnp.sqrt(t_b)) * viol.sum() / k
    return w, b, obj


@dataclasses.dataclass
class PrimalResult:
    w: np.ndarray          # (D,) primal weights in mapped space
    bias: float            # f(x) = w.Phi(x) - bias
    status: Status         # CONVERGED (objective plateau) | MAX_ITER
    epochs_run: int
    n_steps: int           # mini-batch updates taken
    n_rows: int            # rows consumed per epoch
    objective: float       # final epoch-mean regularised objective


def streaming_primal_fit(
    make_reader: Callable[[int], "object"],
    dim: int,
    *,
    C: float,
    n_rows: int,
    batch: int = 1024,
    epochs: int = 64,
    tol: float = 0.05,
    dtype=np.float32,
) -> PrimalResult:
    """Fit the streaming primal SVM.

    make_reader(epoch) must return a FRESH single-pass iterable of
    (Z, Y) blocks of mapped features (a stream.ShardReader with the map
    installed as its transform hook — same seed, same shard traversal).
    dim is the mapped width D; n_rows the manifest row count (sets
    lambda = 1/(C*n) and the step counter's scale).
    """
    if n_rows < 1:
        raise ValueError(f"n_rows must be >= 1, got {n_rows}")
    if batch < 1:
        raise ValueError(f"batch must be >= 1, got {batch}")
    lam = 1.0 / (float(C) * float(n_rows))
    w = jnp.zeros((dim,), dtype)
    b = jnp.zeros((), dtype)
    t = 1          # batch counter (the bias step's clock)
    t_ex = 0       # examples-seen counter (the Pegasos clock)
    prev_obj = None
    status = Status.MAX_ITER
    epochs_run = 0
    n_steps = 0
    for epoch in range(epochs):
        reader = make_reader(epoch)
        obj_sum, obj_batches, rows_seen = 0.0, 0, 0
        for Zb, Yb in reader.batches(batch):
            m = len(Zb)
            rows_seen += m
            if m < batch:
                # fixed-shape pad so the step compiles exactly once
                Zp = np.zeros((batch, dim), dtype)
                Zp[:m] = Zb
                yp = np.zeros((batch,), dtype)
                yp[:m] = Yb
                mask = np.zeros((batch,), bool)
                mask[:m] = True
            else:
                Zp, yp, mask = Zb, np.asarray(Yb, dtype), np.ones(
                    (batch,), bool)
            t_ex += m
            w, b, obj = _primal_batch_step(
                w, b, jnp.asarray(Zp, dtype), jnp.asarray(yp, dtype),
                jnp.asarray(mask), lam, float(t_ex), float(t))
            obj_sum += float(obj)
            obj_batches += 1
            t += 1
            n_steps += 1
        epochs_run += 1
        if rows_seen != n_rows:
            raise ValueError(
                f"streaming primal epoch {epoch} consumed {rows_seen} "
                f"rows, manifest says {n_rows} (reader misconfigured?)"
            )
        epoch_obj = obj_sum / max(obj_batches, 1)
        if prev_obj is not None and abs(prev_obj - epoch_obj) <= \
                tol * max(abs(prev_obj), 0.05):
            status = Status.CONVERGED
            prev_obj = epoch_obj
            break
        prev_obj = epoch_obj
    return PrimalResult(
        w=np.asarray(w, np.float32), bias=float(b), status=status,
        epochs_run=epochs_run, n_steps=n_steps, n_rows=n_rows,
        objective=float(prev_obj if prev_obj is not None else 0.0),
    )
