"""Seeded, deterministic approximate-kernel feature maps: RFF + Nystrom.

Exact SMO is O(n * |SV|) per f-rebuild and the SV set grows with n — past
some row count no shrinking or fleet batching saves the exact path. Both
maps here send the rbf kernel into an EXPLICIT feature space where
K(x, z) ~= Phi(x).Phi(z), so every kernel touchpoint becomes the linear
family's primal-friendly matmul (kernels/linear.py: f-update =
X @ (X_B^T coef), no kernel slab, no row norms) and solver cost turns
linear in n — the scale-class unlock the ROADMAP names.

  * rff (Rahimi & Recht, NeurIPS 2007): D/2 Gaussian frequency draws
    omega ~ N(0, 2*gamma*I) give
        Phi(x) = sqrt(2/D) * [cos(x.omega) ; sin(x.omega)]
    with E[Phi(x).Phi(z)] = exp(-gamma * ||x - z||^2) exactly. The
    cos/sin (paired-frequency) form is used rather than the single
    random-offset cosine: its kernel estimate has uniformly lower
    variance and needs no offset draw.
  * nystrom (Williams & Seeger, NeurIPS 2001): k landmark rows M drawn
    deterministically from the data, Phi(x) = K(x, M) @ K(M, M)^{-1/2}
    with the pseudo-inverse root eigenvalue-FLOORED for stability
    (near-duplicate landmarks make K_mm numerically singular; flooring
    bounds the operator instead of amplifying noise modes).

Determinism contract: every random draw comes from
np.random.default_rng(map_seed) on the HOST — the same (seed, shape,
gamma) reproduces bit-identical map parameters on every platform — and
the transforms are pure jit functions of (X, params), registered with
obs.prof.profiled_jit so the compile observatory and the IR auditor
(JXIR101-106) see them like every other entry point. Map dimensions are
TPU-tile-aligned by config validation (config.validate_map_dim) BEFORE
any data is touched.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tpusvm.config import APPROX_FAMILIES, SVMConfig, validate_map_dim
from tpusvm.obs import prof
from tpusvm.ops.rbf import matmul_p, rbf_cross

# eigenvalue floor of the Nystrom pseudo-inverse root, relative to the
# largest eigenvalue of K_mm (K_mm is PSD with unit diagonal, so its
# spectrum is scale-free); eigenvalues below lam_max * NYSTROM_EIG_FLOOR
# are clamped UP to it before the inverse square root
NYSTROM_EIG_FLOOR = 1e-7


# ----------------------------------------------------------- parameter draws
def rff_omega(n_features: int, D: int, gamma: float, seed: int) -> np.ndarray:
    """The (d, D/2) Gaussian frequency matrix omega ~ N(0, 2*gamma*I).

    Host-side numpy with a seeded Generator: bit-identical on every
    platform and across ingest/train/predict/serve — the map parameters
    never need to be stored for rff, (d, D, gamma, seed) regenerates
    them exactly (models/serialization format v4 carries those four).
    """
    validate_map_dim(D, "rff_dim")
    rng = np.random.default_rng(seed)
    scale = np.sqrt(2.0 * gamma)
    return (rng.standard_normal((n_features, D // 2))
            * scale).astype(np.float32)


def nystrom_landmark_indices(n: int, k: int, seed: int) -> np.ndarray:
    """The k deterministic landmark row indices: the first k entries of
    the seeded permutation of range(n) — a uniform without-replacement
    draw that any holder of (n, k, seed) reproduces (the streamed path
    gathers exactly these global rows from the manifest)."""
    if k > n:
        raise ValueError(
            f"nystrom needs landmarks <= n rows, got landmarks={k} > n={n}"
        )
    return np.sort(np.random.default_rng(seed).permutation(n)[:k])


def nystrom_weights(landmarks: np.ndarray, gamma: float,
                    eig_floor: float = NYSTROM_EIG_FLOOR) -> np.ndarray:
    """The (k, k) eigenvalue-floored inverse root of K(M, M), float32.

    Computed host-side in f64 (one small symmetric eigendecomposition —
    determinism and conditioning both want the wide accumulator), then
    cast once: W = U diag(1/sqrt(max(lam, lam_max*eig_floor))) U^T.
    """
    M = np.asarray(landmarks, np.float64)
    sq = (M * M).sum(axis=1)
    K_mm = np.exp(-gamma * np.maximum(
        sq[:, None] + sq[None, :] - 2.0 * (M @ M.T), 0.0))
    lam, U = np.linalg.eigh(K_mm)
    floor = max(float(lam[-1]), 0.0) * eig_floor
    lam = np.maximum(lam, max(floor, np.finfo(np.float64).tiny))
    W = (U / np.sqrt(lam)) @ U.T
    return W.astype(np.float32)


# ----------------------------------------------------------------- transforms
def _apply_map(family: str, X: jax.Array, arrays: Tuple[jax.Array, ...]
               ) -> jax.Array:
    """The pure map body shared by the standalone transforms and the
    fused approx-decision programs (both trace THIS, so an offline score
    and a serve-bucket score run the same mapped arithmetic)."""
    if family == "rff":
        (omega,) = arrays
        # precision-routed (matmul_p, trust tier): the map matmul feeds
        # cos/sin, where bf16 operand rounding would alias frequencies
        dots = matmul_p(X, omega.astype(X.dtype))
        scale = jnp.asarray(np.sqrt(1.0 / omega.shape[1]), X.dtype)
        return scale * jnp.concatenate(
            [jnp.cos(dots), jnp.sin(dots)], axis=-1)
    if family == "nystrom":
        landmarks, W, gamma = arrays
        K_nm = rbf_cross(X, landmarks.astype(X.dtype), gamma)
        return matmul_p(K_nm, W.astype(X.dtype))
    raise ValueError(
        f"unknown approximate family {family!r}; supported: "
        f"{list(APPROX_FAMILIES)}"
    )


@jax.jit
def _rff_transform_jit(X: jax.Array, omega: jax.Array) -> jax.Array:
    """Phi(X) for the rff family: (n, d) -> (n, D). One MXU matmul plus
    a pointwise cos/sin epilogue — embarrassingly vmappable and
    tile-aligned by construction (D = 2 * omega.shape[1])."""
    return _apply_map("rff", X, (omega,))


@jax.jit
def _nystrom_transform_jit(X: jax.Array, landmarks: jax.Array,
                           W: jax.Array, gamma: jax.Array) -> jax.Array:
    """Phi(X) for the nystrom family: K(X, M) @ K_mm^{-1/2}, (n, k).

    gamma is a traced scalar (one executable per shape regardless of the
    rbf width), the contract every kernel entry point shares.
    """
    return _apply_map("nystrom", X, (landmarks, W, gamma))


rff_transform = prof.profiled_jit(
    "approx.rff_transform", _rff_transform_jit)
nystrom_transform = prof.profiled_jit(
    "approx.nystrom_transform", _nystrom_transform_jit)


# ------------------------------------------------- fused approx prediction
_APPROX_DECISION_STATIC = ("family", "block")


@functools.partial(jax.jit, static_argnames=_APPROX_DECISION_STATIC)
def _approx_decision_jit(Xq, map_arrays, X_sv, coef, b, *, family: str,
                         block: int = 2048):
    """f(x) = Phi(x).sum_j coef_j Phi(x_j) - b for each raw test row.

    The map and the linear decision sum are ONE program: serve's bucket
    executables lower exactly this function, and the offline
    decision_function calls it, so served scores are bit-identical to
    offline scores by construction (same jaxpr, same operands). X_sv is
    already mapped (models store mapped support rows); Xq is raw scaled
    rows — the map runs inside.
    """
    from tpusvm.solver.predict import _decision_function_jit

    # pad the RAW rows up to the block multiple BEFORE the map: XLA
    # dispatches a degenerate dot kernel at m == 1 with ~1-ulp drift
    # against every other row count (the serve bucket-floor rationale,
    # serve/buckets.py _MIN_BUCKET) — mapping the padded rows means no
    # caller geometry ever traces a single-row map program, so offline
    # and bucket scores agree bitwise
    m, _ = Xq.shape
    pad = -m % block
    Xp = jnp.pad(Xq, ((0, pad), (0, 0)))
    Z = _apply_map(family, Xp, map_arrays)
    # gamma/coef0/degree are inert for the linear-geometry dispatch the
    # approx families route through; family keeps the dispatch honest
    return _decision_function_jit(Z, X_sv, coef, b, gamma=0.0,
                                  block=block, kernel=family)[:m]


@functools.partial(jax.jit, static_argnames=("family",))
def _approx_ovr_scores_jit(Xq, map_arrays, X_sv, coef, b, *, family: str):
    """(m, K) one-vs-rest scores over mapped features (map fused in, like
    _approx_decision_jit — the serve ovr bucket lowers this)."""
    from tpusvm.models.ovr import _ovr_scores_jit

    # pad raw rows to the ovr gemm's 4-row floor multiple before the
    # map (same degenerate-row-count rationale as the binary scorer;
    # the ovr floor is 4 — serve/buckets.py _MIN_BUCKET)
    m, _ = Xq.shape
    pad = -m % 4
    Xp = jnp.pad(Xq, ((0, pad), (0, 0)))
    Z = _apply_map(family, Xp, map_arrays)
    zero = jnp.zeros((), Z.dtype)
    return _ovr_scores_jit(Z, X_sv, coef, b, zero, zero,
                           kernel=family)[:m]


approx_decision_function = prof.profiled_jit(
    "predict.approx_decision", _approx_decision_jit,
    static=_APPROX_DECISION_STATIC)
approx_ovr_scores = prof.profiled_jit(
    "predict.approx_ovr_scores", _approx_ovr_scores_jit,
    static=("family",))


# -------------------------------------------------------------- the map object
@dataclasses.dataclass
class FeatureMap:
    """One fitted approximate feature map: family + its parameter arrays.

    arrays: rff -> (omega,); nystrom -> (landmarks, W, gamma0d). All
    float32 numpy on the host; transform() uploads per call (model fit
    paths call it once per matrix; serve pins the arrays itself).
    """

    family: str
    arrays: Tuple[np.ndarray, ...]
    n_features_in: int
    seed: int

    @property
    def dim(self) -> int:
        """Mapped feature width D (rff: 2 * D/2 draws; nystrom: k)."""
        if self.family == "rff":
            return 2 * self.arrays[0].shape[1]
        return self.arrays[1].shape[1]

    def transform(self, X) -> jax.Array:
        """Phi(X) on device; X is (m, n_features_in), any float dtype."""
        if self.family == "rff":
            return rff_transform(X, jnp.asarray(self.arrays[0]))
        landmarks, W, gamma = self.arrays
        return nystrom_transform(X, jnp.asarray(landmarks),
                                 jnp.asarray(W), jnp.asarray(gamma))

    def transform_np(self, X: np.ndarray, dtype=np.float32) -> np.ndarray:
        """Host-side convenience: cast to the compute dtype, map on
        device via the SAME jitted transform, materialise. This is the
        stream/reader.py prefetch hook — per-shard mapping, bit-identical
        to the in-memory fit path's features."""
        return np.asarray(self.transform(jnp.asarray(X, dtype)))

    # --------------------------------------------------------- persistence
    def state_entries(self) -> dict:
        """npz state entries (models/serialization format v4).

        rff stores NOTHING but the input width — (d, D, gamma, seed) in
        the config regenerate omega bit-identically; nystrom stores its
        data-dependent landmark rows and inverse-root weights.
        """
        entries = {"map_n_features_in": np.int64(self.n_features_in)}
        if self.family == "nystrom":
            entries["map_landmarks"] = self.arrays[0]
            entries["map_weights"] = self.arrays[1]
        return entries


def build_map(config: SVMConfig, X_scaled: Optional[np.ndarray] = None,
              n_features: Optional[int] = None,
              landmark_rows: Optional[np.ndarray] = None) -> FeatureMap:
    """Fit the config's approximate map.

    rff needs only the input width (pass n_features, or X_scaled for it);
    nystrom needs landmark rows — either X_scaled (the in-memory path:
    indices drawn by nystrom_landmark_indices over its rows) or
    landmark_rows directly (the streamed path gathers the same seeded
    indices from the manifest — stream.assign.gather_rows — and scales
    them, so both paths hold identical landmarks).
    """
    family = config.kernel
    if family not in APPROX_FAMILIES:
        raise ValueError(
            f"build_map: {family!r} is not an approximate family "
            f"({list(APPROX_FAMILIES)})"
        )
    if family == "rff":
        if n_features is None:
            if X_scaled is None:
                raise ValueError("build_map(rff): pass X_scaled or "
                                 "n_features")
            n_features = int(X_scaled.shape[1])
        omega = rff_omega(n_features, config.rff_dim, config.gamma,
                          config.map_seed)
        return FeatureMap("rff", (omega,), n_features, config.map_seed)
    if landmark_rows is None:
        if X_scaled is None:
            raise ValueError("build_map(nystrom): pass X_scaled or "
                             "landmark_rows")
        idx = nystrom_landmark_indices(len(X_scaled), config.landmarks,
                                       config.map_seed)
        landmark_rows = np.asarray(X_scaled)[idx]
    landmarks = np.asarray(landmark_rows, np.float32)
    if landmarks.shape[0] != config.landmarks:
        raise ValueError(
            f"build_map(nystrom): got {landmarks.shape[0]} landmark rows, "
            f"config says landmarks={config.landmarks}"
        )
    W = nystrom_weights(landmarks, config.gamma)
    gamma0d = np.float32(config.gamma)
    return FeatureMap("nystrom", (landmarks, W, gamma0d),
                      int(landmarks.shape[1]), config.map_seed)


def map_from_state(state: dict, config: SVMConfig) -> FeatureMap:
    """Rebuild the fitted map from a loaded v4 state dict + config."""
    if "map_n_features_in" not in state:
        raise ValueError(
            f"model names approximate kernel {config.kernel!r} but its "
            "state carries no map provenance (map_n_features_in) — the "
            "artifact predates serialization v4 or was tampered with"
        )
    d = int(np.asarray(state["map_n_features_in"]))
    if config.kernel == "rff":
        omega = rff_omega(d, config.rff_dim, config.gamma, config.map_seed)
        return FeatureMap("rff", (omega,), d, config.map_seed)
    landmarks = np.asarray(state["map_landmarks"], np.float32)
    W = np.asarray(state["map_weights"], np.float32)
    return FeatureMap("nystrom", (landmarks, W, np.float32(config.gamma)),
                      d, config.map_seed)


def kernel_approx_error(X: np.ndarray, fmap: FeatureMap, gamma: float,
                        n_pairs: int = 2048, seed: int = 0) -> float:
    """max |K_hat - K| over sampled row pairs — the approximation-error
    probe (decreasing in D; committed by benchmarks/approx_scale.py).

    K is the exact rbf kernel in f64; K_hat = Phi(a).Phi(b) with the
    fitted map. Pairs are drawn with a seeded Generator so committed
    artifact rows reproduce.
    """
    rng = np.random.default_rng(seed)
    n = len(X)
    ii = rng.integers(0, n, n_pairs)
    jj = rng.integers(0, n, n_pairs)
    A, B = np.asarray(X, np.float64)[ii], np.asarray(X, np.float64)[jj]
    K = np.exp(-gamma * ((A - B) ** 2).sum(axis=1))
    Za = fmap.transform_np(A).astype(np.float64)
    Zb = fmap.transform_np(B).astype(np.float64)
    K_hat = (Za * Zb).sum(axis=1)
    return float(np.abs(K_hat - K).max())
