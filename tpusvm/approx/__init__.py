"""tpusvm.approx — the approximate-kernel primal regime.

Random Fourier Features + Nystrom landmark maps (features.py) send the
rbf kernel into an explicit feature space where every kernel touchpoint
is the linear family's primal matmul, and a streaming mini-batch primal
solver (primal.py) consumes mapped shards straight off the prefetch
pipeline — together the linear-cost training path that opens the
ROADMAP's 100M-row scale class (the cascade/fleet machinery applies
unchanged on top). Kernel families "rff"/"nystrom" (config.KERNEL_FAMILIES)
route here via kernels.dispatch and the model layer.
"""

from tpusvm.approx.features import (
    FeatureMap,
    approx_decision_function,
    approx_ovr_scores,
    build_map,
    kernel_approx_error,
    map_from_state,
    nystrom_landmark_indices,
    nystrom_transform,
    nystrom_weights,
    rff_omega,
    rff_transform,
)
from tpusvm.approx.primal import PrimalResult, streaming_primal_fit

__all__ = [
    "FeatureMap",
    "build_map",
    "map_from_state",
    "rff_omega",
    "rff_transform",
    "nystrom_landmark_indices",
    "nystrom_weights",
    "nystrom_transform",
    "approx_decision_function",
    "approx_ovr_scores",
    "kernel_approx_error",
    "PrimalResult",
    "streaming_primal_fit",
]
