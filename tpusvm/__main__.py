"""`python -m tpusvm` — see tpusvm.cli."""

import sys

from tpusvm.cli import main

sys.exit(main())
