"""Platt scaling: calibrated P(y=+1 | decision score) for binary SVMs.

Fits the sigmoid P(y=+1|f) = 1 / (1 + exp(A*f + B)) to (score, label)
pairs by regularised maximum likelihood, using the Newton method with
backtracking line search from Lin, Lin & Weng (2007), "A note on Platt's
probabilistic outputs for support vector machines" — the numerically
robust replacement for Platt's original pseudocode (no exp overflow, no
log-of-zero, guaranteed descent). Pure NumPy on the host: the fit sees a
few thousand scalars, and keeping it off-device makes serve's proba field
bit-identical to the offline predict_proba on the same scores.

Calibration data discipline (Platt 1999 §2.2): the sigmoid must be fit on
scores the model did NOT train on, or the bound SVs' clipped scores bias
A toward overconfidence. BinarySVC.calibrate therefore fits k held-out
fold models (tune/folds.stratified_kfold — the same deterministic splits
the tune subsystem uses) and pools their out-of-fold scores; the final
sigmoid maps the FULL model's decision_function, the standard
CalibratedClassifierCV-style protocol.

A fitted A is (strictly) negative on any separable-ish problem, making
the probability a monotone INCREASING function of the decision score —
asserted in tests; a non-negative A would mean the scores carry no label
signal at all.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


def fit_platt(scores: np.ndarray, labels: np.ndarray, *,
              max_iter: int = 100, min_step: float = 1e-10,
              sigma: float = 1e-12) -> Tuple[float, float]:
    """Fit (A, B) of P(y=+1|f) = 1/(1 + exp(A*f + B)).

    scores: decision-function values; labels: {+1, -1}. Targets are the
    Bayes-shrunk t+ = (N+ + 1)/(N+ + 2), t- = 1/(N- + 2) priors (Platt's
    regularisation — keeps the fit defined even on separable data).
    Raises ValueError unless both classes are present.
    """
    f = np.asarray(scores, np.float64).ravel()
    y = np.asarray(labels).ravel()
    if f.shape != y.shape:
        raise ValueError(
            f"scores/labels length mismatch: {f.shape} vs {y.shape}"
        )
    pos = y > 0
    n_pos = int(pos.sum())
    n_neg = len(y) - n_pos
    if n_pos == 0 or n_neg == 0:
        raise ValueError(
            "Platt calibration needs both classes in the calibration set; "
            f"got {n_pos} positive / {n_neg} negative"
        )
    hi = (n_pos + 1.0) / (n_pos + 2.0)
    lo = 1.0 / (n_neg + 2.0)
    t = np.where(pos, hi, lo)

    def objective(a, b):
        fApB = a * f + b
        # -sum t*log(p) + (1-t)*log(1-p); exp only ever sees -|fApB|, so
        # neither np.where branch can overflow
        return float(np.sum(
            np.where(fApB >= 0, t * fApB, (t - 1.0) * fApB)
            + np.log1p(np.exp(-np.abs(fApB)))
        ))

    a, b = 0.0, np.log((n_neg + 1.0) / (n_pos + 1.0))
    fval = objective(a, b)
    for _ in range(max_iter):
        fApB = a * f + b
        # p = P(y=+1), q = 1-p; exp(-|fApB|) keeps both branches finite
        e = np.exp(-np.abs(fApB))
        p = np.where(fApB >= 0, e / (1.0 + e), 1.0 / (1.0 + e))
        q = 1.0 - p
        d1 = t - p                 # Lin et al.'s d1 (negative gradient
        #                            of the per-point objective in fApB)
        d2 = p * q                 # second derivative per point
        g1 = float(np.sum(f * d1))
        g2 = float(np.sum(d1))
        if abs(g1) < 1e-5 and abs(g2) < 1e-5:
            break
        h11 = float(np.sum(f * f * d2)) + sigma
        h22 = float(np.sum(d2)) + sigma
        h21 = float(np.sum(f * d2))
        det = h11 * h22 - h21 * h21
        dA = -(h22 * g1 - h21 * g2) / det
        dB = -(-h21 * g1 + h11 * g2) / det
        gd = g1 * dA + g2 * dB     # < 0: Newton direction descends
        step = 1.0
        while step >= min_step:
            na, nb = a + step * dA, b + step * dB
            nf = objective(na, nb)
            if nf < fval + 1e-4 * step * gd:
                a, b, fval = na, nb, nf
                break
            step /= 2.0
        else:
            break  # line search failed: at numerical optimum
    return float(a), float(b)


def platt_proba(scores: np.ndarray, A: float, B: float) -> np.ndarray:
    """P(y=+1|f) = 1/(1 + exp(A*f + B)), overflow-stable. Shape of scores.

    Strictly monotone in the scores whenever A < 0 (the fitted sign on
    any informative score set).
    """
    f = np.asarray(scores, np.float64)
    fApB = A * f + B
    e = np.exp(-np.abs(fApB))  # exp never sees a positive argument
    return np.where(fApB >= 0, e / (1.0 + e), 1.0 / (1.0 + e))


def log_loss(proba: np.ndarray, labels: np.ndarray,
             clip: float = 1e-15) -> float:
    """Mean negative log-likelihood of {+1,-1} labels under P(y=+1)."""
    p = np.clip(np.asarray(proba, np.float64), clip, 1.0 - clip)
    y = np.asarray(labels).ravel()
    return float(-np.mean(np.where(y > 0, np.log(p), np.log(1.0 - p))))
