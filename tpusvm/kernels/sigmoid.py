"""Sigmoid kernel K(x, z) = tanh(gamma * x.z + coef0).

The last named exact-kernel gap of the (kernel, task) matrix: the same
"dot product + pointwise epilogue" structure as the polynomial family —
one MXU matmul forms the dots, tanh(gamma*. + coef0) is applied
elementwise on the result tile. gamma and coef0 are traced scalars (a
(gamma, coef0) sweep reuses one compiled solver, the contract every
family shares); there is no static parameter, so one executable serves
the whole family. Note the sigmoid kernel is only conditionally positive
semi-definite (classic libsvm caveat) — SMO still runs (eta <= eps pairs
are excluded like everywhere else), and the f64 oracle carries the same
formulation, so parity evidence is meaningful regardless.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from tpusvm.ops.rbf import _prec, coef_matvec, matmul_p


def _epilogue(dots: jax.Array, gamma, coef0) -> jax.Array:
    return jnp.tanh(gamma * dots + coef0)


def sigmoid_row(X: jax.Array, x: jax.Array, gamma, coef0,
                precision=None) -> jax.Array:
    """K(x, X[j]) for all j. Shape (n,)."""
    return _epilogue(jnp.matmul(X, x, precision=_prec(precision)),
                     gamma, coef0)


def sigmoid_rows_at(X: jax.Array, idx: jax.Array, gamma, coef0,
                    precision=None) -> jax.Array:
    """K(X[idx[k]], X[j]) via one (k, d) x (d, n) matmul. Shape (k, n).

    Routed through the precision ladder (ops.rbf.matmul_p) like the poly
    family's K-row refresh.
    """
    dots = matmul_p(X[idx], X.T, precision)
    return _epilogue(dots, gamma, coef0)


def sigmoid_cross(XA: jax.Array, XB: jax.Array, gamma, coef0,
                  precision=None) -> jax.Array:
    """Full K(XA, XB), shape (nA, nB)."""
    dots = jnp.matmul(XA, XB.T, precision=_prec(precision))
    return _epilogue(dots, gamma, coef0)


def sigmoid_cross_matvec(X: jax.Array, XB: jax.Array, coef: jax.Array,
                         gamma, coef0, *, block: int = 8192,
                         precision=None) -> jax.Array:
    """sum_k coef_k K(x_i, xb_k) for all i, blocked over i. Shape (n,).

    tanh is not linear, so (like poly) there is no primal collapse: the
    generic blocked K-row path streams X in (block, q) tiles, never the
    full (n, q) slab.
    """
    n, d = X.shape
    block = min(block, n)
    nb = -(-n // block)
    coef = coef.astype(X.dtype)

    def step(_, start):
        zero = jnp.zeros((), start.dtype)
        Xblk = jax.lax.dynamic_slice(X, (start, zero), (block, d))
        dots = matmul_p(Xblk, XB.T, precision)
        return None, coef_matvec(_epilogue(dots, gamma, coef0),
                                 coef, precision)

    starts = jnp.minimum(
        jnp.arange(nb, dtype=jnp.int32) * block, max(n - block, 0)
    )
    _, chunks = jax.lax.scan(step, None, starts)
    body = chunks[:-1].reshape(-1)
    tail = chunks[-1, (nb * block - n):]
    return jnp.concatenate([body, tail]).astype(X.dtype)


def sigmoid_matvec(X: jax.Array, coef: jax.Array, gamma, coef0, *,
                   block: int = 1024, precision=None) -> jax.Array:
    """sum_j coef_j K(x_j, x_i) for all i. Shape (n,)."""
    return sigmoid_cross_matvec(X, X, coef, gamma, coef0, block=block,
                                precision=precision)
