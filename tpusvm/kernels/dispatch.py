"""Static kernel-family dispatch: the solver's single kernel touchpoint.

The SMO machinery (analytic 2-alpha update, Keerthi selection, the blocked
outer loop) only touches the kernel through four computations — a K-row
batch for the selected indices, the small K_BB working-set matrix, the
blocked K(X, X_B) @ coef error-vector contraction, and the warm-start
K @ coef reconstruction. This module routes each of those through the
family named by a STATIC string (`kernel` is a jit static argname in both
solvers), so the dispatch happens at trace time and every family compiles
to exactly its own program:

  - "rbf":     the existing ops/rbf.py implementations, called with
               byte-identical arguments — the refactor is bit-transparent
               to every pre-existing RBF trajectory;
  - "linear":  K(x, z) = x.z — no precomputables at all (needs_norms is
               False, so solvers skip the sq_norms pass entirely), and the
               blocked contraction has a primal fast path
               X @ (X_B^T coef) that never materialises a kernel slab
               (kernels/linear.py);
  - "poly":    K(x, z) = (gamma x.z + coef0)^degree — the same dot-form
               matmuls as linear with a pointwise affine+power epilogue
               (kernels/poly.py). `degree` is static (a Python int power),
               gamma/coef0 are traced scalars like gamma everywhere else;
  - "sigmoid": K(x, z) = tanh(gamma x.z + coef0) — poly's structure with
               a tanh epilogue (kernels/sigmoid.py); gamma/coef0 traced;
  - "rff" / "nystrom" (config.APPROX_FAMILIES): the APPROXIMATE-kernel
               primal regime (tpusvm.approx). The caller has already
               applied the explicit feature map Phi — the "X" these
               computations receive IS the mapped matrix, and
               K̂(x, z) = Phi(x).Phi(z) is exactly the linear kernel over
               it — so both names route verbatim through the linear
               family's implementations, primal fast path included. The
               solvers therefore run the LINEAR-COST program for approx
               fits while the model/serve layers own the map; gamma is
               consumed by the map (it parameterises omega / K_nm), never
               by these contractions.

Family validation raises the same clear error everywhere (solvers,
serialization, config) via `validate_family`.
"""

from __future__ import annotations

from typing import Optional

import jax

from tpusvm.config import APPROX_FAMILIES, KERNEL_FAMILIES
from tpusvm.kernels import linear as _lin
from tpusvm.kernels import poly as _poly
from tpusvm.kernels import sigmoid as _sig
from tpusvm.ops import rbf as _rbf


def validate_family(family: str) -> str:
    if family not in KERNEL_FAMILIES:
        raise ValueError(
            f"unknown kernel family {family!r}; supported: "
            f"{list(KERNEL_FAMILIES)}"
        )
    return family


def is_approx(family: str) -> bool:
    """Whether the family's features are an explicit approximate-kernel
    map (tpusvm.approx) — the model layer applies Phi, the kernel layer
    sees linear geometry over the mapped rows."""
    return validate_family(family) in APPROX_FAMILIES


def needs_norms(family: str) -> bool:
    """Whether the family consumes per-row squared norms (sq_norms).

    Only RBF does (the distance-dot trick); linear/poly/sigmoid and the
    approx families skip the O(n*d) norms pass and carry sn=None.
    """
    return validate_family(family) == "rbf"


def sq_norms_for(family: str, X: jax.Array) -> Optional[jax.Array]:
    """The family's precomputable row norms: sq_norms(X) for RBF, None
    otherwise — the one-liner every sn-caching caller (tune's fold
    caches, the shrinking driver's per-compaction cache) repeats."""
    if needs_norms(family):
        from tpusvm.ops.rbf import sq_norms

        return sq_norms(X)
    return None


def rows_at(family: str, X: jax.Array, idx: jax.Array, *, gamma, coef0=0.0,
            degree: int = 3, sn: Optional[jax.Array] = None,
            precision=None) -> jax.Array:
    """K(X[idx[k]], X[j]) for a small static-size index vector. (k, n)."""
    if family == "rbf":
        return _rbf.rbf_rows_at(X, idx, gamma, sn, precision)
    if family == "linear" or family in APPROX_FAMILIES:
        return _lin.linear_rows_at(X, idx, precision)
    if family == "sigmoid":
        return _sig.sigmoid_rows_at(X, idx, gamma, coef0, precision)
    validate_family(family)
    return _poly.poly_rows_at(X, idx, gamma, coef0, degree, precision)


def cross(family: str, XA: jax.Array, XB: jax.Array, *, gamma, coef0=0.0,
          degree: int = 3, snA: Optional[jax.Array] = None,
          snB: Optional[jax.Array] = None, precision=None) -> jax.Array:
    """Full K(XA, XB) kernel matrix, shape (nA, nB)."""
    if family == "rbf":
        return _rbf.rbf_cross(XA, XB, gamma, snA, snB, precision)
    if family == "linear" or family in APPROX_FAMILIES:
        return _lin.linear_cross(XA, XB, precision)
    if family == "sigmoid":
        return _sig.sigmoid_cross(XA, XB, gamma, coef0, precision)
    validate_family(family)
    return _poly.poly_cross(XA, XB, gamma, coef0, degree, precision)


def cross_matvec(family: str, X: jax.Array, XB: jax.Array, coef: jax.Array,
                 *, gamma, coef0=0.0, degree: int = 3,
                 sn: Optional[jax.Array] = None, block: int = 8192,
                 precision=None, fast: bool = True) -> jax.Array:
    """sum_k coef_k K(x_i, xb_k) for all i — the blocked f update. (n,).

    fast only affects the linear-geometry families ("linear" and the
    approx names routing through it): True (default) computes the primal
    form X @ (X_B^T coef) — one (d,) intermediate, no (n, q) kernel slab,
    no row-norm traffic; False runs the generic blocked K-row path (the
    benchmark control arm, benchmarks/kernel_matrix.py).
    """
    if family == "rbf":
        return _rbf.rbf_cross_matvec(X, XB, coef, gamma, sn, block,
                                     precision)
    if family == "linear" or family in APPROX_FAMILIES:
        return _lin.linear_cross_matvec(X, XB, coef, block=block,
                                        precision=precision, fast=fast)
    if family == "sigmoid":
        return _sig.sigmoid_cross_matvec(X, XB, coef, gamma, coef0,
                                         block=block, precision=precision)
    validate_family(family)
    return _poly.poly_cross_matvec(X, XB, coef, gamma, coef0, degree,
                                   block=block, precision=precision)


def matvec(family: str, X: jax.Array, coef: jax.Array, *, gamma, coef0=0.0,
           degree: int = 3, block: int = 1024, precision=None) -> jax.Array:
    """sum_j coef_j K(x_j, x_i) for all i — warm-start f reconstruction."""
    if family == "rbf":
        return _rbf.rbf_matvec(X, coef, gamma, block, precision)
    if family == "linear" or family in APPROX_FAMILIES:
        return _lin.linear_matvec(X, coef, precision=precision)
    if family == "sigmoid":
        return _sig.sigmoid_matvec(X, coef, gamma, coef0, block=block,
                                   precision=precision)
    validate_family(family)
    return _poly.poly_matvec(X, coef, gamma, coef0, degree, block=block,
                             precision=precision)
