"""Polynomial kernel K(x, z) = (gamma * x.z + coef0)^degree.

Structurally the linear family's matmuls with a pointwise affine + power
epilogue — the "powered dot" precomputable the kernel interface names:
every computation forms the dot product first (one MXU matmul, exactly the
linear family's shape) and applies the epilogue elementwise on the result
tile. `degree` is a STATIC Python int (the power unrolls at trace time;
integer powers of possibly-negative bases are exact), gamma and coef0 are
traced scalars so a (gamma, coef0) sweep reuses one compiled solver, the
same contract as RBF's gamma everywhere else.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from tpusvm.ops.rbf import _prec, coef_matvec, matmul_p


def _epilogue(dots: jax.Array, gamma, coef0, degree: int) -> jax.Array:
    return (gamma * dots + coef0) ** degree


def poly_row(X: jax.Array, x: jax.Array, gamma, coef0, degree: int,
             precision=None) -> jax.Array:
    """K(x, X[j]) for all j. Shape (n,)."""
    return _epilogue(jnp.matmul(X, x, precision=_prec(precision)),
                     gamma, coef0, degree)


def poly_rows_at(X: jax.Array, idx: jax.Array, gamma, coef0, degree: int,
                 precision=None) -> jax.Array:
    """K(X[idx[k]], X[j]) via one (k, d) x (d, n) matmul. Shape (k, n).

    Routed through the precision ladder (ops.rbf.matmul_p): the K-row
    refresh is a laddered contraction, like the blocked f update.
    """
    dots = matmul_p(X[idx], X.T, precision)
    return _epilogue(dots, gamma, coef0, degree)


def poly_cross(XA: jax.Array, XB: jax.Array, gamma, coef0, degree: int,
               precision=None) -> jax.Array:
    """Full K(XA, XB), shape (nA, nB)."""
    dots = jnp.matmul(XA, XB.T, precision=_prec(precision))
    return _epilogue(dots, gamma, coef0, degree)


def poly_cross_matvec(X: jax.Array, XB: jax.Array, coef: jax.Array, gamma,
                      coef0, degree: int, *, block: int = 8192,
                      precision=None) -> jax.Array:
    """sum_k coef_k K(x_i, xb_k) for all i, blocked over i. Shape (n,).

    The non-linearity of the epilogue rules out the linear family's primal
    collapse, so this is the generic blocked K-row path: a (block, q) tile
    per step, never the full (n, q) slab.
    """
    n, d = X.shape
    block = min(block, n)
    nb = -(-n // block)
    coef = coef.astype(X.dtype)

    def step(_, start):
        zero = jnp.zeros((), start.dtype)
        Xblk = jax.lax.dynamic_slice(X, (start, zero), (block, d))
        dots = matmul_p(Xblk, XB.T, precision)
        return None, coef_matvec(_epilogue(dots, gamma, coef0, degree),
                                 coef, precision)

    starts = jnp.minimum(
        jnp.arange(nb, dtype=jnp.int32) * block, max(n - block, 0)
    )
    _, chunks = jax.lax.scan(step, None, starts)
    body = chunks[:-1].reshape(-1)
    tail = chunks[-1, (nb * block - n):]
    return jnp.concatenate([body, tail]).astype(X.dtype)


def poly_matvec(X: jax.Array, coef: jax.Array, gamma, coef0, degree: int, *,
                block: int = 1024, precision=None) -> jax.Array:
    """sum_j coef_j K(x_j, x_i) for all i. Shape (n,)."""
    return poly_cross_matvec(X, X, coef, gamma, coef0, degree, block=block,
                             precision=precision)
