"""Linear kernel K(x, z) = x.z, with the primal-friendly fast path.

The linear family needs NO per-kernel precomputables (no row norms, no
distance trick, no exp): every computation is a plain MXU matmul over X.
That structure admits an optimisation the other families cannot express —
the blocked error-vector contraction K(X, X_B) @ coef collapses to

    X @ (X_B^T @ coef)

because K(X, X_B) = X X_B^T: fold the coefficient vector into a single
(d,) weight delta first, then one (n, d) x (d,) matvec applies it to every
row. The generic path streams X once AND materialises (block, q) kernel
slabs per block; the primal form streams X once with a q*d-flop prologue
and no slab at all — the "linear gets a dedicated primal-friendly fast
path" design (ROADMAP Scenario diversity; measured in
benchmarks/results/kernel_matrix_cpu.jsonl). Both forms are kept: the
generic path is the benchmark control arm and the template the poly
family shares.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from tpusvm.ops.rbf import _prec, coef_matvec, matmul_p


def linear_row(X: jax.Array, x: jax.Array, precision=None) -> jax.Array:
    """K(x, X[j]) for all j. Shape (n,)."""
    return jnp.matmul(X, x, precision=_prec(precision))


def linear_rows_at(X: jax.Array, idx: jax.Array, precision=None) -> jax.Array:
    """K(X[idx[k]], X[j]) — one (k, d) x (d, n) matvec, no row-norm
    traffic (the K-row IS the matmul for this family). Shape (k, n).
    Routed through the precision ladder (ops.rbf.matmul_p)."""
    return matmul_p(X[idx], X.T, precision)


def linear_cross(XA: jax.Array, XB: jax.Array, precision=None) -> jax.Array:
    """Full K(XA, XB) = XA @ XB^T, shape (nA, nB)."""
    return jnp.matmul(XA, XB.T, precision=_prec(precision))


def linear_cross_matvec(X: jax.Array, XB: jax.Array, coef: jax.Array, *,
                        block: int = 8192, precision=None,
                        fast: bool = True) -> jax.Array:
    """sum_k coef_k (x_i . xb_k) for all i. Shape (n,).

    fast=True: the primal form X @ (XB^T coef) — O(q*d + n*d) flops, zero
    kernel-slab memory. fast=False: the generic blocked K-row path (same
    loop structure as rbf_cross_matvec minus the distance/exp epilogue) —
    O(n*q*d) flops and a (block, q) slab per step; kept as the measured
    control arm. Both compute the same sum (association differs, so
    results agree to normal f32 matmul reordering noise, not bitwise).
    """
    coef = coef.astype(X.dtype)
    if fast:
        # the (d,)-weight prologue stays at the trust tier regardless of
        # the ladder rung (it is O(q*d), not the streamed contraction);
        # the laddered matmul is the X stream
        w = jnp.matmul(XB.T, coef,
                       precision=_prec(None if precision in
                                       ("bf16_f32", "bf16_f32c")
                                       else precision))  # (d,)
        return matmul_p(X, w, precision).astype(X.dtype)

    n, d = X.shape
    block = min(block, n)
    nb = -(-n // block)

    def step(_, start):
        zero = jnp.zeros((), start.dtype)
        Xblk = jax.lax.dynamic_slice(X, (start, zero), (block, d))
        K = matmul_p(Xblk, XB.T, precision)
        return None, coef_matvec(K, coef, precision)

    starts = jnp.minimum(
        jnp.arange(nb, dtype=jnp.int32) * block, max(n - block, 0)
    )
    _, chunks = jax.lax.scan(step, None, starts)
    body = chunks[:-1].reshape(-1)
    tail = chunks[-1, (nb * block - n):]
    return jnp.concatenate([body, tail]).astype(X.dtype)


def linear_matvec(X: jax.Array, coef: jax.Array, precision=None) -> jax.Array:
    """sum_j coef_j (x_j . x_i) for all i = X @ (X^T coef). Shape (n,)."""
    return linear_cross_matvec(X, X, coef, precision=precision, fast=True)
