"""tpusvm.kernels — the pluggable kernel/task matrix.

The solvers' SMO structure is kernel-agnostic (SURVEY §0: K-row
computation, error-vector update, working-set selection); this package
factors the kernel touchpoints behind a static family dispatch
(dispatch.py: "rbf" | "linear" | "poly" | "sigmoid", plus the
approximate families "rff" | "nystrom" that route the linear primal
path over explicitly mapped features — tpusvm.approx) and hosts the two
task extensions built on it — the epsilon-SVR variable doubling (svr.py)
and Platt probability calibration (platt.py).
"""

from tpusvm.config import APPROX_FAMILIES, KERNEL_FAMILIES
from tpusvm.kernels.dispatch import (
    cross,
    cross_matvec,
    is_approx,
    matvec,
    needs_norms,
    rows_at,
    sq_norms_for,
    validate_family,
)
from tpusvm.kernels.platt import fit_platt, log_loss, platt_proba
from tpusvm.kernels.svr import collapse_duals, doubled_problem

__all__ = [
    "KERNEL_FAMILIES",
    "APPROX_FAMILIES",
    "rows_at",
    "cross",
    "cross_matvec",
    "matvec",
    "needs_norms",
    "is_approx",
    "validate_family",
    "doubled_problem",
    "collapse_duals",
    "fit_platt",
    "platt_proba",
    "log_loss",
]
