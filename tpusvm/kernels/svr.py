"""Epsilon-SVR as a doubled-variable classification-shaped SMO problem.

The SVR dual over (alpha, alpha*) maps exactly onto the classification SMO
skeleton the solvers already implement (Keerthi et al., "Improvements to
SMO for SVM regression"): stack beta = [alpha; alpha*] over 2n variables
with labels y = [+1]*n + [-1]*n and PSEUDO-TARGETS

    z_i     = t_i - epsilon   (the alpha half,  y = +1)
    z_{i+n} = t_i + epsilon   (the alpha* half, y = -1)

Then the error vector f_i = sum_j beta_j y_j K_ij - z_i satisfies
dL/dbeta_i = y_i f_i — identical to classification, where f_i uses z = y.
Every downstream piece is untouched: the I_high/I_low index sets, the
Keerthi (b_high, b_low) stopping rule, the analytic 2-alpha update with
the s = y_h*y_l box, warm starts, the blocked working-set machinery. The
solvers expose this through one new operand (`targets=z`, defaulting to
z = Y, i.e. classification); everything else is "the same SMO skeleton".

The degenerate twin pair (i, i+n) — identical feature rows, opposite
labels, eta = 0 — can never be selected as a violating pair: their f
values differ by exactly 2*epsilon with f_i the LARGER (z_i is smaller),
in the non-violating direction, and f updates shift both by the same
amount (identical K rows), so the gap is invariant. The eta <= eps guard
stays as the backstop for duplicates already present in the data, as in
classification.

Prediction collapses the doubling: coef_i = beta_i - beta_{i+n} =
alpha_i - alpha*_i, and the regressed value is

    y(x) = sum_i coef_i K(x, x_i) - b

with b = (b_high + b_low)/2 from the solver — the SAME form as the
classification decision function (the sign convention matches because the
KKT condition for an interior alpha_i reads f_i = b there too), so
solver/predict.py, serve's bucket executables, and the serialization
state layout all serve SVR models with zero new score paths.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


def doubled_problem(t: np.ndarray, epsilon: float
                    ) -> Tuple[np.ndarray, np.ndarray]:
    """(Y2, z) for the 2n-variable problem; X doubles by concatenation.

    Y2 is the {+1, -1} label stacking, z the pseudo-target vector the
    solvers take as `targets`. Pure NumPy so the f64 oracle shares the
    construction byte-for-byte with the estimators.
    """
    t = np.asarray(t, np.float64)
    if t.ndim != 1:
        raise ValueError(f"targets must be 1-D, got shape {t.shape}")
    if epsilon < 0:
        raise ValueError(f"epsilon must be >= 0, got {epsilon}")
    n = len(t)
    Y2 = np.concatenate([np.ones(n, np.int32), -np.ones(n, np.int32)])
    z = np.concatenate([t - epsilon, t + epsilon])
    return Y2, z


def collapse_duals(beta: np.ndarray) -> np.ndarray:
    """Signed dual coefficients coef = alpha - alpha* from the 2n betas."""
    beta = np.asarray(beta)
    if beta.ndim != 1 or beta.shape[0] % 2:
        raise ValueError(
            f"expected a flat 2n dual vector, got shape {beta.shape}"
        )
    n = beta.shape[0] // 2
    return beta[:n] - beta[n:]
