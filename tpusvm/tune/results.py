"""Versioned persistence for tune runs.

Model artifacts are .npz with a format_version gate
(models/serialization.py); tune results follow the same philosophy in
JSON — the artifact is a TABLE (per-point metrics) plus a verdict (the
winner), both human-greppable, and it must fail loudly and specifically
when a future tpusvm reads an old file or vice versa. `tpusvm info` knows
how to pretty-print these.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict, List

# v2: the kernel-family search axis — results gain a `kernels` list, rows
# and the winner carry kernel/degree/coef0 (tpusvm.kernels). Old v1 files
# fail the version gate with the standard "different tpusvm" message.
_FORMAT_VERSION = 2
_KIND = "tpusvm-tune-result"


@dataclasses.dataclass
class TuneResult:
    """Everything a tune run decided and measured.

    points: one dict per grid point, in solve (snake) order:
      C, gamma, status (TuneStatus name), rung (last rung the point was
      fit at; -1 if never fit), n_subset (training rows per fold at that
      rung), cv_accuracy (mean over folds; None if never fit),
      fold_accuracy (per-fold list), sv_count (mean over folds),
      n_updates (total SMO alpha updates across folds), wall_s,
      warm_seeded (how many of the fold fits started from a donor seed).
    winner: {C, gamma, cv_accuracy} — the argmax of cv_accuracy at the
      final rung, ties broken by solve order (first wins), so reruns and
      cold/warm A/Bs agree deterministically.
    """

    schedule: str
    grid: Dict[str, List[float]]
    folds: int
    seed: int
    n: int
    d: int
    warm_start: bool
    kernels: List[Dict[str, Any]]
    points: List[Dict[str, Any]]
    winner: Dict[str, Any]
    total_updates: int
    wall_s: float
    # batched fleet dispatch (tpusvm.fleet) — defaulted so results
    # written before the fleet existed still load
    fleet: bool = False

    def to_dict(self) -> Dict[str, Any]:
        return {
            "format_version": _FORMAT_VERSION,
            "kind": _KIND,
            **dataclasses.asdict(self),
        }


def save_tune_result(path: str, result: TuneResult) -> None:
    """Atomic write (temp + os.replace): a deploy/warm-start reader
    racing a re-tune sees the old complete result or the new one,
    never a torn half-written JSON."""
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(result.to_dict(), fh, indent=2)
        fh.write("\n")
    os.replace(tmp, path)


def load_tune_result(path: str) -> TuneResult:
    """Version gate first, same contract as model loading: a missing
    kind/version means "not a tpusvm tune result", an unknown version means
    "written by a different tpusvm" — neither may surface as a KeyError
    from whichever field is read first."""
    with open(path) as fh:
        raw = json.load(fh)
    if not isinstance(raw, dict) or raw.get("kind") != _KIND:
        raise ValueError(
            f"{path!r} is not a tpusvm tune-results file (missing "
            f"kind={_KIND!r})"
        )
    if "format_version" not in raw:
        raise ValueError(
            f"{path!r} has no format_version field — written before "
            "format versioning; re-run the tune"
        )
    version = int(raw["format_version"])
    if version != _FORMAT_VERSION:
        raise ValueError(
            f"unsupported tune-results format version {version} in "
            f"{path!r}: this build reads version {_FORMAT_VERSION}"
        )
    fields = {f.name for f in dataclasses.fields(TuneResult)}
    required = {
        f.name for f in dataclasses.fields(TuneResult)
        if f.default is dataclasses.MISSING
        and f.default_factory is dataclasses.MISSING
    }
    missing = required - set(raw)
    if missing:
        raise ValueError(
            f"{path!r} is missing tune-result fields {sorted(missing)}"
        )
    return TuneResult(**{k: raw[k] for k in fields if k in raw})


def format_table(result: TuneResult) -> str:
    """Human-readable run summary: header, winner, per-point table.

    Shared by `tpusvm tune` (after a run) and `tpusvm info <results.json>`
    (re-reading a committed artifact), so both always agree on what a run
    looked like.
    """
    g = result.grid
    families = "+".join(k["kernel"] for k in result.kernels)
    lines = [
        f"tune: schedule={result.schedule} grid="
        f"{len(g['C_values'])}x{len(g['gamma_values'])} "
        f"kernels={families} "
        f"folds={result.folds} seed={result.seed} "
        f"n={result.n} d={result.d} "
        f"warm_start={'on' if result.warm_start else 'off'}",
        f"winner: kernel={result.winner.get('kernel', 'rbf')} "
        f"C={result.winner['C']:g} "
        f"gamma={result.winner['gamma']:g} "
        f"cv_accuracy={result.winner['cv_accuracy']:.6f}",
        f"total SMO updates: {result.total_updates}   "
        f"wall: {result.wall_s:.2f}s",
        f"{'kernel':>7} {'C':>10} {'gamma':>12} {'status':>10} {'rung':>4} "
        f"{'cv_acc':>8} {'sv':>7} {'updates':>8} {'warm':>4} "
        f"{'wall_s':>7}",
    ]
    for r in result.points:
        acc = "-" if r["cv_accuracy"] is None else f"{r['cv_accuracy']:.4f}"
        sv = "-" if r["sv_count"] is None else f"{r['sv_count']:.1f}"
        lines.append(
            f"{r.get('kernel', 'rbf'):>7} "
            f"{r['C']:>10g} {r['gamma']:>12g} {r['status']:>10} "
            f"{r['rung']:>4} {acc:>8} {sv:>7} {r['n_updates']:>8} "
            f"{r['warm_seeded']:>4} {r['wall_s']:>7.2f}"
        )
    return "\n".join(lines)


def is_tune_result(path: str) -> bool:
    """Cheap sniff (no validation): is this file a tune-results JSON?
    Used by `tpusvm info` to dispatch between artifact kinds."""
    try:
        with open(path, "rb") as fh:
            head = fh.read(4096)
        return _KIND.encode() in head
    except OSError:
        return False
