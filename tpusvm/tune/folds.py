"""Deterministic stratified k-fold splitting.

Generalises `tpusvm.data.partition` — which cuts CONTIGUOUS shards for the
cascade scatter (and can hand a shard a class-starved chunk on sorted data;
see the stratified option added there alongside this module) — to the
validation-split shape model selection needs: every fold's train and val
sides carry both classes at (as near as integer-divisibility allows) the
global class ratio, and the split is a pure function of (Y, k, seed), so a
tune run is reproducible row-for-row across platforms.

Construction: per class, the row indices are shuffled by a seeded
`np.random.default_rng` and dealt round-robin to the k folds. Round-robin
(rather than contiguous slicing of the shuffled list) guarantees per-class
fold counts differ by at most one even when the class count is not a
multiple of k — the same reasoning as the partitioner's stratified mode.
"""

from __future__ import annotations

from typing import List, NamedTuple

import numpy as np


class Fold(NamedTuple):
    """One CV split. Indices are into the original row order.

    train_idx is SHUFFLED (class-interleaved by construction, then mixed by
    a seeded permutation) so that prefix subsets of it — the successive-
    halving rungs — are themselves unbiased stratified-ish samples; a
    sorted train_idx would make small rungs echo whatever order the caller
    stored the data in (the exact hazard the stratified partitioner exists
    to kill).
    """

    train_idx: np.ndarray  # (n_train,) int32, shuffled
    val_idx: np.ndarray    # (n_val,) int32, sorted


def stratified_kfold(Y: np.ndarray, k: int, seed: int = 0) -> List[Fold]:
    """Split rows into k stratified folds; returns one Fold per held-out part.

    Y must be a 1-D label array (any hashable dtype; the binary {+1,-1}
    convention is not assumed, so multi-class tuning can reuse this).
    Every row lands in exactly one fold's val side. Requires every class to
    have at least k members — a class that cannot appear in each fold would
    make some folds' val metric structurally blind to it, which silently
    corrupts CV comparisons (better to fail loudly and let the caller lower
    k).
    """
    Y = np.asarray(Y)
    if Y.ndim != 1:
        raise ValueError(f"Y must be 1-D, got shape {Y.shape}")
    n = len(Y)
    if not 2 <= k <= n:
        raise ValueError(f"need 2 <= k <= n rows, got k={k}, n={n}")
    rng = np.random.default_rng(seed)
    classes = np.unique(Y)
    member = [[] for _ in range(k)]
    for c in classes:
        idx = np.flatnonzero(Y == c)
        if len(idx) < k:
            raise ValueError(
                f"class {c!r} has {len(idx)} rows < k={k} folds; every fold "
                "needs at least one validation member per class (lower k)"
            )
        rng.shuffle(idx)
        for f in range(k):
            member[f].extend(idx[f::k])
    folds = []
    for f in range(k):
        val = np.sort(np.asarray(member[f], np.int32))
        mask = np.ones(n, bool)
        mask[val] = False
        train = np.flatnonzero(mask).astype(np.int32)
        # mix the class-interleaved order so rung prefixes are random draws
        rng_f = np.random.default_rng(seed + 7919 * (f + 1))
        rng_f.shuffle(train)
        folds.append(Fold(train_idx=train, val_idx=val))
    return folds
