"""Hyperparameter search for the TPU-native SVM stack (`tpusvm tune`).

The reference project hard-codes a single (C, gamma) pair per dataset
(main3.cpp:308-347) and validates it by cross-implementation parity alone;
model selection happens off-stage. This package makes it a first-class,
benchmarked workload built out of capabilities the codebase already has:

  - `folds`   — deterministic, stratified k-fold splitting (generalises the
    cascade's contiguous `data.partition` to label-balanced validation
    splits);
  - `grid`    — the (C, gamma) search space: explicit value lists, snake
    traversal order, log-space geometry;
  - `warm`    — the warm-start policy: seed each point's alphas from its
    nearest already-solved neighbour in log-(C, gamma) space, made feasible
    for the new box constraint (the same dormant solver capability the
    cascade uses when feeding SVs up the merge tree,
    `blocked_smo_solve(alpha0=..., warm_start=True)`);
  - `search`  — the driver: grid and successive-halving schedules over
    fold x point fits with shared per-fold artifact caches (scaled X, row
    norms) and plateau early-stopping;
  - `results` — the versioned `TuneResult` JSON artifact (winner, per-point
    table, update counts) in the house format-versioned persistence style
    (`models/serialization.py`).
"""

from tpusvm.tune.folds import Fold, stratified_kfold
from tpusvm.tune.grid import GridSpec, log_grid, make_grid
from tpusvm.tune.results import (
    TuneResult,
    format_table,
    is_tune_result,
    load_tune_result,
    save_tune_result,
)
from tpusvm.tune.search import TuneConfig, normalize_kernel_specs, tune

__all__ = [
    "normalize_kernel_specs",
    "Fold",
    "stratified_kfold",
    "GridSpec",
    "log_grid",
    "make_grid",
    "TuneConfig",
    "tune",
    "TuneResult",
    "format_table",
    "save_tune_result",
    "load_tune_result",
    "is_tune_result",
]
