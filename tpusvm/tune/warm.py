"""Warm-start seeding across grid points and halving rungs.

The solver capability this converts into an end-to-end win already exists:
`blocked_smo_solve(alpha0=..., warm_start=True)` rebuilds the error vector
from the seeded alphas with one blocked MXU matvec — the cascade uses it
when feeding merged SV sets up the tree (mpi_svm_main3.cpp:156-186
semantics). Until now nothing else exercised it. During a grid sweep,
adjacent points in (log C, log gamma) share most of their active set, so
seeding a fit from its nearest already-solved neighbour's alphas skips the
bulk of the cold-start SMO updates (measured in
benchmarks/results/tune_sweep_cpu.jsonl).

Two corrections make an arbitrary donor solution a VALID seed:

  - box feasibility: the donor's alphas are clipped into the recipient's
    [0, C] box (a donor with larger C can exceed it);
  - equality-constraint repair: pairwise SMO updates preserve
    sum(alpha_i * y_i) exactly, so a seed that violates the dual equality
    constraint (after clipping, or after rung resizing dropped rows) would
    pin that violation into every iterate; the heavier class side is
    scaled down so sum(alpha[y=+1]) == sum(alpha[y=-1]) again. Scaling
    DOWN keeps box feasibility for free.

Across successive-halving rungs the row sets are nested prefixes of each
fold's fixed shuffled order, so a previous rung's solution transfers by
zero-padding the new rows (`solver.blocked.pad_alpha0` — the resume-shape
helper); new rows start at alpha=0 exactly as cold SMO would start them.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from tpusvm.solver.blocked import pad_alpha0
from tpusvm.tune.grid import nearest_point


def feasible_seed(alpha: np.ndarray, Y: np.ndarray, C: float) -> np.ndarray:
    """Project a donor alpha vector into the recipient's feasible set.

    Clip to [0, C], then restore sum(alpha * y) == 0 by scaling down the
    class side carrying more dual mass. If either side ends at zero mass
    the whole seed collapses to zeros (an all-one-sided seed cannot
    satisfy the equality constraint except trivially) — the caller then
    just runs a cold start.
    """
    a = np.clip(np.asarray(alpha, np.float64), 0.0, C)
    y = np.asarray(Y)
    pos = y > 0
    s_pos = float(a[pos].sum())
    s_neg = float(a[~pos].sum())
    if s_pos <= 0.0 or s_neg <= 0.0:
        return np.zeros_like(a)
    if s_pos > s_neg:
        a[pos] *= s_neg / s_pos
    elif s_neg > s_pos:
        a[~pos] *= s_pos / s_neg
    return a


def deployed_seed(sv_ids: np.ndarray, sv_alpha: np.ndarray, n_rows: int,
                  Y: np.ndarray, C: float) -> np.ndarray:
    """Full-length alpha0 for a refresh fit, from a DEPLOYED model's SV set.

    The online-learning warm start (`tpusvm refresh`): the deployed
    artifact stores only its support vectors' (sv_ids, sv_alpha); the
    refresh training set must keep the deployed run's rows as a PREFIX
    (new data appends — the stream.ShardWriter tail-shard contract), so
    the donor solution scatters back to full length at its original row
    positions, new rows start at alpha=0 exactly as cold SMO would
    start them (the pad_alpha0 semantics, by construction), and the
    result is projected feasible for the refresh problem's labels/box
    (feasible_seed — the scaler refit may have moved the geometry, but
    a feasible seed is a valid seed regardless).
    """
    ids = np.asarray(sv_ids, np.int64)
    if ids.size and int(ids.max()) >= n_rows:
        raise ValueError(
            f"deployed model's SV ids reach row {int(ids.max())} but the "
            f"refresh training set has only {n_rows} rows — refresh "
            "requires the deployed run's rows as a prefix of the new data"
        )
    a = np.zeros(n_rows, np.float64)
    a[ids] = np.asarray(sv_alpha, np.float64)
    return feasible_seed(a, Y, C)


def deployed_seed_ovr(sv_ids: np.ndarray, coef: np.ndarray, n_rows: int,
                      labels: np.ndarray, classes: np.ndarray,
                      C: float) -> np.ndarray:
    """(K, n) per-head alpha0 seeds for an OvR refresh, from the
    deployed artifact's signed coefficients.

    The OvR state stores coef = alpha * y per head over the SV union
    (models/ovr.py), so each head's duals recover as |coef[k]| (alpha is
    non-negative and y carries the sign). Every head scatters to its
    original row positions (the shared prefix-extension contract) and is
    projected feasible against ITS one-vs-rest labels — the heads share
    rows but not label vectors, so the equality-constraint repair is
    per-head."""
    ids = np.asarray(sv_ids, np.int64)
    if ids.size and int(ids.max()) >= n_rows:
        raise ValueError(
            f"deployed OvR model's SV ids reach row {int(ids.max())} but "
            f"the refresh training set has only {n_rows} rows — refresh "
            "requires the deployed run's rows as a prefix of the new data"
        )
    coef = np.asarray(coef, np.float64)
    labels = np.asarray(labels)
    seeds = np.zeros((len(classes), n_rows), np.float64)
    for k, c in enumerate(classes):
        a = np.zeros(n_rows, np.float64)
        a[ids] = np.abs(coef[k])
        yk = np.where(labels == c, 1, -1).astype(np.int32)
        seeds[k] = feasible_seed(a, yk, C)
    return seeds


def deployed_seed_svr(sv_ids: np.ndarray, sv_coef: np.ndarray,
                      n_rows: int, C: float) -> np.ndarray:
    """Doubled-variable beta0 seed (length 2n) for an SVR refresh.

    The SVR state stores signed coef_i = alpha_i - alpha*_i; at any SMO
    optimum the twin duals never overlap (alpha_i * alpha*_i == 0), so
    the doubling inverts exactly: beta_i = max(coef_i, 0) on the +1 half
    and beta_{n+i} = max(-coef_i, 0) on the -1 half
    (tpusvm.kernels.svr.doubled_problem's label convention). Projected
    feasible against the doubled labels — sum(coef) == 0 at the donor
    optimum, so the repair only bites after box clipping."""
    ids = np.asarray(sv_ids, np.int64)
    if ids.size and int(ids.max()) >= n_rows:
        raise ValueError(
            f"deployed SVR model's SV ids reach row {int(ids.max())} but "
            f"the refresh training set has only {n_rows} rows — refresh "
            "requires the deployed run's rows as a prefix of the new data"
        )
    coef = np.asarray(sv_coef, np.float64)
    beta = np.zeros(2 * n_rows, np.float64)
    beta[ids] = np.maximum(coef, 0.0)
    beta[n_rows + ids] = np.maximum(-coef, 0.0)
    Y2 = np.concatenate([np.ones(n_rows, np.int32),
                         -np.ones(n_rows, np.int32)])
    return feasible_seed(beta, Y2, C)


class WarmStore:
    """Per-fold memory of solved points' alphas, queried by log-space
    nearest neighbour.

    Keyed by grid point; each entry keeps only the LATEST (largest-rung)
    alpha per fold — earlier rungs are strictly dominated as seeds. Alphas
    are host-side numpy (the store outlives any single device computation
    and a tune run can hold hundreds of entries).
    """

    def __init__(self):
        # fold -> point -> alpha (np.ndarray, length = that fit's rows)
        self._store: Dict[int, Dict[Tuple[float, float], np.ndarray]] = {}

    def record(self, fold: int, point: Tuple[float, float],
               alpha: np.ndarray) -> None:
        self._store.setdefault(fold, {})[point] = np.asarray(alpha)

    def seed(self, fold: int, point: Tuple[float, float], n_rows: int,
             Y_sub: np.ndarray, C: float) -> Optional[np.ndarray]:
        """Best available seed for `point` at `n_rows` training rows, or
        None (cold start). Preference order:

          1. the SAME point's previous-rung solution (strongest prior —
             the optimisation problem only gained rows);
          2. the nearest already-solved neighbour in (log C, log gamma).

        Either donor is resized with pad_alpha0 and projected feasible; a
        seed that projects to all-zeros is reported as None so callers
        don't pay the warm-start f reconstruction for a cold state.
        """
        entries = self._store.get(fold)
        if not entries:
            return None
        if point in entries:
            donor = entries[point]
        else:
            pts: List[Tuple[float, float]] = list(entries)
            donor = entries[pts[nearest_point(point, pts)]]
        a = feasible_seed(pad_alpha0(donor, n_rows), Y_sub, C)
        return a if a.any() else None
