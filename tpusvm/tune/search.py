"""The tune driver: fold x point scheduling, shared caches, warm chaining.

Two schedules over a GridSpec:

  - "grid": every point, full training folds, in snake order (so each fit
    warm-starts from an adjacent solved point), with optional plateau
    early-stopping;
  - "halving": successive halving over data-subset rungs (Li et al. 2018,
    Hyperband's inner loop): all points are fit on a small stratified
    subset first, the best 1/eta survive to an eta-times-larger subset,
    repeating until the full fold — so hopeless corners of the grid cost a
    small-rung fit instead of a full one. Rung subsets are nested prefixes
    of each fold's fixed shuffled row order, which makes a point's
    previous-rung solution a valid (zero-padded) warm seed for its next
    rung.

Cost structure the driver is built around (what "embarrassingly parallel in
exactly the ways this codebase is already good at" means concretely):

  - per fold, the scaled training matrix, its row norms (sq_norms), and the
    scaled validation side are computed ONCE and reused by every fit and
    every evaluation at every grid point — a gamma sweep at fixed fold
    re-streams zero feature bytes for setup (the norms thread into
    `blocked_smo_solve(sn=...)` and `rbf_cross(snA=, snB=)`);
  - the k fold fits of a point are dispatched before any result is
    materialised (JAX dispatch is async), so they pipeline on device and
    overlap with each other instead of running strictly back-to-back;
  - every rung uses ONE uniform subset size across folds (the minimum of
    the per-fold cap), so each rung compiles the solver exactly once
    instead of once per ±1-row fold-size variant.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from tpusvm import kernels as _kernels
from tpusvm.config import SVMConfig, resolve_accum_dtype
from tpusvm.data.scaler import MinMaxScaler
from tpusvm.ops.rbf import coef_matvec, sq_norms
from tpusvm.solver.blocked import blocked_smo_solve
from tpusvm.status import Status, TuneStatus
from tpusvm.tune.folds import Fold, stratified_kfold
from tpusvm.tune.grid import GridSpec
from tpusvm.tune.results import TuneResult
from tpusvm.tune.warm import WarmStore


@dataclasses.dataclass(frozen=True)
class TuneConfig:
    """Search-level knobs (the per-point SVM hyperparameters come from the
    grid; numerical tolerances from the base SVMConfig passed to tune()).

    Attributes:
      folds: stratified CV fold count k (>= 2).
      seed: fold-split / rung-subset shuffle seed — the whole run is a
        pure function of (data, grid, config), so cold/warm A/Bs compare
        identical problems.
      schedule: "grid" or "halving".
      eta: halving aggressiveness — rung subsets grow by eta, the best
        ceil(1/eta) fraction of points survives each rung (>= 2).
      min_rung: smallest rung subset size (halving); rungs run
        min_rung, min_rung*eta, ..., full fold.
      warm_start: seed each fit from the nearest solved neighbour /
        previous rung (tpusvm.tune.warm); False = every fit cold — the
        benchmark's control arm.
      patience: grid schedule only — stop the sweep after this many
        consecutive points that fail to improve the best CV accuracy by
        more than plateau_tol (None = sweep every point). Unvisited
        points are recorded as SKIPPED. Ignored by halving (its pruning
        already bounds the cost of bad points).
      plateau_tol: minimum improvement that resets the patience counter.
      fleet: dispatch each rung's point population as ONE batched fleet
        launch per fold (tpusvm.fleet) instead of per-point sequential
        fits — the B grid points share the fold's scaled X (and its
        cached norms), differing only in (C, gamma), which is exactly
        the fleet's problem axis; (C, gamma) enter the launch as arrays,
        so the whole sweep reuses one compiled program per
        (bucket, rung-size). Warm seeding still works across RUNGS (a
        point's previous-rung solution seeds its next-rung lane), but
        not across points WITHIN a rung — the rung solves concurrently,
        so there is no "already-solved neighbour" to borrow from;
        expect slightly more updates per rung in exchange for the
        batched launch. Incompatible with patience (a plateau stop is a
        sequential notion). The sequential dispatch path remains the
        default and is what --no-fleet selects from the CLI.
      fleet_compact: fleet only — compact_every rounds between
        problem-axis compactions (tpusvm.fleet.fleet_train); 0 = one
        monolithic launch per (fold, rung).
    """

    folds: int = 3
    seed: int = 0
    schedule: str = "grid"
    eta: int = 3
    min_rung: int = 256
    warm_start: bool = True
    patience: Optional[int] = None
    plateau_tol: float = 0.0
    fleet: bool = False
    fleet_compact: int = 0

    def __post_init__(self):
        if self.schedule not in ("grid", "halving"):
            raise ValueError(
                f"schedule must be grid|halving, got {self.schedule!r}"
            )
        if self.folds < 2:
            raise ValueError(f"folds must be >= 2, got {self.folds}")
        if self.eta < 2:
            raise ValueError(f"eta must be >= 2, got {self.eta}")
        if self.min_rung < 2:
            raise ValueError(f"min_rung must be >= 2, got {self.min_rung}")
        if self.patience is not None and self.patience < 1:
            raise ValueError(f"patience must be >= 1, got {self.patience}")
        if self.fleet and self.patience is not None:
            raise ValueError(
                "fleet=True fits a whole rung's points in one batched "
                "launch; patience (a sequential plateau stop) cannot "
                "apply — drop one of the two"
            )
        if self.fleet_compact < 0:
            raise ValueError(
                f"fleet_compact must be >= 0, got {self.fleet_compact}"
            )


class _FoldCache:
    """Per-fold shared artifacts: scaled X (train fold order / val), row
    norms, labels. Built once; every grid point's fit and eval reuse the
    same device arrays, and rung subsets are prefix slices (so even the
    norms cache is shared across rungs).

    rows_fn(indices) -> (len(indices), d) raw feature rows — a slice of
    the in-memory array, or stream.gather_rows against a sharded dataset
    (which loads only the shards carrying the fold's rows); the cache is
    agnostic to where the bytes come from."""

    def __init__(self, rows_fn, Y: np.ndarray, fold: Fold, dtype,
                 scale: bool):
        Xtr = rows_fn(fold.train_idx)
        Xval = rows_fn(fold.val_idx)
        if scale:
            scaler = MinMaxScaler().fit(Xtr)
            Xtr = scaler.transform(Xtr)
            Xval = scaler.transform(Xval)
        self.Xtr = jnp.asarray(Xtr, dtype)
        self.Ytr = jnp.asarray(Y[fold.train_idx])
        self.Ytr_host = np.asarray(Y[fold.train_idx])
        self.sn = sq_norms(self.Xtr)          # one X stream, whole sweep
        self.Xval = jnp.asarray(Xval, dtype)
        self.sn_val = sq_norms(self.Xval)
        self.Yval = np.asarray(Y[fold.val_idx])
        self.n_train = len(fold.train_idx)


def _rung_sizes(n_full: int, min_rung: int, eta: int) -> List[int]:
    if min_rung >= n_full:
        return [n_full]
    sizes = []
    s = min_rung
    while s < n_full:
        sizes.append(s)
        s *= eta
    sizes.append(n_full)
    return sizes


def _point_row(C: float, gamma: float, spec: Dict[str, Any]
               ) -> Dict[str, Any]:
    return {
        "C": C, "gamma": gamma, "kernel": spec["kernel"],
        "degree": spec["degree"], "coef0": spec["coef0"],
        "status": TuneStatus.SKIPPED.name,
        "rung": -1, "n_subset": 0, "cv_accuracy": None,
        "fold_accuracy": [], "sv_count": None, "n_updates": 0,
        "wall_s": 0.0, "warm_seeded": 0,
    }


def normalize_kernel_specs(kernel_specs, base: SVMConfig) -> List[Dict[str, Any]]:
    """Kernel-family search axis -> full {kernel, degree, coef0} dicts.

    Accepts None (search only the base config's family), bare family
    names, or partial dicts; degree/coef0 default from the base config.
    Duplicate fully-resolved specs are rejected (they would silently
    double the search cost and make the winner tie-break order-dependent).
    """
    if kernel_specs is None:
        kernel_specs = [base.kernel]
    out = []
    for spec in kernel_specs:
        if isinstance(spec, str):
            spec = {"kernel": spec}
        family = _kernels.validate_family(spec.get("kernel", base.kernel))
        if _kernels.is_approx(family):
            # explicit interop decision (no silent wrong-answer path):
            # tune sweeps gamma as a TRACED scalar over shared fold
            # caches, but an approx family bakes gamma into its feature
            # map — every gamma cell would need its own mapped fold
            # caches and its own warm store, which is a different search
            # architecture (a map-aware tune is a future PR)
            raise ValueError(
                f"tune does not search approximate kernel families "
                f"({family!r}): gamma parameterises the feature map "
                "itself (tpusvm.approx), so the shared-fold-cache "
                "(C, gamma) sweep cannot apply; tune the exact 'rbf' "
                "family and train the chosen (C, gamma) with "
                f"kernel={family!r}, or sweep approx fits explicitly "
                "with benchmarks/approx_scale.py"
            )
        resolved = {
            "kernel": family,
            "degree": int(spec.get("degree", base.degree)),
            "coef0": float(spec.get("coef0", base.coef0)),
        }
        if resolved in out:
            raise ValueError(f"duplicate kernel spec {resolved}")
        out.append(resolved)
    return out


def tune(
    X: Optional[np.ndarray],
    Y: Optional[np.ndarray],
    grid: GridSpec,
    config: TuneConfig = TuneConfig(),
    *,
    base: SVMConfig = SVMConfig(),
    dtype=jnp.float32,
    accum_dtype="auto",
    scale: bool = True,
    solver_opts: Optional[dict] = None,
    log_fn: Optional[Callable[[str], None]] = None,
    dataset=None,
    tracer=None,
    kernels=None,
) -> TuneResult:
    """Cross-validated search over `grid` (x kernel families); returns the
    TuneResult table.

    base: numerical-tolerance donor (tau/eps/sv_tol/max_iter); its C and
    gamma are ignored — the grid supplies those per point. Fits use the
    blocked solver with the fold's cached row norms; extra static knobs
    (q, max_inner, ...) pass through solver_opts.

    kernels: optional kernel-family search axis — a list of family names
    or {kernel, degree, coef0} dicts (normalize_kernel_specs; None =
    search only base.kernel). Each family runs the full (C, gamma)
    schedule over the SAME fold caches (scaled X / norms / labels are
    kernel-independent, so the per-fold setup is paid once for the whole
    matrix) with its OWN warm-start store — duals do not transfer across
    kernel geometries — and the winner is the global cv_accuracy argmax,
    carrying its kernel/degree/coef0 alongside C and gamma.

    dataset: a stream.ShardedDataset used INSTEAD of (X, Y) — pass None
    for both. Folds are computed from a labels-only manifest pass
    (identical splits to the in-memory path: stratified_kfold is a pure
    function of (Y, k, seed)), and each fold cache gathers only its own
    rows, shard by shard (stream.gather_rows), so the monolithic array is
    never materialised — peak residency is the fold caches plus one shard.

    tracer: an obs.trace.Tracer; every scored point then lands as a
    `tune.point` event (C, gamma, rung, subset size, CV accuracy, update
    count, warm-seed count) and the winner as `tune.winner` — the search
    trajectory in the run's one trace file.
    """
    if dataset is not None:
        if X is not None or Y is not None:
            raise ValueError("tune: pass (X, Y) or dataset=, not both")
        from tpusvm.stream.assign import gather_rows

        Y = dataset.load_labels()
        n_rows, n_feat = dataset.n_rows, dataset.n_features

        def rows_fn(idx):
            return gather_rows(dataset, idx)
    else:
        X = np.asarray(X)
        Y = np.asarray(Y)
        n_rows, n_feat = X.shape

        def rows_fn(idx):
            return X[idx]
    accum = resolve_accum_dtype(accum_dtype)
    opts = dict(solver_opts or {})
    say = log_fn or (lambda msg: None)
    t_run = time.perf_counter()

    folds = stratified_kfold(Y, config.folds, seed=config.seed)
    caches = [_FoldCache(rows_fn, Y, f, dtype, scale) for f in folds]
    n_full = min(c.n_train for c in caches)  # uniform rung cap: one
    # compiled solver shape per rung instead of one per ±1-row fold size
    points = grid.points()
    specs = normalize_kernel_specs(kernels, base)
    all_rows: List[Dict[str, Any]] = []

    def run_family(spec: Dict[str, Any]) -> List[Dict[str, Any]]:
        """One kernel family's full (C, gamma) schedule over the shared
        fold caches, with its own warm store."""
        rows = [_point_row(C, g, spec) for C, g in points]
        store = WarmStore()
        rbf = spec["kernel"] == "rbf"
        kern = dict(kernel=spec["kernel"], degree=spec["degree"],
                    coef0=spec["coef0"])

        def seeds_for(pi: int, m: int) -> List[Optional[np.ndarray]]:
            """Per-fold warm seeds for one point (None entries = cold)."""
            if not config.warm_start:
                return [None] * len(caches)
            C = points[pi][0]
            return [store.seed(fi, points[pi], m, c.Ytr_host[:m], C)
                    for fi, c in enumerate(caches)]

        def fit_point(pi: int, m: int, rung: int) -> Dict[str, Any]:
            """All k fold fits of one point at rung size m: seeds first,
            then every solve dispatched, then one materialisation pass."""
            C, gamma = points[pi]
            t0 = time.perf_counter()
            seeds = seeds_for(pi, m)
            results = []
            for c, seed in zip(caches, seeds):
                alpha0 = None if seed is None else jnp.asarray(seed, accum)
                results.append(blocked_smo_solve(
                    c.Xtr[:m], c.Ytr[:m], alpha0=alpha0,
                    warm_start=seed is not None,
                    # the norms cache only exists for the RBF family
                    sn=c.sn[:m] if rbf else None,
                    C=C, gamma=gamma, eps=base.eps, tau=base.tau,
                    max_iter=base.max_iter, accum_dtype=accum, **kern,
                    **opts,
                ))
            return score_point(pi, m, rung, results, seeds,
                               time.perf_counter() - t0)

        def fit_points_fleet(pis: List[int], m: int,
                             rung: int) -> List[Dict[str, Any]]:
            """One rung's whole point population, one fleet launch per
            fold: the B points share the fold's scaled rows and cached
            norms and differ only in (C, gamma) — exactly the fleet's
            problem axis (tpusvm.fleet). Seeds are queried BEFORE the
            launches (previous rungs only — the rung solves
            concurrently, so same-rung neighbour seeding cannot
            happen); the launch wall is attributed evenly across the
            rung's points."""
            from tpusvm.fleet import fleet_train

            t0 = time.perf_counter()
            seeds = {pi: seeds_for(pi, m) for pi in pis}
            Cs = [points[pi][0] for pi in pis]
            gs = [points[pi][1] for pi in pis]
            fold_results = []
            for fi, c in enumerate(caches):
                al0 = [seeds[pi][fi] for pi in pis]
                outs = fleet_train(
                    c.Xtr[:m], [c.Ytr_host[:m]] * len(pis), Cs, gs,
                    alpha0s=(al0 if any(a is not None for a in al0)
                             else None),
                    sn=c.sn[:m] if rbf else None,
                    compact_every=config.fleet_compact,
                    eps=base.eps, tau=base.tau, max_iter=base.max_iter,
                    accum_dtype=accum, **kern, **opts,
                )
                fold_results.append(outs)
            solve_share = (time.perf_counter() - t0) / max(1, len(pis))
            return [
                score_point(pi, m, rung,
                            [fold_results[fi][j]
                             for fi in range(len(caches))],
                            seeds[pi], solve_share)
                for j, pi in enumerate(pis)
            ]

        def score_point(pi: int, m: int, rung: int, results, seeds,
                        solve_s: float) -> Dict[str, Any]:
            """Materialise + score one point's fold results into its row
            (shared by the sequential and fleet dispatch paths)."""
            C, gamma = points[pi]
            row = rows[pi]
            t_eval = time.perf_counter()
            accs, svs, updates = [], [], 0
            for fi, (c, res) in enumerate(zip(caches, results)):
                alpha = np.asarray(res.alpha)  # completion barrier
                store.record(fi, points[pi], alpha)
                coef = jnp.asarray(alpha * c.Ytr_host[:m], dtype)
                K_val = _kernels.cross(
                    spec["kernel"], c.Xval, c.Xtr[:m], gamma=gamma,
                    coef0=spec["coef0"], degree=spec["degree"],
                    snA=c.sn_val if rbf else None,
                    snB=c.sn[:m] if rbf else None,
                )
                scores = np.asarray(
                    coef_matvec(K_val, coef) - jnp.asarray(res.b, dtype)
                )
                pred = np.where(scores > 0, 1, -1)
                accs.append(float((pred == c.Yval).mean()))
                svs.append(int((alpha > base.sv_tol).sum()))
                updates += int(res.n_iter) - 1
                status = Status(int(res.status))
                if status not in (Status.CONVERGED, Status.NO_WORKING_SET):
                    say(f"tune: point (C={C:g}, gamma={gamma:g}, "
                        f"kernel={spec['kernel']}) fold {fi} "
                        f"ended {status.name}")
            wall = solve_s + (time.perf_counter() - t_eval)
            row.update(
                rung=rung, n_subset=m,
                cv_accuracy=float(np.mean(accs)), fold_accuracy=accs,
                sv_count=float(np.mean(svs)),
                n_updates=row["n_updates"] + updates,
                wall_s=row["wall_s"] + wall,
                warm_seeded=row["warm_seeded"]
                + sum(s is not None for s in seeds),
            )
            if tracer is not None:
                tracer.event(
                    "tune.point", C=C, gamma=gamma, rung=rung, n_subset=m,
                    kernel=spec["kernel"],
                    cv_accuracy=row["cv_accuracy"], n_updates=updates,
                    warm_seeded=sum(s is not None for s in seeds),
                    wall_s=wall,
                )
            return row

        if config.schedule == "grid":
            if config.fleet:
                # the whole grid is one rung: one fleet launch per fold
                # trains every point's fit together (patience is
                # rejected by TuneConfig — there is no sequential sweep
                # to stop early)
                for row in fit_points_fleet(list(range(len(points))),
                                            n_full, rung=0):
                    row["status"] = TuneStatus.EVALUATED.name
                    say(f"tune: [{spec['kernel']}] C={row['C']:g} "
                        f"gamma={row['gamma']:g} "
                        f"cv={row['cv_accuracy']:.4f} "
                        f"updates={row['n_updates']} (fleet)")
            else:
                best = -np.inf
                since_improve = 0
                for pi in range(len(points)):
                    row = fit_point(pi, n_full, rung=0)
                    row["status"] = TuneStatus.EVALUATED.name
                    say(f"tune: [{spec['kernel']}] C={row['C']:g} "
                        f"gamma={row['gamma']:g} "
                        f"cv={row['cv_accuracy']:.4f} "
                        f"updates={row['n_updates']} "
                        f"warm={row['warm_seeded']}/{config.folds}")
                    if row["cv_accuracy"] > best + config.plateau_tol:
                        best = row["cv_accuracy"]
                        since_improve = 0
                    else:
                        since_improve += 1
                    if config.patience and since_improve >= config.patience:
                        say(f"tune: plateau after {pi + 1}/{len(points)} "
                            f"points (no improvement in {since_improve})")
                        break
        else:
            survivors = list(range(len(points)))
            sizes = _rung_sizes(n_full, config.min_rung, config.eta)
            for rung, m in enumerate(sizes):
                last = rung == len(sizes) - 1
                if config.fleet:
                    # the rung's surviving points as one fleet launch
                    # per fold — previous-rung seeds still apply (each
                    # lane warm-starts from ITS OWN last solution)
                    fit_points_fleet(survivors, m, rung=rung)
                else:
                    for pi in survivors:
                        fit_point(pi, m, rung=rung)
                say(f"tune: [{spec['kernel']}] rung {rung} (m={m}) "
                    f"scored {len(survivors)} points"
                    + (" (fleet)" if config.fleet else ""))
                # rank: best CV accuracy first, solve order breaks ties
                # deterministically
                ranked = sorted(
                    survivors,
                    key=lambda pi: (-rows[pi]["cv_accuracy"], pi),
                )
                if last:
                    for pi in survivors:
                        rows[pi]["status"] = TuneStatus.EVALUATED.name
                else:
                    keep = max(1, -(-len(survivors) // config.eta))
                    for pi in ranked[keep:]:
                        rows[pi]["status"] = TuneStatus.PRUNED.name
                    survivors = sorted(ranked[:keep])
        return rows

    for spec in specs:
        all_rows.extend(run_family(spec))

    evaluated = [r for r in all_rows
                 if r["status"] == TuneStatus.EVALUATED.name]
    if not evaluated:  # unreachable: both schedules evaluate >= 1 point
        raise RuntimeError("tune evaluated no grid points")
    win = max(evaluated, key=lambda r: r["cv_accuracy"])  # first max wins
    winner = {"C": win["C"], "gamma": win["gamma"],
              "kernel": win["kernel"], "degree": win["degree"],
              "coef0": win["coef0"],
              "cv_accuracy": win["cv_accuracy"]}
    say(f"tune: winner kernel={win['kernel']} C={win['C']:g} "
        f"gamma={win['gamma']:g} cv={win['cv_accuracy']:.4f}")
    if tracer is not None:
        tracer.event("tune.winner", **winner)
    return TuneResult(
        fleet=config.fleet,
        schedule=config.schedule,
        grid={"C_values": list(grid.C_values),
              "gamma_values": list(grid.gamma_values)},
        folds=config.folds,
        seed=config.seed,
        n=int(n_rows),
        d=int(n_feat),
        warm_start=config.warm_start,
        kernels=specs,
        points=all_rows,
        winner=winner,
        total_updates=int(sum(r["n_updates"] for r in all_rows)),
        wall_s=time.perf_counter() - t_run,
    )
