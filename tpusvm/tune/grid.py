"""The (C, gamma) search space and its log-space geometry.

SVM hyperparameter response surfaces are smooth in (log C, log gamma) —
the standard grid-search practice (and the reason warm-starting from a
log-space neighbour works: nearby points share most of their active set).
This module owns the space itself: explicit value lists, the snake
traversal order that maximises step-to-step adjacency for the warm-start
chain, and the log-space distance the nearest-neighbour seeding keys on.
"""

from __future__ import annotations

import math
from typing import List, NamedTuple, Sequence, Tuple


class GridSpec(NamedTuple):
    """Cartesian (C, gamma) grid. Values must be positive (log-space)."""

    C_values: Tuple[float, ...]
    gamma_values: Tuple[float, ...]

    @property
    def shape(self) -> Tuple[int, int]:
        return (len(self.C_values), len(self.gamma_values))

    def points(self) -> List[Tuple[float, float]]:
        """All (C, gamma) points in snake order: C ascending, gamma
        alternating direction per C-row, so consecutive points differ in
        exactly one coordinate by one grid step — every fit after the
        first has an immediately-adjacent already-solved neighbour to
        warm-start from."""
        Cs = sorted(self.C_values)
        gs = sorted(self.gamma_values)
        pts = []
        for i, C in enumerate(Cs):
            row = gs if i % 2 == 0 else gs[::-1]
            pts.extend((C, g) for g in row)
        return pts


def make_grid(C_values: Sequence[float],
              gamma_values: Sequence[float]) -> GridSpec:
    Cs = tuple(float(c) for c in C_values)
    gs = tuple(float(g) for g in gamma_values)
    if not Cs or not gs:
        raise ValueError("grid needs at least one C and one gamma value")
    if any(v <= 0 for v in Cs + gs):
        raise ValueError("C and gamma grid values must be positive "
                         "(the search space is log-scaled)")
    if len(set(Cs)) != len(Cs) or len(set(gs)) != len(gs):
        raise ValueError("grid values must be distinct")
    return GridSpec(C_values=Cs, gamma_values=gs)


def log_grid(center_C: float, center_gamma: float, span: int = 2,
             step: float = 4.0) -> GridSpec:
    """A (2*span+1)^2 grid of multiplicative `step`s around a center point.

    The zero-config search space: centered on the caller's best guess
    (e.g. the reference's preset constants), step=4 covers ~2.4 decades
    per axis at span=2 — the coarse pass of the classic two-stage grid
    refinement.
    """
    if span < 0:
        raise ValueError(f"span must be >= 0, got {span}")
    if step <= 1.0:
        raise ValueError(f"step must be > 1, got {step}")
    return make_grid(
        [center_C * step ** e for e in range(-span, span + 1)],
        [center_gamma * step ** e for e in range(-span, span + 1)],
    )


def log_distance(a: Tuple[float, float], b: Tuple[float, float]) -> float:
    """Euclidean distance in (log C, log gamma) space."""
    return math.hypot(math.log(a[0]) - math.log(b[0]),
                      math.log(a[1]) - math.log(b[1]))


def nearest_point(target: Tuple[float, float],
                  candidates: Sequence[Tuple[float, float]]) -> int:
    """Index of the log-space-nearest candidate; ties break to the earliest
    (solve-order) candidate so the choice is deterministic."""
    if not candidates:
        raise ValueError("no candidates")
    best, best_d = 0, float("inf")
    for i, c in enumerate(candidates):
        d = log_distance(target, c)
        if d < best_d:
            best, best_d = i, d
    return best
