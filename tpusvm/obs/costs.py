"""Normalise XLA executable cost/memory accounting across backends.

`Compiled.cost_analysis()` is the compiler's own static estimate of what
an executable does (FLOPs, bytes touched); `memory_analysis()` is the
allocator's view (argument/output/temp bytes). Both are best-effort
surfaces: the shape of the return value has changed across jax releases
(dict vs list-of-dicts), some backends return nothing, and the key names
carry spaces ("bytes accessed"). This module is the single place that
flattens all of that into plain floats, so the profiler (obs.prof), the
report renderer and tests never touch the raw structures.

The derived figure everything downstream wants is ARITHMETIC INTENSITY
(FLOPs per byte accessed) — the roofline x-axis. The solver ROADMAP item
(mixed-precision/fused-selection ladder) starts from exactly this table:
an executable far below the machine's FLOPs/byte ridge point is
bandwidth- or latency-bound and bf16 MXU work will not move it.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

# cost_analysis keys, as emitted by XLA (spaces included)
_FLOPS_KEY = "flops"
_BYTES_KEY = "bytes accessed"


def _as_entries(raw: Any):
    """cost_analysis() has returned a dict (new jax) or a list of dicts
    (one per computation, older jax); normalise to a list of dicts."""
    if raw is None:
        return []
    if isinstance(raw, dict):
        return [raw]
    if isinstance(raw, (list, tuple)):
        return [e for e in raw if isinstance(e, dict)]
    return []


def cost_summary(compiled) -> Dict[str, Any]:
    """{"available", "flops", "bytes_accessed"} for one executable.

    available=False (values None) when the backend provides no cost
    model — the caller must SAY so (`cost_analysis: unavailable`), never
    silently report zeros a dashboard would read as "free"."""
    try:
        entries = _as_entries(compiled.cost_analysis())
    except Exception:  # noqa: BLE001 — any backend refusal means "absent"
        entries = []
    flops = bytes_accessed = None
    for e in entries:
        if _FLOPS_KEY in e:
            flops = (flops or 0.0) + float(e[_FLOPS_KEY])
        if _BYTES_KEY in e:
            bytes_accessed = (bytes_accessed or 0.0) + float(e[_BYTES_KEY])
    return {
        "available": flops is not None or bytes_accessed is not None,
        "flops": flops,
        "bytes_accessed": bytes_accessed,
    }


def memory_summary(compiled) -> Dict[str, Any]:
    """{"available", "arg_bytes", "out_bytes", "temp_bytes",
    "code_bytes"} from memory_analysis(), where the backend provides it."""
    try:
        mem = compiled.memory_analysis()
    except Exception:  # noqa: BLE001
        mem = None
    if mem is None:
        return {"available": False, "arg_bytes": None, "out_bytes": None,
                "temp_bytes": None, "code_bytes": None}

    def _get(*names):
        for n in names:
            v = getattr(mem, n, None)
            if v is not None:
                return float(v)
        return None

    return {
        "available": True,
        "arg_bytes": _get("argument_size_in_bytes"),
        "out_bytes": _get("output_size_in_bytes"),
        "temp_bytes": _get("temp_size_in_bytes"),
        "code_bytes": _get("generated_code_size_in_bytes"),
    }


def arithmetic_intensity(flops: Optional[float],
                         bytes_accessed: Optional[float]) -> Optional[float]:
    """FLOPs per byte accessed (the roofline x-coordinate), or None when
    either side is unknown or the byte count is zero."""
    if flops is None or not bytes_accessed:
        return None
    return flops / bytes_accessed


def compile_record(name: str, lower_s: float, compile_s: float,
                   compiled=None, **extra: Any) -> Dict[str, Any]:
    """One flat JSON-able record describing a compile: timings + cost +
    memory + arithmetic intensity. The shared shape written to trace
    events (`prof.compile`) and rendered by the report's compile table."""
    rec: Dict[str, Any] = {
        "executable": name,
        "lower_s": float(lower_s),
        "compile_s": float(compile_s),
    }
    cost = (cost_summary(compiled) if compiled is not None
            else {"available": False, "flops": None, "bytes_accessed": None})
    rec["cost_available"] = cost["available"]
    rec["flops"] = cost["flops"]
    rec["bytes_accessed"] = cost["bytes_accessed"]
    rec["arith_intensity"] = arithmetic_intensity(cost["flops"],
                                                  cost["bytes_accessed"])
    mem = (memory_summary(compiled) if compiled is not None
           else {"available": False})
    if mem["available"]:
        rec["arg_bytes"] = mem["arg_bytes"]
        rec["out_bytes"] = mem["out_bytes"]
        rec["temp_bytes"] = mem["temp_bytes"]
    rec.update(extra)
    return rec
