"""On-device solver convergence telemetry: the host-side half.

The paper's entire subject — the Keerthi gap b_low - b_high collapsing to
2*tau — was invisible at runtime: the solver runs as ONE lax.while_loop
and materialises nothing until it terminates. The wrong fix is a host
callback per round (jax.debug.print / io_callback — a device->host round
trip inside the hot loop, now linted against as JX009). The right fix is
the one the solver already uses for its RESULT: carry the telemetry in
the loop state and materialise it once at the end.

blocked_smo_solve(telemetry=T) threads a fixed-size ring of T slots
through the outer-loop carry; every outer iteration writes its gap,
inner-update count and end-of-round status into slot (i mod T) — pure
scatter-into-carry, zero host syncs, bit-transparent to alpha/f (the
telemetry arrays are written, never read, by the solve; asserted by
tests/test_obs.py). The device half lives in solver/blocked.py; this
module owns the dtype-free pieces: the result container, the ring
unwrap, and the trace/table adapters.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple

import numpy as np


class ConvergenceTelemetry(NamedTuple):
    """Device-side ring carried through the solver (leaves are arrays).

    gap:    (T,) accum dtype — b_low - b_high at each recorded round
            (NaN where no working set existed that round).
    n_upd:  (T,) int32 — inner alpha updates the round performed.
    status: (T,) int32 — Status value the round ended with.
    count:  scalar int32 — total rounds recorded (may exceed T: the ring
            then holds the LAST T rounds).
    active: (T,) int32 — live (unfrozen) rows that round: the active-set
            size under the shrinking heuristic (= all valid rows when
            shrink tracking is off). None on rings recorded before
            round 9.
    """

    gap: Any
    n_upd: Any
    status: Any
    count: Any
    active: Any = None


def materialize(tele: ConvergenceTelemetry) -> Dict[str, Any]:
    """Unwrap the ring into oldest-first host arrays.

    Returns {"gap", "updates", "status" (np arrays, oldest round first),
    "rounds_recorded" (total rounds the solver ran, >= len(gap) when the
    ring wrapped), "wrapped" (bool)}.
    """
    gap = np.asarray(tele.gap)
    n_upd = np.asarray(tele.n_upd)
    status = np.asarray(tele.status)
    count = int(tele.count)
    T = gap.shape[0]
    if count <= T:
        order = np.arange(count)
    else:
        order = (count + np.arange(T)) % T  # oldest surviving slot first
    out = {
        "gap": gap[order],
        "updates": n_upd[order],
        "status": status[order],
        "rounds_recorded": count,
        "wrapped": count > T,
    }
    if tele.active is not None:
        out["active"] = np.asarray(tele.active)[order]
    return out


def to_trace_events(tracer, conv: Dict[str, Any]) -> None:
    """Write a materialized telemetry dict as convergence.round events
    (the records `tpusvm report` renders as the gap table)."""
    from tpusvm.status import Status

    first = conv["rounds_recorded"] - len(conv["gap"]) + 1
    active = conv.get("active")
    for i in range(len(conv["gap"])):
        g = float(conv["gap"][i])
        extra = {} if active is None else {"active": int(active[i])}
        tracer.event(
            "convergence.round",
            round=first + i,
            gap=None if np.isnan(g) else g,
            updates=int(conv["updates"][i]),
            status=Status(int(conv["status"][i])).name,
            **extra,
        )


def format_gap_table(conv: Dict[str, Any], max_rows: int = 40) -> str:
    """Human-readable gap table straight from a materialized dict (the
    same renderer `tpusvm report` uses on trace files)."""
    from tpusvm.obs.report import format_convergence_table
    from tpusvm.status import Status

    first = conv["rounds_recorded"] - len(conv["gap"]) + 1
    active = conv.get("active")
    rows = []
    for i in range(len(conv["gap"])):
        g = float(conv["gap"][i])
        row = {
            "round": first + i,
            "gap": None if np.isnan(g) else g,
            "updates": int(conv["updates"][i]),
            "status": Status(int(conv["status"][i])).name,
        }
        if active is not None:
            row["active"] = int(active[i])
        rows.append(row)
    return format_convergence_table(rows, max_rows=max_rows)
