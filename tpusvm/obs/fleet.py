"""Fleet metrics aggregation: one merged view over many processes.

Every tpusvm process already keeps its counters in an obs.registry
whose snapshots merge exactly (`merge_snapshots` is associative and
commutative — the property PR 5 built in for precisely this). This
module is the cross-process consumer:

  * each process exports ONE payload (`snapshot_payload`):
    ``{"v": 1, "role": ..., "instance": ..., "pid": ..., "status": {...},
    "snapshot": <registry snapshot>}`` — over HTTP (`/metrics.json` on
    serve replicas and the router), over the pod socket protocol (the
    coordinator's ``snapshot`` op), or as an on-disk drop for processes
    with no listener at all (autopilot; `write_snapshot_file`, staged +
    fsync_replace so a crash never publishes a torn file);
  * `merge_fleet` tags every metric entry with (role, instance) labels
    and folds the payloads with `merge_snapshots` — the merged page IS
    the sum of the per-process pages, exactly, which is the acceptance
    contract `tpusvm fleet-metrics` is tested against;
  * `FleetCollector` owns the scrape loop (injectable fetch + clock,
    owned background thread per JXC205: daemon=True AND stop() joins),
    derives per-process rates (qps) from counter deltas between
    scrapes, and feeds the renderers: `render_fleet_text` (one
    fleet-wide Prometheus page), `fleet_json`, and `format_top`
    (the `tpusvm top` table).
"""

from __future__ import annotations

import json
import os
import threading
import urllib.error
import urllib.request
from typing import Any, Callable, Dict, List, Optional, Tuple

from tpusvm.obs.registry import (SNAPSHOT_VERSION, merge_snapshots,
                                 render_snapshot_text)

FLEET_SCHEMA_VERSION = 1

#: counters whose per-scrape delta defines a process's qps in `top`
RATE_COUNTERS = ("serve.ok", "router.requests", "pod.worker_requests")


# --------------------------------------------------------------- payloads
def snapshot_payload(role: str, instance: str, snapshot: dict,
                     status: Optional[dict] = None,
                     pid: Optional[int] = None) -> dict:
    """The one-process export every fleet source speaks."""
    if snapshot.get("v") != SNAPSHOT_VERSION:
        raise ValueError(
            f"unsupported metrics snapshot version {snapshot.get('v')!r}")
    return {"v": FLEET_SCHEMA_VERSION, "role": role, "instance": instance,
            "pid": os.getpid() if pid is None else int(pid),
            "status": status or {}, "snapshot": snapshot}


def parse_payload(obj: Any) -> dict:
    """Validate a fleet payload read off the wire/disk; ValueError on
    junk or an unknown schema version."""
    if not isinstance(obj, dict) or obj.get("v") != FLEET_SCHEMA_VERSION:
        raise ValueError(
            f"not a fleet snapshot payload (v={None if not isinstance(obj, dict) else obj.get('v')!r}, "
            f"this build reads v{FLEET_SCHEMA_VERSION})")
    for k in ("role", "instance", "snapshot"):
        if k not in obj:
            raise ValueError(f"fleet payload missing {k!r}")
    return obj


def tag_snapshot(snap: dict, **labels: str) -> dict:
    """A copy of a registry snapshot with `labels` merged into every
    entry's label set. Fleet labels (role/instance) take precedence over
    same-named process-local labels — the collector's identity
    assignment must win, or two processes could alias one series."""
    out = []
    for e in snap["metrics"]:
        out.append({**e, "labels": {**e["labels"],
                                    **{k: str(v) for k, v in labels.items()}}})
    return {"v": snap["v"], "metrics": out}


def merge_fleet(payloads) -> dict:
    """Fold per-process payloads into ONE registry snapshot, every entry
    tagged with its origin (role, instance). Being a `merge_snapshots`
    fold over label-disjoint entries, the merged page equals the union
    of the per-process pages exactly — counter totals included."""
    tagged = [tag_snapshot(p["snapshot"], role=p["role"],
                           instance=p["instance"]) for p in payloads]
    if not tagged:
        return {"v": SNAPSHOT_VERSION, "metrics": []}
    return merge_snapshots(*tagged)


# ---------------------------------------------------------- on-disk drops
def write_snapshot_file(path: str, payload: dict) -> None:
    """Publish a payload for HTTP-less processes (autopilot): staged
    write beside the target + fsync_replace, so a reader never sees a
    torn JSON file and a crash mid-write leaves the previous drop."""
    from tpusvm.utils.durable import fsync_replace

    parse_payload(payload)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(json.dumps(payload, sort_keys=True))
        f.flush()
    fsync_replace(tmp, path)


def read_snapshot_file(path: str) -> dict:
    with open(path) as f:
        return parse_payload(json.load(f))


# -------------------------------------------------------------- transport
def http_fetch_json(url: str, timeout_s: float = 2.0) -> Any:
    """GET a JSON document (the collector's default fetch; injectable)."""
    with urllib.request.urlopen(url, timeout=timeout_s) as resp:
        return json.loads(resp.read())


class FleetView:
    """One scrape's outcome: per-process payloads, per-source errors,
    and the merged fleet snapshot."""

    def __init__(self, processes: List[dict], errors: Dict[str, str],
                 merged: dict, scraped_at: float):
        self.processes = processes
        self.errors = errors
        self.merged = merged
        self.scraped_at = scraped_at


class FleetCollector:
    """Scrapes fleet sources into one merged view.

    Sources (added once, scraped every pass):
      * `add_replica(url)`  — GET <url>/metrics.json (a serve replica or
        any process exporting a fleet payload over HTTP);
      * `add_router(url)`   — GET <url>/fleet/metrics.json and adopt the
        router's already-collected process list (a collector can chain
        through a router instead of knowing every replica);
      * `add_file(path)`    — read an on-disk drop (autopilot);
      * `add_callable(fn)`  — fn() -> payload (the pod coordinator wraps
        its snapshot-over-socket op this way; also the test seam).

    `scrape_once()` is the synchronous test surface. `start()` runs it
    on an owned background thread (`tpusvm top`'s refresher): daemon=True
    AND stop() joins — the JXC205 teardown discipline `stop_http_server`
    set for the repo. fetch and clock are injectable so renderer tests
    and rate math are deterministic.
    """

    def __init__(self, fetch: Callable[..., Any] = http_fetch_json,
                 clock: Optional[Callable[[], float]] = None,
                 timeout_s: float = 2.0):
        import time

        self._fetch = fetch
        self._clock = clock or time.monotonic
        self.timeout_s = timeout_s
        self._sources: List[Tuple[str, str, Any]] = []
        self._lock = threading.Lock()
        self._view: Optional[FleetView] = None
        # (role, instance) -> {counter: (total, t)} from the previous
        # scrape; written under _lock with _rates (rates() reads there)
        self._prev: Dict[Tuple[str, str], Dict[str, Tuple[float, float]]] = {}
        self._rates: Dict[Tuple[str, str], Dict[str, float]] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ sources
    def add_replica(self, url: str) -> "FleetCollector":
        self._sources.append(("replica", url.rstrip("/"), None))
        return self

    def add_router(self, url: str) -> "FleetCollector":
        self._sources.append(("router", url.rstrip("/"), None))
        return self

    def add_file(self, path: str) -> "FleetCollector":
        self._sources.append(("file", path, None))
        return self

    def add_callable(self, fn: Callable[[], Any],
                     name: str = "callable") -> "FleetCollector":
        self._sources.append(("call", name, fn))
        return self

    # ------------------------------------------------------------- scrape
    def _scrape_source(self, kind: str, name: str, spec: Any) -> List[dict]:
        if kind == "replica":
            return [parse_payload(self._fetch(name + "/metrics.json",
                                              timeout_s=self.timeout_s))]
        if kind == "router":
            doc = self._fetch(name + "/fleet/metrics.json",
                              timeout_s=self.timeout_s)
            if not isinstance(doc, dict) or not isinstance(
                    doc.get("processes"), list):
                raise ValueError(f"{name}: not a fleet page: {doc!r}")
            return [parse_payload(p) for p in doc["processes"]]
        if kind == "file":
            return [read_snapshot_file(name)]
        out = spec()
        if isinstance(out, list):
            return [parse_payload(p) for p in out]
        return [parse_payload(out)]

    def _update_rates(self, processes: List[dict], now: float) -> None:
        nxt: Dict[Tuple[str, str], Dict[str, Tuple[float, float]]] = {}
        rates: Dict[Tuple[str, str], Dict[str, float]] = {}
        for p in processes:
            key = (p["role"], p["instance"])
            totals = {
                e["name"]: float(e["value"])
                for e in p["snapshot"]["metrics"]
                if e["type"] == "counter" and e["name"] in RATE_COUNTERS
            }
            nxt[key] = {k: (v, now) for k, v in totals.items()}
            prev = self._prev.get(key, {})
            r: Dict[str, float] = {}
            for k, v in totals.items():
                if k in prev:
                    pv, pt = prev[k]
                    dt = now - pt
                    if dt > 0 and v >= pv:
                        r[k] = (v - pv) / dt
            if r:
                # qps = the sum of this process's request-counter rates
                rates[key] = {"qps": sum(r.values()), **r}
        with self._lock:
            self._prev = nxt
            self._rates = rates

    def scrape_once(self) -> FleetView:
        """One pass over every source; errors are per-source data, not
        collector crashes (a down replica is a row in `errors`)."""
        processes: List[dict] = []
        errors: Dict[str, str] = {}
        for kind, name, spec in self._sources:
            try:
                processes.extend(self._scrape_source(kind, name, spec))
            except Exception as e:  # noqa: BLE001 — a dead source is a
                # fleet observation, not a scrape failure
                errors[name] = f"{type(e).__name__}: {e}"
        now = self._clock()
        self._update_rates(processes, now)
        view = FleetView(processes, errors, merge_fleet(processes), now)
        with self._lock:
            self._view = view
        return view

    def view(self) -> Optional[FleetView]:
        """The last scrape's view (immutable after publication)."""
        with self._lock:
            return self._view

    def rates(self) -> Dict[Tuple[str, str], Dict[str, float]]:
        """Per-(role, instance) counter rates from the last two scrapes.
        Empty until a second scrape has produced deltas."""
        with self._lock:
            return dict(self._rates)

    # ------------------------------------------------------------- thread
    def start(self, interval_s: float = 2.0) -> "FleetCollector":
        """Begin background refreshing (the `tpusvm top` loop)."""
        if interval_s <= 0:
            raise ValueError(f"interval_s must be > 0, got {interval_s}")
        if self._thread is not None:
            raise RuntimeError("fleet collector already started")
        self.scrape_once()  # a first view before the caller renders

        def run():
            while not self._stop.wait(interval_s):
                try:
                    self.scrape_once()
                except Exception:  # noqa: BLE001 — keep scraping; the
                    # per-source errors dict is the reporting channel
                    pass

        # tpusvm: guarded-by=owner-only lifecycle; start/stop run on the owning thread, the scrape thread never touches _thread
        self._thread = threading.Thread(target=run, daemon=True,
                                        name="tpusvm-fleet-collector")
        self._thread.start()
        return self

    def stop(self, timeout_s: float = 5.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout_s)
            # tpusvm: guarded-by=owner-only lifecycle; cleared after the joined thread exited
            self._thread = None

    def __enter__(self) -> "FleetCollector":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.stop()


# -------------------------------------------------------------- renderers
def render_fleet_text(view: FleetView, prefix: str = "tpusvm") -> str:
    """One fleet-wide Prometheus page: the merged snapshot rendered by
    the standard registry renderer, prefixed with provenance comments."""
    head = [f"# fleet: {len(view.processes)} process(es), "
            f"{len(view.errors)} error(s)"]
    head += [f"# fleet error: {name}: {err}"
             for name, err in sorted(view.errors.items())]
    return "\n".join(head) + "\n" + render_snapshot_text(
        view.merged, prefix=prefix)


def fleet_json(view: FleetView) -> dict:
    """The /fleet/metrics.json document: per-process payloads + merged."""
    return {"v": FLEET_SCHEMA_VERSION, "processes": view.processes,
            "errors": view.errors, "merged": view.merged}


def _counter_total(snap: dict, name: str) -> Optional[float]:
    vals = [e["value"] for e in snap["metrics"]
            if e["type"] == "counter" and e["name"] == name]
    return float(sum(vals)) if vals else None


def _gauge_value(snap: dict, name: str) -> Optional[float]:
    vals = [e["value"] for e in snap["metrics"]
            if e["type"] == "gauge" and e["name"] == name]
    return max(float(v) for v in vals) if vals else None


def top_rows(view: FleetView,
             rates: Optional[Dict[Tuple[str, str], Dict[str, float]]] = None
             ) -> List[dict]:
    """One row per process for the `top` table, sorted (role, instance).

    Role-specific columns come from each payload's status block (serve:
    per-model generation/breaker/p99/burn summarized to the worst model;
    pod workers: live_shards gauge); absent facts render as "-"."""
    rates = rates or {}
    rows = []
    for p in view.processes:
        status = p.get("status") or {}
        models = status.get("models") or {}
        gens = [m.get("generation") for m in models.values()
                if isinstance(m, dict) and m.get("generation") is not None]
        breakers = [m.get("breaker") for m in models.values()
                    if isinstance(m, dict) and m.get("breaker")]
        p99s = [m.get("p99_s") for m in models.values()
                if isinstance(m, dict) and m.get("p99_s") is not None]
        burning = any(m.get("burning") for m in models.values()
                      if isinstance(m, dict))
        worst_breaker = None
        for state in ("open", "half-open", "closed"):
            if state in breakers:
                worst_breaker = state
                break
        key = (p["role"], p["instance"])
        rows.append({
            "role": p["role"],
            "instance": p["instance"],
            "pid": p.get("pid"),
            "generation": max(gens) if gens else status.get("generation"),
            "qps": (rates.get(key) or {}).get("qps"),
            "p99_s": max(p99s) if p99s else None,
            "burn": burning if models else status.get("burning"),
            "breaker": worst_breaker or status.get("breaker"),
            "live_shards": _gauge_value(p["snapshot"], "pod.live_shards"),
            "requests": _counter_total(
                p["snapshot"], {"serve": "serve.ok",
                                "router": "router.requests",
                                "pod-worker": "pod.worker_requests"
                                }.get(p["role"], "")),
        })
    rows.sort(key=lambda r: (r["role"], str(r["instance"])))
    return rows


_TOP_COLUMNS = ("ROLE", "INSTANCE", "PID", "GEN", "REQS", "QPS",
                "P99MS", "BURN", "BREAKER", "SHARDS")


def _top_cell(row: dict, col: str) -> str:
    if col == "ROLE":
        return row["role"]
    if col == "INSTANCE":
        return str(row["instance"])
    if col == "PID":
        return "-" if row["pid"] is None else str(row["pid"])
    if col == "GEN":
        return "-" if row["generation"] is None else str(row["generation"])
    if col == "REQS":
        return "-" if row["requests"] is None else f"{row['requests']:.0f}"
    if col == "QPS":
        return "-" if row["qps"] is None else f"{row['qps']:.1f}"
    if col == "P99MS":
        return ("-" if row["p99_s"] is None
                else f"{row['p99_s'] * 1e3:.1f}")
    if col == "BURN":
        return "-" if row["burn"] is None else ("yes" if row["burn"] else "no")
    if col == "BREAKER":
        return row["breaker"] or "-"
    if col == "SHARDS":
        return ("-" if row["live_shards"] is None
                else f"{row['live_shards']:.0f}")
    raise KeyError(col)


def format_top(rows: List[dict], errors: Optional[Dict[str, str]] = None,
               clock_s: Optional[float] = None) -> str:
    """Render the fleet table (pure function of its inputs — goldens
    pass fixed rows and a fixed clock and diff the exact string)."""
    grid = [list(_TOP_COLUMNS)]
    grid += [[_top_cell(r, c) for c in _TOP_COLUMNS] for r in rows]
    widths = [max(len(row[i]) for row in grid)
              for i in range(len(_TOP_COLUMNS))]
    lines = []
    if clock_s is not None:
        lines.append(f"tpusvm fleet — {len(rows)} process(es) — "
                     f"t={clock_s:.1f}s")
    lines += ["  ".join(cell.ljust(w) for cell, w in zip(row, widths)).rstrip()
              for row in grid]
    for name, err in sorted((errors or {}).items()):
        lines.append(f"! {name}: {err}")
    return "\n".join(lines) + "\n"
