"""Schema-versioned JSONL span/event tracing + the PhaseTimer adapter.

One trace file per run, one JSON object per line. Record kinds:

  {"v": 1, "kind": "meta",  "t0": ..., "wall": ..., "argv": [...]}
  {"v": 1, "kind": "span",  "id": 3, "parent": 1, "name": "training",
   "t0": ..., "t1": ..., "dur_s": ..., "attrs": {...}}
  {"v": 1, "kind": "event", "id": 7, "parent": 3, "name": "cascade.round",
   "ts": ..., "attrs": {...}}
  {"v": 1, "kind": "end",   "t1": ..., "total_s": ...}

Spans nest (per thread — each thread keeps its own open-span stack, so a
serve worker's spans parent correctly without cross-thread races); a
span line is written when the span CLOSES, so the file is append-only
and a crashed run still holds every completed span. Timestamps come from
an injectable monotonic clock — tests pass a counter and get a
bit-stable file; production uses time.perf_counter.

`tpusvm report <trace.jsonl>` renders these files (tpusvm.obs.report);
`read_trace` is the version-gated parser everything shares.

PhaseTimer lives here as a thin span adapter: same accumulate-by-name
surface and the reference's three-line report contract
(`<phase> time: ... s` per phase + `elapsed time:` — SURVEY.md §5.1,
previously implemented standalone in utils/timing.py, which now
re-exports this one), but every phase entry also lands as a span in an
attached Tracer, so cascade rounds, tune points, ingest shards and serve
batches all come out in one trace file.
"""

from __future__ import annotations

import contextlib
import json
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

TRACE_SCHEMA_VERSION = 1


def _jsonable(x: Any) -> Any:
    import numpy as np

    if isinstance(x, np.ndarray):
        return x.tolist()
    if isinstance(x, (np.integer, np.floating, np.bool_)):
        return x.item()
    raise TypeError(f"not JSON-serialisable: {type(x)}")


class Tracer:
    """Append-only JSONL trace writer with nested spans.

    Args:
      path: output file (opened for append so a driver can direct several
        commands at one trace; the meta record delimits each run).
      clock: monotonic float clock — injectable so tests are
        deterministic (default time.perf_counter).
      wall: wall-clock for the meta record only (default time.time).
    """

    def __init__(self, path: str, clock=None, wall=None,
                 argv: Optional[List[str]] = None,
                 max_bytes: Optional[int] = None):
        if max_bytes is not None and max_bytes < 1:
            raise ValueError(f"max_bytes must be >= 1, got {max_bytes}")
        self._clock = clock or time.perf_counter
        self._wall = wall or time.time
        self._lock = threading.Lock()
        self._local = threading.local()
        self._next_id = 0
        self._f = open(path, "a")
        self.path = path
        # size-capped rotation (serve --trace runs for days; an unbounded
        # append-only file is a disk-filler): when the current file would
        # exceed max_bytes it becomes `path.1` (overwriting — the records
        # in the displaced backup are COUNTED as dropped in the registry,
        # obs.trace_dropped_records) and a fresh file starts with a
        # continuation meta record carrying the ORIGINAL t0/wall so span
        # timestamps stay on one clock. None = unbounded (the default).
        self.max_bytes = max_bytes
        self._size = self._f.tell()
        self.rotations = 0
        self._closed = False
        self._meta = {"v": TRACE_SCHEMA_VERSION, "kind": "meta",
                      "t0": self._clock(), "wall": self._wall()}
        self._t0 = self._meta["t0"]
        if argv is not None:
            self._meta["argv"] = list(argv)
        self._write(self._meta)

    # ------------------------------------------------------------ plumbing
    def _rotate_locked(self) -> None:
        import os

        from tpusvm.obs.registry import default_registry

        backup = self.path + ".1"
        dropped = 0
        if os.path.exists(backup):
            with open(backup) as f:
                dropped = sum(1 for line in f if line.strip())
        self._f.close()
        # tpusvm: durable-by=rotation renames already-persisted bytes; either name stays readable and read_trace rejects a torn tail
        os.replace(self.path, backup)
        self._f = open(self.path, "a")
        self._size = 0
        self.rotations += 1
        reg = default_registry()
        reg.counter("obs.trace_rotations").inc()
        if dropped:
            reg.counter("obs.trace_dropped_records").inc(dropped)
        cont = dict(self._meta, rotated=self.rotations)
        line = json.dumps(cont, default=_jsonable)
        self._f.write(line + "\n")
        self._size += len(line) + 1

    def _write(self, rec: dict) -> None:
        line = json.dumps(rec, default=_jsonable)
        with self._lock:
            if self._closed:
                return
            if (self.max_bytes is not None and self._size > 0
                    and self._size + len(line) + 1 > self.max_bytes):
                self._rotate_locked()
            self._f.write(line + "\n")
            self._size += len(line) + 1
            self._f.flush()

    def _stack(self) -> List[int]:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def _new_id(self) -> int:
        with self._lock:
            self._next_id += 1
            return self._next_id

    # ------------------------------------------------------------- surface
    @contextlib.contextmanager
    def span(self, name: str, **attrs: Any) -> Iterator[None]:
        """Nested timed region; the record is written when it closes."""
        sid = self._new_id()
        stack = self._stack()
        parent = stack[-1] if stack else None
        stack.append(sid)
        t0 = self._clock()
        try:
            yield
        finally:
            t1 = self._clock()
            stack.pop()
            self._write({
                "v": TRACE_SCHEMA_VERSION, "kind": "span", "id": sid,
                "parent": parent, "name": name, "t0": t0, "t1": t1,
                "dur_s": t1 - t0, "attrs": attrs,
            })

    def event(self, name: str, **attrs: Any) -> None:
        """Point-in-time record, parented to the innermost open span."""
        stack = self._stack()
        self._write({
            "v": TRACE_SCHEMA_VERSION, "kind": "event",
            "id": self._new_id(),
            "parent": stack[-1] if stack else None,
            "name": name, "ts": self._clock(), "attrs": attrs,
        })

    def metrics_snapshot(self, snapshot: dict) -> None:
        """Embed a registry snapshot (obs.registry) as an event, so one
        trace file carries the run's counters next to its spans."""
        self.event("metrics.snapshot", snapshot=snapshot)

    def close(self) -> None:
        if self._closed:
            return
        t1 = self._clock()
        self._write({"v": TRACE_SCHEMA_VERSION, "kind": "end", "t1": t1,
                     "total_s": t1 - self._t0})
        with self._lock:
            self._closed = True
            self._f.close()

    def __enter__(self) -> "Tracer":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()


def trace_file_set(path: str) -> List[str]:
    """The rotated-set members of a trace, oldest first: `path.K` for
    descending K (higher = older under the shift-up scheme; the default
    single-backup rotation only ever produces `.1`), then `path`."""
    import os
    import re

    d, base = os.path.split(path)
    pat = re.compile(re.escape(base) + r"\.(\d+)$")
    ks = sorted(
        (int(m.group(1)) for f in os.listdir(d or ".")
         if (m := pat.match(f))),
        reverse=True,
    )
    return [f"{path}.{k}" for k in ks] + [path]


def read_trace(path: str) -> List[dict]:
    """Parse a trace file; raises ValueError on schema mismatch.

    A size-capped Tracer leaves a rotated set (`path.1`, then `path`);
    the set is read in rotation order so records stay chronological.
    Blank lines are tolerated (crash-truncated final lines are not —
    a torn record is worth hearing about, not skipping silently)."""
    records: List[dict] = []
    for member in trace_file_set(path):
        records.extend(_read_one_trace(member))
    return records


def _read_one_trace(path: str) -> List[dict]:
    records = []
    with open(path) as f:
        for i, line in enumerate(f, 1):
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except ValueError as e:
                raise ValueError(
                    f"{path}:{i}: not a JSON record ({e}); the trace "
                    "file is corrupt or truncated"
                ) from None
            v = rec.get("v")
            if v != TRACE_SCHEMA_VERSION:
                raise ValueError(
                    f"{path}:{i}: trace schema version {v!r} is not "
                    f"supported (this build reads v{TRACE_SCHEMA_VERSION})"
                )
            records.append(rec)
    return records


class PhaseTimer:
    """Accumulating named phase timer (span adapter).

    >>> t = PhaseTimer()
    >>> with t.phase("train"):
    ...     pass
    >>> t["train"] >= 0
    True

    Phases accumulate across repeated entries (the cascade enters "train"
    once per round). `report()` returns the human-readable summary lines
    in the reference's output contract (SURVEY.md Appendix A: three phase
    timings), listing phases in first-entry order and ending with the
    total. With a tracer attached, every phase entry is ALSO written as a
    span (attrs: phase=True), which is how `tpusvm report` reconstructs
    the same summary from the trace file alone.

    On-device timing caveat: JAX dispatch is asynchronous, so a phase
    that ends while device work is still in flight under-reports.
    Callers must close each phase only after host materialisation of the
    phase's result (np.asarray) — see utils/timing.py's original note.
    """

    def __init__(self, tracer: Optional[Tracer] = None) -> None:
        self._acc: Dict[str, float] = {}
        self._t0 = time.perf_counter()
        self.tracer = tracer

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        span = (self.tracer.span(name, phase=True) if self.tracer
                else contextlib.nullcontext())
        start = time.perf_counter()
        try:
            with span:
                yield
        finally:
            self._acc[name] = self._acc.get(name, 0.0) + (
                time.perf_counter() - start
            )

    def add(self, name: str, seconds: float) -> None:
        """Accumulate an externally-measured duration (e.g. a per-round
        time already captured by cascade_fit's history)."""
        self._acc[name] = self._acc.get(name, 0.0) + seconds

    def __getitem__(self, name: str) -> float:
        return self._acc[name]

    def __contains__(self, name: str) -> bool:
        return name in self._acc

    @property
    def total(self) -> float:
        """Wall-clock since construction (the reference's 'elapsed time')."""
        return time.perf_counter() - self._t0

    def asdict(self) -> Dict[str, float]:
        d = dict(self._acc)
        d["total"] = self.total
        return d

    def report(self) -> str:
        from tpusvm.obs.report import render_phase_lines

        return render_phase_lines(self._acc, self.total)
