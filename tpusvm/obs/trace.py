"""Schema-versioned JSONL span/event tracing + the PhaseTimer adapter.

One trace file per run, one JSON object per line. Record kinds:

  {"v": 1, "kind": "meta",  "t0": ..., "wall": ..., "argv": [...]}
  {"v": 1, "kind": "span",  "id": 3, "parent": 1, "name": "training",
   "t0": ..., "t1": ..., "dur_s": ..., "attrs": {...}}
  {"v": 1, "kind": "event", "id": 7, "parent": 3, "name": "cascade.round",
   "ts": ..., "attrs": {...}}
  {"v": 1, "kind": "end",   "t1": ..., "total_s": ...}

Spans nest (per thread — each thread keeps its own open-span stack, so a
serve worker's spans parent correctly without cross-thread races); a
span line is written when the span CLOSES, so the file is append-only
and a crashed run still holds every completed span. Timestamps come from
an injectable monotonic clock — tests pass a counter and get a
bit-stable file; production uses time.perf_counter.

Cross-process propagation: a Tracer constructed with `role=` can mint a
compact TraceContext (`Tracer.ctx()`) naming (trace_id, innermost open
span id, role, pid). The context travels in pod protocol frames (a free
``ctx`` meta key) or as the ``X-Tpusvm-Trace`` HTTP header, and the
receiving process opens its OWN Tracer with `ctx=` — its meta record
then carries the propagated context, and `tpusvm report` over the merged
files re-parents each file's root spans under the originating span
(obs.report.cross_process_spans). Tracers without a role write exactly
the meta record they always did, byte-for-byte.

`tpusvm report <trace.jsonl>` renders these files (tpusvm.obs.report);
`read_trace` is the version-gated parser everything shares.

PhaseTimer lives here as a thin span adapter: same accumulate-by-name
surface and the reference's three-line report contract
(`<phase> time: ... s` per phase + `elapsed time:` — SURVEY.md §5.1,
previously implemented standalone in utils/timing.py, which now
re-exports this one), but every phase entry also lands as a span in an
attached Tracer, so cascade rounds, tune points, ingest shards and serve
batches all come out in one trace file.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import threading
import time
import uuid
from typing import Any, Dict, Iterator, List, Optional

TRACE_SCHEMA_VERSION = 1

# HTTP header carrying a serialized TraceContext (router → replica).
TRACE_HEADER = "X-Tpusvm-Trace"

# Version prefix of the header wire format; bump on incompatible change.
_CTX_WIRE_VERSION = "1"


@dataclasses.dataclass(frozen=True)
class TraceContext:
    """Compact cross-process trace context.

    Names the span a remote process should parent its own root spans
    under: the originating run's trace_id, the id of the span open at
    mint time (None when minted outside any span — the receiver then
    parents under the origin file's root), and the origin's role/pid so
    the merged report can find the originating trace file.
    """

    trace_id: str
    span_id: Optional[int]
    role: str
    pid: int

    def to_dict(self) -> Dict[str, Any]:
        return {"trace_id": self.trace_id, "span_id": self.span_id,
                "role": self.role, "pid": self.pid}

    @classmethod
    def from_dict(cls, d: Any) -> Optional["TraceContext"]:
        """Parse a ctx dict; returns None on anything malformed (a peer
        speaking a newer/older dialect must degrade to 'no context',
        never to a crash)."""
        if not isinstance(d, dict):
            return None
        trace_id, role, pid = d.get("trace_id"), d.get("role"), d.get("pid")
        span_id = d.get("span_id")
        if not isinstance(trace_id, str) or not isinstance(role, str):
            return None
        if not isinstance(pid, int) or isinstance(pid, bool):
            return None
        if span_id is not None and (
                not isinstance(span_id, int) or isinstance(span_id, bool)):
            return None
        return cls(trace_id=trace_id, span_id=span_id, role=role, pid=pid)

    def to_header(self) -> str:
        """Serialize for the X-Tpusvm-Trace header:
        ``1;<trace_id>;<span_id|->;<role>;<pid>``."""
        sid = "-" if self.span_id is None else str(self.span_id)
        return ";".join([_CTX_WIRE_VERSION, self.trace_id, sid,
                         self.role, str(self.pid)])

    @classmethod
    def from_header(cls, value: Optional[str]) -> Optional["TraceContext"]:
        """Parse a header value; None on absent/junk/unknown version."""
        if not value or not isinstance(value, str):
            return None
        parts = value.strip().split(";")
        if len(parts) != 5 or parts[0] != _CTX_WIRE_VERSION:
            return None
        _, trace_id, sid, role, pid = parts
        if not trace_id or not role:
            return None
        try:
            span_id = None if sid == "-" else int(sid)
            return cls(trace_id=trace_id, span_id=span_id, role=role,
                       pid=int(pid))
        except ValueError:
            return None


def _jsonable(x: Any) -> Any:
    import numpy as np

    if isinstance(x, np.ndarray):
        return x.tolist()
    if isinstance(x, (np.integer, np.floating, np.bool_)):
        return x.item()
    raise TypeError(f"not JSON-serialisable: {type(x)}")


class Tracer:
    """Append-only JSONL trace writer with nested spans.

    Args:
      path: output file (opened for append so a driver can direct several
        commands at one trace; the meta record delimits each run).
      clock: monotonic float clock — injectable so tests are
        deterministic (default time.perf_counter).
      wall: wall-clock for the meta record only (default time.time).
      role: fleet role name ("pod-coordinator", "pod-worker", "router",
        "serve", ...). Setting it marks this tracer as a cross-process
        participant: the meta record gains role/pid/trace_id and
        `ctx()` becomes mintable. Without it the meta record is
        byte-identical to what older builds wrote.
      ctx: the propagated TraceContext this process was SPAWNED with —
        recorded in the meta so the merged report re-parents this
        file's root spans under the originating span. Implies the
        origin's trace_id unless one is given explicitly.
      trace_id: explicit correlation id (tests inject a fixed one;
        default a fresh random id when role is set).
    """

    def __init__(self, path: str, clock=None, wall=None,
                 argv: Optional[List[str]] = None,
                 max_bytes: Optional[int] = None,
                 role: Optional[str] = None,
                 ctx: Optional[TraceContext] = None,
                 trace_id: Optional[str] = None):
        if max_bytes is not None and max_bytes < 1:
            raise ValueError(f"max_bytes must be >= 1, got {max_bytes}")
        if role is not None and ";" in role:
            raise ValueError(f"role must not contain ';': {role!r}")
        self._clock = clock or time.perf_counter
        self._wall = wall or time.time
        self._lock = threading.Lock()
        self._local = threading.local()
        self._next_id = 0
        self._f = open(path, "a")
        self.path = path
        self.role = role
        self.pid = os.getpid()
        if trace_id is None and (role is not None or ctx is not None):
            trace_id = ctx.trace_id if ctx is not None else uuid.uuid4().hex[:16]
        self.trace_id = trace_id
        self.parent_ctx = ctx
        # size-capped rotation (serve --trace runs for days; an unbounded
        # append-only file is a disk-filler): when the current file would
        # exceed max_bytes it becomes `path.1` (overwriting — the records
        # in the displaced backup are COUNTED as dropped in the registry,
        # obs.trace_dropped_records) and a fresh file starts with a
        # continuation meta record carrying the ORIGINAL t0/wall so span
        # timestamps stay on one clock. None = unbounded (the default).
        self.max_bytes = max_bytes
        self._size = self._f.tell()
        self.rotations = 0
        self._closed = False
        self._meta = {"v": TRACE_SCHEMA_VERSION, "kind": "meta",
                      "t0": self._clock(), "wall": self._wall()}
        self._t0 = self._meta["t0"]
        if argv is not None:
            self._meta["argv"] = list(argv)
        # cross-process identity keys are OPT-IN: a role-less, ctx-less
        # tracer keeps writing the exact meta record older builds wrote
        # (deterministic-file tests diff these bytes).
        if self.trace_id is not None:
            self._meta["trace_id"] = self.trace_id
        if role is not None:
            self._meta["role"] = role
            self._meta["pid"] = self.pid
        if ctx is not None:
            self._meta["ctx"] = ctx.to_dict()
        self._write(self._meta)

    # ------------------------------------------------------------ plumbing
    def _rotate_locked(self) -> None:
        import os

        from tpusvm.obs.registry import default_registry

        backup = self.path + ".1"
        dropped = 0
        if os.path.exists(backup):
            with open(backup) as f:
                dropped = sum(1 for line in f if line.strip())
        self._f.close()
        # tpusvm: durable-by=rotation renames already-persisted bytes; either name stays readable and read_trace rejects a torn tail
        os.replace(self.path, backup)
        self._f = open(self.path, "a")
        self._size = 0
        self.rotations += 1
        reg = default_registry()
        reg.counter("obs.trace_rotations").inc()
        if dropped:
            reg.counter("obs.trace_dropped_records").inc(dropped)
        cont = dict(self._meta, rotated=self.rotations)
        line = json.dumps(cont, default=_jsonable)
        self._f.write(line + "\n")
        self._size += len(line) + 1

    def _write(self, rec: dict) -> None:
        line = json.dumps(rec, default=_jsonable)
        with self._lock:
            if self._closed:
                return
            if (self.max_bytes is not None and self._size > 0
                    and self._size + len(line) + 1 > self.max_bytes):
                self._rotate_locked()
            self._f.write(line + "\n")
            self._size += len(line) + 1
            self._f.flush()

    def _stack(self) -> List[int]:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def _new_id(self) -> int:
        with self._lock:
            self._next_id += 1
            return self._next_id

    # ------------------------------------------------------------- surface
    @contextlib.contextmanager
    def span(self, name: str, **attrs: Any) -> Iterator[None]:
        """Nested timed region; the record is written when it closes."""
        sid = self._new_id()
        stack = self._stack()
        parent = stack[-1] if stack else None
        stack.append(sid)
        t0 = self._clock()
        try:
            yield
        finally:
            t1 = self._clock()
            stack.pop()
            self._write({
                "v": TRACE_SCHEMA_VERSION, "kind": "span", "id": sid,
                "parent": parent, "name": name, "t0": t0, "t1": t1,
                "dur_s": t1 - t0, "attrs": attrs,
            })

    def event(self, name: str, **attrs: Any) -> None:
        """Point-in-time record, parented to the innermost open span."""
        stack = self._stack()
        self._write({
            "v": TRACE_SCHEMA_VERSION, "kind": "event",
            "id": self._new_id(),
            "parent": stack[-1] if stack else None,
            "name": name, "ts": self._clock(), "attrs": attrs,
        })

    def ctx(self) -> TraceContext:
        """Mint a TraceContext naming the calling thread's innermost open
        span (None outside any span) as the remote parent. Requires a
        role — anonymous tracers have no fleet identity to propagate."""
        if self.role is None:
            raise ValueError(
                "Tracer.ctx() needs a role= at construction; an anonymous "
                "tracer has no cross-process identity to propagate")
        stack = self._stack()
        return TraceContext(trace_id=self.trace_id,
                            span_id=stack[-1] if stack else None,
                            role=self.role, pid=self.pid)

    def metrics_snapshot(self, snapshot: dict) -> None:
        """Embed a registry snapshot (obs.registry) as an event, so one
        trace file carries the run's counters next to its spans."""
        self.event("metrics.snapshot", snapshot=snapshot)

    def close(self) -> None:
        if self._closed:
            return
        t1 = self._clock()
        self._write({"v": TRACE_SCHEMA_VERSION, "kind": "end", "t1": t1,
                     "total_s": t1 - self._t0})
        with self._lock:
            self._closed = True
            self._f.close()

    def __enter__(self) -> "Tracer":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()


def trace_file_set(path: str) -> List[str]:
    """The rotated-set members of a trace, oldest first: `path.K` for
    descending K (higher = older under the shift-up scheme; the default
    single-backup rotation only ever produces `.1`), then `path`."""
    import os
    import re

    d, base = os.path.split(path)
    pat = re.compile(re.escape(base) + r"\.(\d+)$")
    ks = sorted(
        (int(m.group(1)) for f in os.listdir(d or ".")
         if (m := pat.match(f))),
        reverse=True,
    )
    return [f"{path}.{k}" for k in ks] + [path]


def read_trace(path: str) -> List[dict]:
    """Parse a trace file; raises ValueError on schema mismatch.

    A size-capped Tracer leaves a rotated set (`path.1`, then `path`);
    the set is read in rotation order so records stay chronological.
    Blank lines are tolerated (crash-truncated final lines are not —
    a torn record is worth hearing about, not skipping silently)."""
    records: List[dict] = []
    for member in trace_file_set(path):
        records.extend(_read_one_trace(member))
    return records


def _read_one_trace(path: str) -> List[dict]:
    records = []
    with open(path) as f:
        for i, line in enumerate(f, 1):
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except ValueError as e:
                raise ValueError(
                    f"{path}:{i}: not a JSON record ({e}); the trace "
                    "file is corrupt or truncated"
                ) from None
            v = rec.get("v")
            if v != TRACE_SCHEMA_VERSION:
                raise ValueError(
                    f"{path}:{i}: trace schema version {v!r} is not "
                    f"supported (this build reads v{TRACE_SCHEMA_VERSION})"
                )
            records.append(rec)
    return records


class PhaseTimer:
    """Accumulating named phase timer (span adapter).

    >>> t = PhaseTimer()
    >>> with t.phase("train"):
    ...     pass
    >>> t["train"] >= 0
    True

    Phases accumulate across repeated entries (the cascade enters "train"
    once per round). `report()` returns the human-readable summary lines
    in the reference's output contract (SURVEY.md Appendix A: three phase
    timings), listing phases in first-entry order and ending with the
    total. With a tracer attached, every phase entry is ALSO written as a
    span (attrs: phase=True), which is how `tpusvm report` reconstructs
    the same summary from the trace file alone.

    On-device timing caveat: JAX dispatch is asynchronous, so a phase
    that ends while device work is still in flight under-reports.
    Callers must close each phase only after host materialisation of the
    phase's result (np.asarray) — see utils/timing.py's original note.
    """

    def __init__(self, tracer: Optional[Tracer] = None) -> None:
        self._acc: Dict[str, float] = {}
        self._t0 = time.perf_counter()
        self.tracer = tracer

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        span = (self.tracer.span(name, phase=True) if self.tracer
                else contextlib.nullcontext())
        start = time.perf_counter()
        try:
            with span:
                yield
        finally:
            self._acc[name] = self._acc.get(name, 0.0) + (
                time.perf_counter() - start
            )

    def add(self, name: str, seconds: float) -> None:
        """Accumulate an externally-measured duration (e.g. a per-round
        time already captured by cascade_fit's history)."""
        self._acc[name] = self._acc.get(name, 0.0) + seconds

    def __getitem__(self, name: str) -> float:
        return self._acc[name]

    def __contains__(self, name: str) -> bool:
        return name in self._acc

    @property
    def total(self) -> float:
        """Wall-clock since construction (the reference's 'elapsed time')."""
        return time.perf_counter() - self._t0

    def asdict(self) -> Dict[str, float]:
        d = dict(self._acc)
        d["total"] = self.total
        return d

    def report(self) -> str:
        from tpusvm.obs.report import render_phase_lines

        return render_phase_lines(self._acc, self.total)
