"""Render telemetry back into the reference's human-readable contracts.

The SINGLE render path for end-of-run timing: `render_phase_lines` is the
reference's three-line timing contract (`<phase> time: X.XXX s` per phase
in first-entry order, then `elapsed time:`), used by PhaseTimer.report()
(live runs: cli.py, bench.py) and by `tpusvm report` (trace files) — the
two surfaces can no longer drift apart because they call the same
function.

`render_report` is the `tpusvm report <trace.jsonl>` body: phase summary
reconstructed from phase spans, the convergence-gap table from
convergence.round events, and any embedded metrics snapshots' non-zero
counters.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Tuple


def render_phase_lines(acc: Dict[str, float], total: float) -> str:
    """The reference's end-of-run timing block (SURVEY.md §5.1)."""
    lines = [f"{name} time: {secs:.3f} s" for name, secs in acc.items()]
    lines.append(f"elapsed time: {total:.3f} s")
    return "\n".join(lines)


def phase_summary(records: Iterable[dict]) -> Tuple[Dict[str, float], float]:
    """(accumulated phase durations in first-entry order, total seconds)
    from trace records.

    Phases are spans written with attrs.phase=True (PhaseTimer). Total
    comes from the `end` record when present, else the span envelope.
    Records carrying a `_wall` key (a multi-trace merge —
    merge_trace_files) use the wall-clock envelope instead: per-process
    `end` totals would undercount a run spanning several workers."""
    acc: Dict[str, float] = {}
    total = 0.0
    t_min = t_max = None
    w_min = w_max = None
    for rec in records:
        if "_wall" in rec:
            w = rec["_wall"]
            w_min = w if w_min is None else min(w_min, w)
            w_end = w + rec.get("dur_s", 0.0)
            w_max = w_end if w_max is None else max(w_max, w_end)
        if rec["kind"] == "span":
            t_min = rec["t0"] if t_min is None else min(t_min, rec["t0"])
            t_max = rec["t1"] if t_max is None else max(t_max, rec["t1"])
            if rec.get("attrs", {}).get("phase"):
                name = rec["name"]
                acc[name] = acc.get(name, 0.0) + rec["dur_s"]
        elif rec["kind"] == "end":
            total = rec["total_s"]
    if w_min is not None:
        return acc, w_max - w_min
    if not total and t_min is not None:
        total = t_max - t_min
    return acc, total


def convergence_rows(records: Iterable[dict]) -> List[dict]:
    """The convergence.round events, in file (= round) order."""
    return [r["attrs"] for r in records
            if r["kind"] == "event" and r["name"] == "convergence.round"]


def format_convergence_table(rows: List[dict], max_rows: int = 40) -> str:
    """Fixed-width outer-round table: round, Keerthi gap, updates,
    active-set size (when the ring recorded one — round 9 shrink
    telemetry), status.

    Long runs are elided in the middle (first/last max_rows//2 rounds) —
    the interesting structure is the head (cold-start collapse) and the
    tail (the approach to 2*tau)."""
    if not rows:
        return "no convergence records in this trace"
    has_active = any(r.get("active") is not None for r in rows)
    if has_active:
        head = ["round      gap            updates   active  status",
                "-----      ---            -------   ------  ------"]
    else:
        head = ["round      gap            updates  status",
                "-----      ---            -------  ------"]
    idx = list(range(len(rows)))
    if len(idx) > max_rows:
        k = max_rows // 2
        idx = idx[:k] + [None] + idx[-k:]
    out = list(head)
    for i in idx:
        if i is None:
            out.append(f"  ... {len(rows) - 2 * (max_rows // 2)} "
                       "rounds elided ...")
            continue
        r = rows[i]
        gap = r.get("gap")
        gap_s = f"{gap:.6e}" if gap is not None else "n/a"
        line = (f"{r.get('round', i + 1):>5}  {gap_s:>13}  "
                f"{r.get('updates', 0):>7}")
        if has_active:
            act = r.get("active")
            line += f"  {act if act is not None else 'n/a':>7}"
        out.append(f"{line}  {r.get('status', '?')}")
    return "\n".join(out)


def merge_trace_files(paths: List[str]) -> List[dict]:
    """Records of several trace files interleaved on ONE wall clock.

    Each file's monotonic timestamps are mapped to wall time via its
    meta record (wall - t0), so cascade leaves, fold-parallel tune
    workers and a serve process traced to separate files come out as one
    chronological stream. Every record gains `_wall` (the sort key) and
    `_file` (provenance); metrics snapshots across files still merge
    exactly (nonzero_counters → obs.registry.merge_snapshots)."""
    from tpusvm.obs.trace import read_trace

    out: List[dict] = []
    for p in paths:
        recs = read_trace(p)
        offset = 0.0
        for r in recs:
            if r["kind"] == "meta":
                offset = r.get("wall", 0.0) - r.get("t0", 0.0)
                break
        for r in recs:
            t = r.get("t0", r.get("ts", r.get("t1")))
            rr = dict(r)
            rr["_wall"] = offset + (t if t is not None else 0.0)
            rr["_file"] = p
            out.append(rr)
    out.sort(key=lambda r: r["_wall"])
    return out


def compile_rows(records: Iterable[dict]) -> List[dict]:
    """The prof.compile events (tpusvm.obs.prof), in record order."""
    return [r["attrs"] for r in records
            if r["kind"] == "event" and r["name"] == "prof.compile"]


def format_compile_table(rows: List[dict]) -> str:
    """Per-executable compile/cost table (the observatory's headline).

    One row per executable, compiles and lower/compile seconds summed
    across events, FLOPs / bytes accessed / arithmetic intensity from the
    cost analysis (max across events — re-lowers of one entry point are
    the same program family). Backends without a cost model get an
    explicit `cost_analysis: unavailable` marker, never silent zeros."""
    if not rows:
        return "no compile records in this trace (profiling was off)"
    agg: Dict[str, dict] = {}
    order: List[str] = []
    for r in rows:
        name = r.get("executable", "?")
        a = agg.get(name)
        if a is None:
            agg[name] = a = {"n": 0, "lower_s": 0.0, "compile_s": 0.0,
                             "flops": None, "bytes": None,
                             "available": False}
            order.append(name)
        a["n"] += 1
        a["lower_s"] += r.get("lower_s") or 0.0
        a["compile_s"] += r.get("compile_s") or 0.0
        if r.get("cost_available"):
            a["available"] = True
            for src, dst in (("flops", "flops"),
                             ("bytes_accessed", "bytes")):
                v = r.get(src)
                if v is not None:
                    a[dst] = v if a[dst] is None else max(a[dst], v)
    out = ["executable                        #  lower s  compile s"
           "     GFLOP       MB  FLOP/B",
           "----------                        -  -------  ---------"
           "     -----       --  ------"]
    for name in order:
        a = agg[name]
        left = (f"{name:<32} {a['n']:>2}  {a['lower_s']:>7.3f}  "
                f"{a['compile_s']:>9.3f}")
        if not a["available"]:
            out.append(f"{left}  cost_analysis: unavailable")
            continue
        flops, nbytes = a["flops"], a["bytes"]
        gflop = f"{flops / 1e9:>9.4f}" if flops is not None else "      n/a"
        mb = (f"{nbytes / 1e6:>8.2f}" if nbytes is not None else "     n/a")
        ai = (f"{flops / nbytes:>6.2f}" if flops is not None and nbytes
              else "   n/a")
        out.append(f"{left}  {gflop} {mb}  {ai}")
    return "\n".join(out)


def autopilot_rows(records: Iterable[dict]) -> List[dict]:
    """The autopilot.drift decision events, in tick order."""
    return [r["attrs"] for r in records
            if r["kind"] == "event" and r["name"] == "autopilot.drift"]


def format_autopilot_table(rows: List[dict], max_rows: int = 40) -> str:
    """Per-tick drift-decision table: tick, decision, per-detector
    scores vs their (jittered) thresholds, and the reason string. Long
    runs elide the middle like the convergence table — the interesting
    structure is the warm-up and the ticks around a triggered refresh."""
    if not rows:
        return "no autopilot decisions in this trace"
    out = [" tick  decision  detector scores (score/threshold)",
           " ----  --------  ---------------------------------"]
    idx = list(range(len(rows)))
    if len(idx) > max_rows:
        k = max_rows // 2
        idx = idx[:k] + [None] + idx[-k:]
    for i in idx:
        if i is None:
            out.append(f"  ... {len(rows) - 2 * (max_rows // 2)} "
                       "ticks elided ...")
            continue
        r = rows[i]
        rep = r.get("report", {})
        dets = "  ".join(
            f"{d['name']}={d['score']:.3g}/{d['threshold']:.3g}"
            + ("*" if d.get("triggered") else "")
            for d in rep.get("detectors", []))
        out.append(f"{r.get('tick', i + 1):>5}  "
                   f"{'REFRESH' if r.get('decision') else 'watch':>8}  "
                   f"{dets}")
        if r.get("decision"):
            out.append(f"       reason: {r.get('reason', '?')}")
    return "\n".join(out)


def nonzero_counters(records: Iterable[dict]) -> List[str]:
    """`name{labels} value` lines for every non-zero counter/gauge in
    embedded metrics snapshots (merged when several are present)."""
    from tpusvm.obs.registry import merge_snapshots

    snaps = [r["attrs"]["snapshot"] for r in records
             if r["kind"] == "event" and r["name"] == "metrics.snapshot"]
    if not snaps:
        return []
    merged = merge_snapshots(*snaps)
    lines = []
    for e in merged["metrics"]:
        if e["type"] == "histogram":
            if e["count"]:
                lines.append(f"{e['name']} count={e['count']} "
                             f"sum={e['sum']:g}")
        elif e["value"]:
            lab = ",".join(f"{k}={v}" for k, v in
                           sorted(e["labels"].items()))
            lines.append(f"{e['name']}{'{' + lab + '}' if lab else ''} "
                         f"{e['value']:g}")
    return lines


def render_report(records: List[dict]) -> str:
    """The `tpusvm report` body for one parsed (or merged) trace."""
    acc, total = phase_summary(records)
    spans = sum(1 for r in records if r["kind"] == "span")
    events = sum(1 for r in records if r["kind"] == "event")
    parts = [f"trace: {spans} spans, {events} events", ""]
    comp = compile_rows(records)
    if comp:
        parts += ["compiles (lower/compile wall time, "
                  "XLA cost analysis):",
                  format_compile_table(comp), ""]
    conv = convergence_rows(records)
    parts += ["convergence (b_low - b_high per outer round):",
              format_convergence_table(conv), ""]
    auto = autopilot_rows(records)
    if auto:
        parts += ["autopilot (drift decisions per tick):",
                  format_autopilot_table(auto), ""]
    counters = nonzero_counters(records)
    if counters:
        parts += ["counters:"] + ["  " + line for line in counters] + [""]
    parts.append(render_phase_lines(acc, total))
    return "\n".join(parts)
