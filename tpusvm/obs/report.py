"""Render telemetry back into the reference's human-readable contracts.

The SINGLE render path for end-of-run timing: `render_phase_lines` is the
reference's three-line timing contract (`<phase> time: X.XXX s` per phase
in first-entry order, then `elapsed time:`), used by PhaseTimer.report()
(live runs: cli.py, bench.py) and by `tpusvm report` (trace files) — the
two surfaces can no longer drift apart because they call the same
function.

`render_report` is the `tpusvm report <trace.jsonl>` body: phase summary
reconstructed from phase spans, the convergence-gap table from
convergence.round events, and any embedded metrics snapshots' non-zero
counters.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Tuple


def render_phase_lines(acc: Dict[str, float], total: float) -> str:
    """The reference's end-of-run timing block (SURVEY.md §5.1)."""
    lines = [f"{name} time: {secs:.3f} s" for name, secs in acc.items()]
    lines.append(f"elapsed time: {total:.3f} s")
    return "\n".join(lines)


def phase_summary(records: Iterable[dict]) -> Tuple[Dict[str, float], float]:
    """(accumulated phase durations in first-entry order, total seconds)
    from trace records.

    Phases are spans written with attrs.phase=True (PhaseTimer). Total
    comes from the `end` record when present, else the span envelope.
    Records carrying a `_wall` key (a multi-trace merge —
    merge_trace_files) use the wall-clock envelope instead: per-process
    `end` totals would undercount a run spanning several workers."""
    acc: Dict[str, float] = {}
    total = 0.0
    t_min = t_max = None
    w_min = w_max = None
    for rec in records:
        if "_wall" in rec:
            w = rec["_wall"]
            w_min = w if w_min is None else min(w_min, w)
            w_end = w + rec.get("dur_s", 0.0)
            w_max = w_end if w_max is None else max(w_max, w_end)
        if rec["kind"] == "span":
            t_min = rec["t0"] if t_min is None else min(t_min, rec["t0"])
            t_max = rec["t1"] if t_max is None else max(t_max, rec["t1"])
            if rec.get("attrs", {}).get("phase"):
                name = rec["name"]
                acc[name] = acc.get(name, 0.0) + rec["dur_s"]
        elif rec["kind"] == "end":
            total = rec["total_s"]
    if w_min is not None:
        return acc, w_max - w_min
    if not total and t_min is not None:
        total = t_max - t_min
    return acc, total


def convergence_rows(records: Iterable[dict]) -> List[dict]:
    """The convergence.round events, in file (= round) order."""
    return [r["attrs"] for r in records
            if r["kind"] == "event" and r["name"] == "convergence.round"]


def format_convergence_table(rows: List[dict], max_rows: int = 40) -> str:
    """Fixed-width outer-round table: round, Keerthi gap, updates,
    active-set size (when the ring recorded one — round 9 shrink
    telemetry), status.

    Long runs are elided in the middle (first/last max_rows//2 rounds) —
    the interesting structure is the head (cold-start collapse) and the
    tail (the approach to 2*tau)."""
    if not rows:
        return "no convergence records in this trace"
    has_active = any(r.get("active") is not None for r in rows)
    if has_active:
        head = ["round      gap            updates   active  status",
                "-----      ---            -------   ------  ------"]
    else:
        head = ["round      gap            updates  status",
                "-----      ---            -------  ------"]
    idx = list(range(len(rows)))
    if len(idx) > max_rows:
        k = max_rows // 2
        idx = idx[:k] + [None] + idx[-k:]
    out = list(head)
    for i in idx:
        if i is None:
            out.append(f"  ... {len(rows) - 2 * (max_rows // 2)} "
                       "rounds elided ...")
            continue
        r = rows[i]
        gap = r.get("gap")
        gap_s = f"{gap:.6e}" if gap is not None else "n/a"
        line = (f"{r.get('round', i + 1):>5}  {gap_s:>13}  "
                f"{r.get('updates', 0):>7}")
        if has_active:
            act = r.get("active")
            line += f"  {act if act is not None else 'n/a':>7}"
        out.append(f"{line}  {r.get('status', '?')}")
    return "\n".join(out)


def merge_trace_files(paths: List[str]) -> List[dict]:
    """Records of several trace files interleaved on ONE wall clock.

    Each file's monotonic timestamps are mapped to wall time via its
    meta record (wall - t0), so cascade leaves, fold-parallel tune
    workers and a serve process traced to separate files come out as one
    chronological stream. Every record gains `_wall` (the sort key) and
    `_file` (provenance); metrics snapshots across files still merge
    exactly (nonzero_counters → obs.registry.merge_snapshots)."""
    from tpusvm.obs.trace import read_trace

    out: List[dict] = []
    for p in paths:
        recs = read_trace(p)
        offset = 0.0
        for r in recs:
            if r["kind"] == "meta":
                offset = r.get("wall", 0.0) - r.get("t0", 0.0)
                break
        for r in recs:
            t = r.get("t0", r.get("ts", r.get("t1")))
            rr = dict(r)
            rr["_wall"] = offset + (t if t is not None else 0.0)
            rr["_file"] = p
            out.append(rr)
    out.sort(key=lambda r: r["_wall"])
    return out


def cross_process_spans(records: Iterable[dict]
                        ) -> Tuple[List[dict], List[str]]:
    """Resolve cross-process parentage over merged trace records.

    Two propagation mechanisms re-parent spans across files:

      * file-level: a process spawned WITH a context (pod worker,
        `ctx=` at Tracer construction) carries it in its meta record —
        the file's root spans parent under the originating span;
      * span-level: a span whose attrs carry a ``ctx`` dict (a worker's
        per-request train span, a replica's serve.request) parents
        under exactly the originating span named there.

    A context resolves when a merged file's meta matches its
    (trace_id, role, pid) — the origin identity a role-ful Tracer
    writes. Unresolvable contexts (origin file not merged in, junk)
    degrade to the span's local parentage.

    Returns (spans, roles): each span is its record plus
      _gid      globally-unique id "<file#>:<id>"
      _gparent  resolved parent gid (local parent, or the propagated
                target for cross-process roots); None for true roots
      _role     the file's role (meta), "main" when the file has none
      _pid      the file's pid (meta), None when absent
    in merged (_wall) order, and roles is the sorted distinct role set.
    """
    from tpusvm.obs.trace import TraceContext

    files: Dict[str, dict] = {}
    order: List[str] = []
    for r in records:
        f = r.get("_file", "")
        if f not in files:
            files[f] = {"meta": None, "spans": []}
            order.append(f)
        if r["kind"] == "meta" and files[f]["meta"] is None:
            files[f]["meta"] = r
        elif r["kind"] == "span":
            files[f]["spans"].append(r)
    fidx = {f: i for i, f in enumerate(order)}
    origin: Dict[Tuple[str, str, int], str] = {}
    for f in order:
        m = files[f]["meta"] or {}
        if m.get("trace_id") and m.get("role") and m.get("pid") is not None:
            origin[(m["trace_id"], m["role"], m["pid"])] = f

    def resolve(ctx_dict):
        ctx = TraceContext.from_dict(ctx_dict)
        if ctx is None or ctx.span_id is None:
            return None
        f = origin.get((ctx.trace_id, ctx.role, ctx.pid))
        if f is None:
            return None
        return f"{fidx[f]}:{ctx.span_id}"

    spans: List[dict] = []
    roles = set()
    for f in order:
        m = files[f]["meta"] or {}
        role = m.get("role") or "main"
        roles.add(role)
        file_parent = resolve(m.get("ctx")) if m.get("ctx") else None
        for r in files[f]["spans"]:
            attrs = r.get("attrs") or {}
            gparent = None
            if attrs.get("ctx"):
                gparent = resolve(attrs["ctx"])
            if gparent is None and r.get("parent") is not None:
                gparent = f"{fidx[f]}:{r['parent']}"
            if gparent is None and r.get("parent") is None:
                gparent = file_parent
            spans.append({**r, "_gid": f"{fidx[f]}:{r['id']}",
                          "_gparent": gparent, "_role": role,
                          "_pid": m.get("pid")})
    spans.sort(key=lambda s: s.get("_wall", s.get("t0", 0.0)))
    return spans, sorted(roles)


def reparent_stats(records: Iterable[dict]) -> dict:
    """Machine-checkable re-parenting summary for a merged trace dir.

    `unresolved` counts root spans of ctx-carrying files that FAILED to
    re-parent (their origin span should be in the merged set — the
    chaos gate and `report --smoke` assert this stays 0)."""
    from tpusvm.obs.trace import TraceContext

    recs = list(records)
    spans, roles = cross_process_spans(recs)
    ctx_files = set()
    for r in recs:
        if r["kind"] == "meta" and TraceContext.from_dict(
                r.get("ctx")) is not None:
            ctx_files.add(r.get("_file", ""))
    unresolved = sum(
        1 for s in spans
        if s.get("_file", "") in ctx_files and s.get("parent") is None
        and s["_gparent"] is None)
    reparented = sum(
        1 for s in spans
        if s["_gparent"] is not None
        and s["_gparent"].split(":")[0] != s["_gid"].split(":")[0])
    return {"files": len({s.get("_file", "") for s in spans}),
            "roles": roles, "spans": len(spans),
            "reparented": reparented, "unresolved": unresolved}


def _span_attr_brief(attrs: dict, limit: int = 40) -> str:
    parts = []
    for k in ("round", "req", "leaf", "shard", "model", "topology",
              "rows", "n_leaves"):
        if k in attrs:
            parts.append(f"{k}={attrs[k]}")
    s = " ".join(parts)
    return s if len(s) <= limit else s[:limit - 3] + "..."


def format_timeline(records: Iterable[dict], max_rows: int = 60) -> str:
    """The cross-process timeline: one line per span in wall order,
    per-role lanes, indentation by RESOLVED depth (a worker's train span
    indents under the coordinator's round span it was re-parented to).
    Long traces elide the middle like the convergence table."""
    spans, roles = cross_process_spans(records)
    if not spans:
        return "no spans in this trace"
    by_gid = {s["_gid"]: s for s in spans}

    def depth(s):
        d, cur, seen = 0, s, set()
        while cur["_gparent"] is not None and cur["_gparent"] in by_gid:
            if cur["_gid"] in seen:  # defensive: never loop on bad data
                break
            seen.add(cur["_gid"])
            cur = by_gid[cur["_gparent"]]
            d += 1
        return d

    base = min(s.get("_wall", s.get("t0", 0.0)) for s in spans)
    role_w = max(len(r) for r in roles)
    out = [f"{'start_ms':>10}  {'dur_ms':>9}  {'role':<{role_w}}  span",
           f"{'--------':>10}  {'------':>9}  {'----':<{role_w}}  ----"]
    idx = list(range(len(spans)))
    if len(idx) > max_rows:
        k = max_rows // 2
        idx = idx[:k] + [None] + idx[-k:]
    for i in idx:
        if i is None:
            out.append(f"  ... {len(spans) - 2 * (max_rows // 2)} "
                       "spans elided ...")
            continue
        s = spans[i]
        t = s.get("_wall", s.get("t0", 0.0)) - base
        brief = _span_attr_brief(s.get("attrs") or {})
        name = "  " * min(depth(s), 8) + s["name"]
        line = (f"{t * 1e3:>10.1f}  {s['dur_s'] * 1e3:>9.1f}  "
                f"{s['_role']:<{role_w}}  {name}")
        if brief:
            line += f"  [{brief}]"
        out.append(line)
    return "\n".join(out)


def format_round_gantt(records: Iterable[dict], width: int = 32) -> str:
    """Round-level gantt over the pod fit's wall window: one bar per
    coordinator pod.round span, with the worker spans that landed
    inside each round's window counted per role."""
    spans, _ = cross_process_spans(records)
    rounds = [s for s in spans if s["name"] == "pod.round"]
    if not rounds:
        return ""
    lo = min(s.get("_wall", s.get("t0", 0.0)) for s in spans)
    hi = max(s.get("_wall", s.get("t0", 0.0)) + s["dur_s"] for s in spans)
    total = max(hi - lo, 1e-9)
    out = [f"{'round':>5}  {'start_ms':>9}  {'dur_ms':>9}  "
           f"{'window':<{width}}  worker spans"]
    for s in rounds:
        t0 = s.get("_wall", s.get("t0", 0.0))
        t1 = t0 + s["dur_s"]
        a = int((t0 - lo) / total * width)
        b = max(a + 1, int((t1 - lo) / total * width))
        bar = "." * a + "#" * (b - a) + "." * (width - b)
        inside: Dict[str, int] = {}
        for w in spans:
            if w["_role"] == s["_role"] or w["kind"] != "span":
                continue
            wt = w.get("_wall", w.get("t0", 0.0))
            if t0 <= wt <= t1:
                inside[w["_role"]] = inside.get(w["_role"], 0) + 1
        counts = " ".join(f"{r}:{n}" for r, n in sorted(inside.items()))
        rnd = (s.get("attrs") or {}).get("round", "?")
        out.append(f"{rnd:>5}  {(t0 - lo) * 1e3:>9.1f}  "
                   f"{s['dur_s'] * 1e3:>9.1f}  {bar}  {counts}")
    return "\n".join(out)


def compile_rows(records: Iterable[dict]) -> List[dict]:
    """The prof.compile events (tpusvm.obs.prof), in record order."""
    return [r["attrs"] for r in records
            if r["kind"] == "event" and r["name"] == "prof.compile"]


def format_compile_table(rows: List[dict]) -> str:
    """Per-executable compile/cost table (the observatory's headline).

    One row per executable, compiles and lower/compile seconds summed
    across events, FLOPs / bytes accessed / arithmetic intensity from the
    cost analysis (max across events — re-lowers of one entry point are
    the same program family). Backends without a cost model get an
    explicit `cost_analysis: unavailable` marker, never silent zeros."""
    if not rows:
        return "no compile records in this trace (profiling was off)"
    agg: Dict[str, dict] = {}
    order: List[str] = []
    for r in rows:
        name = r.get("executable", "?")
        a = agg.get(name)
        if a is None:
            agg[name] = a = {"n": 0, "lower_s": 0.0, "compile_s": 0.0,
                             "flops": None, "bytes": None,
                             "available": False}
            order.append(name)
        a["n"] += 1
        a["lower_s"] += r.get("lower_s") or 0.0
        a["compile_s"] += r.get("compile_s") or 0.0
        if r.get("cost_available"):
            a["available"] = True
            for src, dst in (("flops", "flops"),
                             ("bytes_accessed", "bytes")):
                v = r.get(src)
                if v is not None:
                    a[dst] = v if a[dst] is None else max(a[dst], v)
    out = ["executable                        #  lower s  compile s"
           "     GFLOP       MB  FLOP/B",
           "----------                        -  -------  ---------"
           "     -----       --  ------"]
    for name in order:
        a = agg[name]
        left = (f"{name:<32} {a['n']:>2}  {a['lower_s']:>7.3f}  "
                f"{a['compile_s']:>9.3f}")
        if not a["available"]:
            out.append(f"{left}  cost_analysis: unavailable")
            continue
        flops, nbytes = a["flops"], a["bytes"]
        gflop = f"{flops / 1e9:>9.4f}" if flops is not None else "      n/a"
        mb = (f"{nbytes / 1e6:>8.2f}" if nbytes is not None else "     n/a")
        ai = (f"{flops / nbytes:>6.2f}" if flops is not None and nbytes
              else "   n/a")
        out.append(f"{left}  {gflop} {mb}  {ai}")
    return "\n".join(out)


def autopilot_rows(records: Iterable[dict]) -> List[dict]:
    """The autopilot.drift decision events, in tick order."""
    return [r["attrs"] for r in records
            if r["kind"] == "event" and r["name"] == "autopilot.drift"]


def format_autopilot_table(rows: List[dict], max_rows: int = 40) -> str:
    """Per-tick drift-decision table: tick, decision, per-detector
    scores vs their (jittered) thresholds, and the reason string. Long
    runs elide the middle like the convergence table — the interesting
    structure is the warm-up and the ticks around a triggered refresh."""
    if not rows:
        return "no autopilot decisions in this trace"
    out = [" tick  decision  detector scores (score/threshold)",
           " ----  --------  ---------------------------------"]
    idx = list(range(len(rows)))
    if len(idx) > max_rows:
        k = max_rows // 2
        idx = idx[:k] + [None] + idx[-k:]
    for i in idx:
        if i is None:
            out.append(f"  ... {len(rows) - 2 * (max_rows // 2)} "
                       "ticks elided ...")
            continue
        r = rows[i]
        rep = r.get("report", {})
        dets = "  ".join(
            f"{d['name']}={d['score']:.3g}/{d['threshold']:.3g}"
            + ("*" if d.get("triggered") else "")
            for d in rep.get("detectors", []))
        out.append(f"{r.get('tick', i + 1):>5}  "
                   f"{'REFRESH' if r.get('decision') else 'watch':>8}  "
                   f"{dets}")
        if r.get("decision"):
            out.append(f"       reason: {r.get('reason', '?')}")
    return "\n".join(out)


def nonzero_counters(records: Iterable[dict]) -> List[str]:
    """`name{labels} value` lines for every non-zero counter/gauge in
    embedded metrics snapshots (merged when several are present)."""
    from tpusvm.obs.registry import merge_snapshots

    snaps = [r["attrs"]["snapshot"] for r in records
             if r["kind"] == "event" and r["name"] == "metrics.snapshot"]
    if not snaps:
        return []
    merged = merge_snapshots(*snaps)
    lines = []
    for e in merged["metrics"]:
        if e["type"] == "histogram":
            if e["count"]:
                lines.append(f"{e['name']} count={e['count']} "
                             f"sum={e['sum']:g}")
        elif e["value"]:
            lab = ",".join(f"{k}={v}" for k, v in
                           sorted(e["labels"].items()))
            lines.append(f"{e['name']}{'{' + lab + '}' if lab else ''} "
                         f"{e['value']:g}")
    return lines


def render_report(records: List[dict]) -> str:
    """The `tpusvm report` body for one parsed (or merged) trace."""
    acc, total = phase_summary(records)
    spans = sum(1 for r in records if r["kind"] == "span")
    events = sum(1 for r in records if r["kind"] == "event")
    parts = [f"trace: {spans} spans, {events} events", ""]
    comp = compile_rows(records)
    if comp:
        parts += ["compiles (lower/compile wall time, "
                  "XLA cost analysis):",
                  format_compile_table(comp), ""]
    conv = convergence_rows(records)
    parts += ["convergence (b_low - b_high per outer round):",
              format_convergence_table(conv), ""]
    auto = autopilot_rows(records)
    if auto:
        parts += ["autopilot (drift decisions per tick):",
                  format_autopilot_table(auto), ""]
    _, roles = cross_process_spans(records)
    if len(roles) > 1:
        # a merged multi-process trace: stitch ONE timeline across the
        # fleet (propagated contexts re-parent worker/replica spans)
        stats = reparent_stats(records)
        parts += [f"cross-process timeline ({stats['files']} files, "
                  f"roles: {', '.join(roles)}; "
                  f"{stats['reparented']} spans re-parented, "
                  f"{stats['unresolved']} unresolved):",
                  format_timeline(records), ""]
        gantt = format_round_gantt(records)
        if gantt:
            parts += ["pod rounds (gantt over the fit wall window):",
                      gantt, ""]
    counters = nonzero_counters(records)
    if counters:
        parts += ["counters:"] + ["  " + line for line in counters] + [""]
    parts.append(render_phase_lines(acc, total))
    return "\n".join(parts)
