"""tpusvm.obs — unified telemetry: metrics registry, JSONL tracing,
on-device convergence telemetry, and the shared report renderers.

Three pillars (see each module's docstring):
  registry.py    — process-wide counters/gauges/histograms with exactly
                   mergeable snapshots (serve/tune/stream/cascade share
                   one vocabulary);
  trace.py       — schema-versioned JSONL span/event tracer + PhaseTimer
                   (the span adapter preserving the reference's
                   three-line timing contract);
  convergence.py — host half of the solver's carry-resident convergence
                   ring (device half: solver/blocked.py telemetry=T);
  fleet.py       — cross-process aggregation: per-process snapshot
                   payloads merged into one (role, instance)-labelled
                   fleet view (`tpusvm fleet-metrics` / `tpusvm top`).
report.py renders all of it (`tpusvm report <trace.jsonl>`), including
the cross-process timeline stitched from propagated trace contexts.
"""

from tpusvm.obs.registry import (
    MetricsRegistry,
    default_registry,
    merge_snapshots,
    render_snapshot_text,
    reset_default_registry,
)
from tpusvm.obs.trace import (
    TRACE_HEADER,
    PhaseTimer,
    TraceContext,
    Tracer,
    read_trace,
)
from tpusvm.obs.fleet import (
    FleetCollector,
    format_top,
    merge_fleet,
    render_fleet_text,
    snapshot_payload,
    top_rows,
)
from tpusvm.obs.convergence import (
    ConvergenceTelemetry,
    format_gap_table,
    materialize,
    to_trace_events,
)

__all__ = [
    "ConvergenceTelemetry",
    "FleetCollector",
    "MetricsRegistry",
    "PhaseTimer",
    "TRACE_HEADER",
    "TraceContext",
    "Tracer",
    "default_registry",
    "format_gap_table",
    "format_top",
    "materialize",
    "merge_fleet",
    "merge_snapshots",
    "read_trace",
    "render_fleet_text",
    "render_snapshot_text",
    "reset_default_registry",
    "snapshot_payload",
    "to_trace_events",
]
