"""tpusvm.obs — unified telemetry: metrics registry, JSONL tracing,
on-device convergence telemetry, and the shared report renderers.

Three pillars (see each module's docstring):
  registry.py    — process-wide counters/gauges/histograms with exactly
                   mergeable snapshots (serve/tune/stream/cascade share
                   one vocabulary);
  trace.py       — schema-versioned JSONL span/event tracer + PhaseTimer
                   (the span adapter preserving the reference's
                   three-line timing contract);
  convergence.py — host half of the solver's carry-resident convergence
                   ring (device half: solver/blocked.py telemetry=T).
report.py renders all of it (`tpusvm report <trace.jsonl>`).
"""

from tpusvm.obs.registry import (
    MetricsRegistry,
    default_registry,
    merge_snapshots,
    render_snapshot_text,
    reset_default_registry,
)
from tpusvm.obs.trace import PhaseTimer, Tracer, read_trace
from tpusvm.obs.convergence import (
    ConvergenceTelemetry,
    format_gap_table,
    materialize,
    to_trace_events,
)

__all__ = [
    "ConvergenceTelemetry",
    "MetricsRegistry",
    "PhaseTimer",
    "Tracer",
    "default_registry",
    "format_gap_table",
    "materialize",
    "merge_snapshots",
    "read_trace",
    "render_snapshot_text",
    "reset_default_registry",
    "to_trace_events",
]
