"""The compile observatory: lower/compile accounting on every jit entry.

The ROADMAP's two hottest open items (the Pallas mixed-precision ladder,
the traffic-scale serving runtime) are both attribution problems first:
nobody can say where compile time, FLOPs or bytes actually go. This
module answers that with ZERO change to the default path:

  * `profiled_jit(name, jitted)` wraps a jit entry point. With the
    observatory DISABLED (the default) the wrapper is one attribute read
    + the original jit call — the compiled program, its cache, and every
    result byte are untouched.
  * With the observatory ENABLED (CLI `--trace`, or tests via
    `profiling(...)`), calls route through an explicit
    `jitted.lower(...).compile()` per distinct input signature: the
    lower and compile wall times are measured, the executable's
    `cost_analysis()` / `memory_analysis()` are read (obs.costs), and
    one `prof.compile` record lands in the metrics registry (gauges +
    a compile counter) and the trace event sink. The compiled
    executable is then CALLED and cached, so steady-state profiled runs
    pay one extra dict lookup, not a recompile.

Bit-transparency is a hard contract: the AOT executable is built from
the same jaxpr the jit cache would build, so alpha bytes / SV ids / b
are identical with the observatory on or off (tests/test_prof.py).
Two escape hatches keep it safe everywhere:

  * tracer passthrough — a wrapped entry called INSIDE another trace
    (cascade's shard_map body, ovr's vmap) sees abstract tracers and
    simply calls the jitted function (jit-of-jit inlines as always);
  * call fallback — if the AOT executable refuses the concrete call
    (an aval signature this module keyed wrong), the original jit path
    runs instead and a `prof.fallbacks` counter says so. Wrong never;
    slow-but-honest at worst.

Signature keys deliberately mirror jit's own cache rules: arrays key by
(shape, dtype), Python scalars by weak type (NOT value — a tune sweep
varying C must reuse one executable), static kwargs by value.
"""

from __future__ import annotations

import contextlib
import functools
import threading
import time
from typing import Any, Callable, Dict, Iterator, Optional, Tuple

import numpy as np

from tpusvm.obs import costs
from tpusvm.obs.registry import MetricsRegistry, default_registry


class CompileObservatory:
    """Holds the compile cache + where records go while profiling is on."""

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 event_sink: Optional[Callable[..., None]] = None):
        self.registry = registry if registry is not None \
            else default_registry()
        self.event_sink = event_sink
        self._lock = threading.Lock()
        # key -> (fn, compiled): fn is kept so id(fn) in the key can
        # never alias a garbage-collected closure's reused id
        self._cache: Dict[Tuple, Tuple[Any, Any]] = {}
        self.records: list = []  # compile records, in compile order

    # ------------------------------------------------------------ recording
    def record(self, rec: dict) -> None:
        name = rec["executable"]
        self.records.append(rec)
        reg = self.registry
        reg.counter("prof.compiles", executable=name).inc()
        reg.gauge("prof.lower_s", executable=name).set_max(rec["lower_s"])
        reg.gauge("prof.compile_s", executable=name).set_max(
            rec["compile_s"])
        for key in ("flops", "bytes_accessed", "arith_intensity",
                    "temp_bytes"):
            v = rec.get(key)
            if v is not None:
                reg.gauge(f"prof.{key}", executable=name).set_max(v)
        if self.event_sink is not None:
            self.event_sink("prof.compile", **rec)

    # ------------------------------------------------------------- the call
    def call(self, name: str, fn, args: tuple, static: tuple,
             kwargs: dict):
        static_kw = {k: kwargs[k] for k in kwargs if k in static}
        dyn_kw = {k: v for k, v in kwargs.items() if k not in static}
        key = (name, id(fn), _signature_key(args, dyn_kw),
               tuple(sorted((k, repr(v)) for k, v in static_kw.items())))
        with self._lock:
            entry = self._cache.get(key)
        if entry is None:
            try:
                t0 = time.perf_counter()
                lowered = fn.lower(*args, **kwargs)
                t1 = time.perf_counter()
                compiled = lowered.compile()
                t2 = time.perf_counter()
            except Exception:  # noqa: BLE001 — never lose the run to the
                # observatory: an entry point the AOT surface cannot
                # lower (donations, custom transforms) falls back whole
                self.registry.counter("prof.fallbacks",
                                      executable=name).inc()
                return fn(*args, **kwargs)
            self.record(costs.compile_record(name, t1 - t0, t2 - t1,
                                             compiled))
            with self._lock:
                self._cache[key] = entry = (fn, compiled)
        _, compiled = entry
        try:
            return compiled(*args, **dyn_kw)
        except (TypeError, ValueError):
            # aval mismatch this module's key failed to distinguish:
            # honesty over speed — run the normal jit path and count it
            self.registry.counter("prof.fallbacks", executable=name).inc()
            return fn(*args, **kwargs)


# ------------------------------------------------------------ module state
_active: Optional[CompileObservatory] = None
_lock = threading.Lock()


def enable_profiling(registry: Optional[MetricsRegistry] = None,
                     event_sink: Optional[Callable[..., None]] = None,
                     ) -> CompileObservatory:
    """Turn the observatory on process-wide; returns it (idempotent-ish:
    a second enable replaces the first — last caller wins)."""
    global _active
    with _lock:
        _active = CompileObservatory(registry=registry,
                                     event_sink=event_sink)
        return _active


def disable_profiling() -> None:
    global _active
    with _lock:
        _active = None


def profiling_enabled() -> bool:
    return _active is not None


def current() -> Optional[CompileObservatory]:
    return _active


@contextlib.contextmanager
def profiling(registry: Optional[MetricsRegistry] = None,
              event_sink: Optional[Callable[..., None]] = None,
              ) -> Iterator[CompileObservatory]:
    """Scoped enable/disable (the test surface)."""
    obs = enable_profiling(registry=registry, event_sink=event_sink)
    try:
        yield obs
    finally:
        disable_profiling()


# --------------------------------------------------------- signature keys
def _leaf_key(x) -> tuple:
    import jax

    if isinstance(x, (jax.Array, np.ndarray)):
        return ("arr", tuple(x.shape), str(x.dtype))
    if isinstance(x, bool):
        return ("scalar", "bool")
    if isinstance(x, (int, float, complex, np.generic)):
        # weak-typed like jit's own cache: two C values share a program
        return ("scalar", type(x).__name__)
    return ("static", repr(x))


def _signature_key(args: tuple, dyn_kw: dict) -> str:
    import jax

    leaves, treedef = jax.tree_util.tree_flatten((args, dyn_kw))
    return f"{treedef}|{tuple(_leaf_key(x) for x in leaves)}"


def _has_tracer(args: tuple, kwargs: dict) -> bool:
    import jax

    return any(isinstance(x, jax.core.Tracer)
               for x in jax.tree_util.tree_leaves((args, kwargs)))


# ------------------------------------------------- jit entry-point registry
# Every profiled_jit wrap records (jitted, static argnames) here, keyed by
# its observatory name. This is the abstract-signature registry the IR
# auditor (tpusvm.analysis.ir.entrypoints) enumerates: the auditor pairs
# each registered jit object with a canonical set of abstract input
# shapes/dtypes and walks the traced jaxpr, so "every jit entry point is
# audited" stays true by construction — wrapping a new entry point with
# profiled_jit is the same act that registers it for auditing. The static
# tables themselves (_BLOCKED_STATIC / _SMO_STATIC / the predict statics)
# stay deduplicated at their definition sites and flow through `static`.
JIT_ENTRY_POINTS: Dict[str, Tuple[Any, tuple]] = {}


# -------------------------------------------------------------- public API
def profiled_call(name: str, fn, *args, static: tuple = (), **kwargs):
    """Call jit-compiled `fn`; route through the observatory when on.

    static: the fn's static_argnames (static kwargs are baked into the
    executable and must be stripped from the AOT call)."""
    obs = _active
    if obs is None or _has_tracer(args, kwargs):
        return fn(*args, **kwargs)
    return obs.call(name, fn, args, static, kwargs)


def profiled_jit(name: str, jitted, static: tuple = ()):
    """Wrap a jit entry point so every call goes via profiled_call.

    The wrapper preserves the jit object's AOT surface (`.lower`, used
    by serve's bucket cache and the benchmark harnesses) and its
    introspectable signature (functools.wraps → inspect.signature keeps
    resolving the original parameters, which the CLI's --solver-opt
    validation reads)."""

    @functools.wraps(jitted)
    def wrapper(*args, **kwargs):
        return profiled_call(name, jitted, *args, static=static, **kwargs)

    wrapper.lower = jitted.lower
    wrapper._profiled_name = name
    wrapper._jitted = jitted
    # last definition wins, like the jit objects themselves on re-import
    JIT_ENTRY_POINTS[name] = (jitted, tuple(static))
    return wrapper


def record_compile(name: str, lower_s: float, compile_s: float,
                   compiled=None,
                   registry: Optional[MetricsRegistry] = None,
                   **extra: Any) -> dict:
    """Report an externally-driven compile (serve's bucket AOT builds,
    cascade's shard_map round executable) into the observatory.

    Always writes the gauges into `registry` (default: the observatory's
    when enabled, else the process default — the write is host-side and
    cheap, so serve compile accounting exists even unprofiled); the
    trace event fires only while the observatory is on."""
    rec = costs.compile_record(name, lower_s, compile_s, compiled, **extra)
    obs = _active
    if registry is None:
        registry = obs.registry if obs is not None else default_registry()
    if obs is not None and obs.registry is registry:
        obs.record(rec)
    else:
        # record into the caller's registry; mirror the event if profiling
        reg = registry
        nm = rec["executable"]
        reg.counter("prof.compiles", executable=nm).inc()
        reg.gauge("prof.lower_s", executable=nm).set_max(rec["lower_s"])
        reg.gauge("prof.compile_s", executable=nm).set_max(rec["compile_s"])
        for key in ("flops", "bytes_accessed", "arith_intensity",
                    "temp_bytes"):
            v = rec.get(key)
            if v is not None:
                reg.gauge(f"prof.{key}", executable=nm).set_max(v)
        if obs is not None:
            obs.records.append(rec)
            if obs.event_sink is not None:
                obs.event_sink("prof.compile", **rec)
    return rec
