"""Process-wide metrics registry: counters, gauges, bucketed histograms.

The repro grew four subsystems that each invented a private slice of
observability (serve's counters/percentiles, the stream reader's
max_live_shards audit field, tune's per-point dict rows, PhaseTimer).
This module is the one vocabulary they all emit into: a metric is a
(name, labels) pair owned by a MetricsRegistry, and a registry SNAPSHOT
is a plain JSON-able dict that merges EXACTLY across processes/workers —
fold-parallel tune arms and cascade leaves can each fill an independent
registry and `merge_snapshots` reconstructs the global view with no
approximation:

  * counters add (integers — associative, commutative, exact);
  * gauges combine by max (the only order-free reduction that needs no
    timestamps; documented, and what the existing high-water-mark gauges
    — queue depth, live shards — actually want);
  * histograms add per-bucket counts, sum and count elementwise
    (identical bucket bounds are required; merging mismatched bounds is
    a ValueError, never a resample).

Thread safety is one lock per registry: the request rates any host-side
path here sees are orders of magnitude below lock contention, and one
lock keeps snapshots consistent (a scrape never sees a half-applied
compound update — the same argument serve/metrics.py made for its
private stack before it was refolded onto this one).

Renderers: `snapshot()` (schema-versioned dict), `render_text()`
(Prometheus-style `name{labels} value` lines).
"""

from __future__ import annotations

import bisect
import threading
from typing import Dict, List, Optional, Sequence, Tuple

SNAPSHOT_VERSION = 1

# default histogram bounds: latency-ish log scale; callers with real
# domains (batch sizes, shard counts) pass their own
DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0)

_LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Dict[str, str]) -> _LabelKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class Counter:
    """Monotonic integer counter. Merge rule: add."""

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self._v = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._v += int(n)

    @property
    def value(self) -> int:
        with self._lock:
            return self._v

    def read_locked(self) -> int:
        """Raw value. CALLER holds the shared registry lock — the
        snapshot path acquires it exactly once for all metrics (the
        lock is shared and non-reentrant, so reacquiring per metric
        would deadlock; reading without it would tear)."""
        return self._v


class Gauge:
    """Last-set value with a high-water mark. Merge rule: max.

    `set` tracks the running maximum too, so snapshot merges (which must
    be order-free) expose the high-water mark — the semantics every
    current gauge (queue depth, live shards) wants across workers."""

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self._v = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._v = float(v)

    def set_max(self, v: float) -> None:
        with self._lock:
            self._v = max(self._v, float(v))

    @property
    def value(self) -> float:
        with self._lock:
            return self._v

    def read_locked(self) -> float:
        """Raw value; caller holds the shared registry lock (see
        Counter.read_locked)."""
        return self._v


class Histogram:
    """Bucketed histogram with fixed ascending bounds (+inf implicit).

    Merge rule: elementwise add of counts/sum/count — exact, provided
    both sides share the same bounds."""

    def __init__(self, lock: threading.Lock, bounds: Sequence[float]):
        b = tuple(float(x) for x in bounds)
        if list(b) != sorted(set(b)):
            raise ValueError(f"histogram bounds must be strictly "
                             f"ascending, got {bounds}")
        self._lock = lock
        self.bounds = b
        self._counts = [0] * (len(b) + 1)  # last = +inf overflow
        self._sum = 0.0
        self._n = 0

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self._counts[bisect.bisect_left(self.bounds, v)] += 1
            self._sum += v
            self._n += 1

    @property
    def count(self) -> int:
        with self._lock:
            return self._n

    def read_locked(self) -> dict:
        """(bounds, counts, sum, count) copies; caller holds the shared
        registry lock (see Counter.read_locked) — one acquisition covers
        the whole histogram, so counts/sum/count are mutually consistent
        even when the snapshot races a writer."""
        return {"bounds": list(self.bounds), "counts": list(self._counts),
                "sum": self._sum, "count": self._n}


class MetricsRegistry:
    """Get-or-create home for every metric of one process/worker."""

    def __init__(self):
        self._lock = threading.Lock()
        # keyed by (name, labels) ALONE so one name cannot be two metric
        # types — a vocabulary clash is a bug worth a loud TypeError, not
        # two silently-coexisting series
        self._metrics: Dict[Tuple[str, _LabelKey], Tuple[str, object]] = {}

    def _get(self, kind: str, name: str, labels: Dict[str, str], make):
        key = (name, _label_key(labels))
        with self._lock:
            entry = self._metrics.get(key)
            if entry is None:
                entry = self._metrics[key] = (kind, make())
            elif entry[0] != kind:
                raise TypeError(
                    f"metric {name!r} is already registered as a "
                    f"{entry[0]}, requested {kind}"
                )
            return entry[1]

    def counter(self, name: str, **labels: str) -> Counter:
        return self._get("counter", name, labels,
                         lambda: Counter(self._lock))

    def gauge(self, name: str, **labels: str) -> Gauge:
        return self._get("gauge", name, labels, lambda: Gauge(self._lock))

    def histogram(self, name: str, bounds: Sequence[float] = DEFAULT_BUCKETS,
                  **labels: str) -> Histogram:
        m = self._get("histogram", name, labels,
                      lambda: Histogram(self._lock, bounds))
        if m.bounds != tuple(float(x) for x in bounds):
            raise ValueError(
                f"histogram {name!r} already registered with bounds "
                f"{m.bounds}, requested {tuple(bounds)}"
            )
        return m

    # ------------------------------------------------------------ export
    def snapshot(self) -> dict:
        """One consistent, JSON-able, MERGEABLE view of every metric.

        The shared registry lock is taken EXACTLY ONCE for the whole
        snapshot (every metric wrapper holds the same lock, so a
        per-metric value() loop would deadlock on the non-reentrant
        lock — and releasing between metrics would let a scrape observe
        metric A after a compound update and metric B before it). The
        wrappers' read_locked() accessors make that contract explicit;
        conc-stress asserts a snapshot taken mid-write is still
        internally consistent and mergeable."""
        out: List[dict] = []
        with self._lock:
            items = sorted(self._metrics.items())
            for (name, lkey), (kind, m) in items:
                entry = {"name": name, "type": kind, "labels": dict(lkey)}
                if kind in ("counter", "gauge"):
                    entry["value"] = m.read_locked()
                else:
                    entry.update(m.read_locked())
                out.append(entry)
        return {"v": SNAPSHOT_VERSION, "metrics": out}

    def render_text(self, prefix: str = "tpusvm") -> str:
        return render_snapshot_text(self.snapshot(), prefix=prefix)


def _entry_key(e: dict) -> Tuple[str, str, _LabelKey]:
    return (e["type"], e["name"], _label_key(e["labels"]))


def merge_snapshots(*snaps: dict) -> dict:
    """Exact, associative, commutative merge of registry snapshots.

    merge(a, b) == merge(b, a) on every metric type, and
    merge(merge(a, b), c) == merge(a, merge(b, c)) — the property that
    lets fold-parallel workers and cascade leaves emit independently and
    be combined in any order (asserted by tests/test_obs.py)."""
    merged: Dict[Tuple[str, str, _LabelKey], dict] = {}
    for snap in snaps:
        if snap.get("v") != SNAPSHOT_VERSION:
            raise ValueError(
                f"unsupported metrics snapshot version {snap.get('v')!r} "
                f"(this build reads v{SNAPSHOT_VERSION})"
            )
        for e in snap["metrics"]:
            key = _entry_key(e)
            cur = merged.get(key)
            if cur is None:
                merged[key] = {**e, "labels": dict(e["labels"])}
                continue
            if e["type"] == "counter":
                cur["value"] += e["value"]
            elif e["type"] == "gauge":
                cur["value"] = max(cur["value"], e["value"])
            else:
                if cur["bounds"] != e["bounds"]:
                    raise ValueError(
                        f"cannot merge histogram {e['name']!r}: bounds "
                        f"{cur['bounds']} != {e['bounds']}"
                    )
                cur["counts"] = [a + b for a, b in
                                 zip(cur["counts"], e["counts"])]
                cur["sum"] += e["sum"]
                cur["count"] += e["count"]
    return {"v": SNAPSHOT_VERSION,
            "metrics": [merged[k] for k in sorted(merged)]}


def escape_label_value(v: str) -> str:
    """Escape a label value per the Prometheus exposition format:
    backslash, double-quote and newline must be backslash-escaped
    (https://prometheus.io/docs/instrumenting/exposition_formats/).
    Backslash first — escaping it last would re-escape the others."""
    return (str(v).replace("\\", "\\\\")
            .replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{escape_label_value(v)}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def render_snapshot_text(snap: dict, prefix: str = "tpusvm") -> str:
    """Prometheus-style text rendering of a (possibly merged) snapshot."""
    lines: List[str] = []
    for e in snap["metrics"]:
        name = f"{prefix}_{e['name'].replace('.', '_')}"
        lab = _fmt_labels(e["labels"])
        if e["type"] == "counter":
            lines.append(f"{name}_total{lab} {e['value']}")
        elif e["type"] == "gauge":
            lines.append(f"{name}{lab} {e['value']:g}")
        else:
            cum = 0
            for bound, c in zip(list(e["bounds"]) + ["+Inf"],
                                e["counts"]):
                cum += c
                sep = "," if e["labels"] else ""
                blab = _fmt_labels(e["labels"])[:-1] if e["labels"] else "{"
                lines.append(f'{name}_bucket{blab}{sep}le="{bound}"}} {cum}')
            lines.append(f"{name}_sum{lab} {e['sum']:g}")
            lines.append(f"{name}_count{lab} {e['count']}")
    return "\n".join(lines) + ("\n" if lines else "")


# --------------------------------------------------------------- default
_DEFAULT: Optional[MetricsRegistry] = None
_DEFAULT_LOCK = threading.Lock()


def default_registry() -> MetricsRegistry:
    """The process-wide registry shared by subsystems that have no
    natural owner object (the stream reader's prefetch counters)."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        if _DEFAULT is None:
            _DEFAULT = MetricsRegistry()
        return _DEFAULT


def reset_default_registry() -> None:
    """Testing hook: drop the process-wide registry."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        _DEFAULT = None
