"""Machine-checked comparison of two benchmark JSONL artifacts.

The repo carries 20+ committed `benchmarks/results/*.jsonl` artifacts and,
until this round, NO machine-checked way to compare two of them — a perf
regression (or a CPU-fallback run masquerading as TPU numbers, the
BENCH_r02–r05 failure) could land silently. `tpusvm benchdiff old new`
closes that:

  * records pair up by schema (`bench` field) + identifying fields
    (mode/engine/n/seed/...); a baseline row with no counterpart in the
    new artifact is itself a regression (a silently-skipped bench);
  * each schema declares per-metric RULES — direction + tolerance:
    `>=` for throughput-like metrics (new may not fall below
    old - rel·|old|), `<=` for latency/overhead-like ones, `==` for
    correctness booleans (bit_identical, status). Wall-clock rules are
    marked `timing` and SKIPPED at `--level smoke` (CI machines are not
    the committed baseline's machine; correctness/direction metrics
    still gate) — the "direction-only rules at smoke scale" CI gate;
  * PROVENANCE is compared first: records carry a backend (the
    `provenance` dict bench harnesses now emit, falling back to the
    older `platform` field), and a cross-backend diff is REFUSED unless
    `--allow-cross-backend` (then it is annotated) — exactly the
    mismatch that let r02–r05's single-CPU fallbacks read as
    TPU-comparable numbers.

Unknown schemas get the default rules only (violations must stay empty,
bit_identical must stay true) so `benchdiff a a` is exit-0 on every
committed artifact (asserted by tests/test_benchdiff.py) while still
catching the universal failure shapes.

Output: text (default), --format json / markdown. Exit 0 = clean,
non-zero = regression, missing rows, or a refused comparison.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, List, Optional, Tuple

# identifying fields, in precedence order, used to pair rows between the
# two artifacts (only fields PRESENT in a record participate in its key)
KEY_FIELDS = (
    "bench", "metric", "summary", "mode", "engine", "kernel", "task",
    "config", "threads", "topology", "P", "n", "n_train", "d", "q",
    "seed", "case", "rows_per_shard", "telemetry", "smoke", "rung",
    "bucket", "B", "arm", "D", "replicas",
)


@dataclasses.dataclass(frozen=True)
class Rule:
    """One metric's comparison rule.

    direction: ">=" (new may not fall below old), "<=" (may not rise
    above), "==" (exact), "empty" (must stay empty when old is empty —
    the violations-list rule). rel_tol/abs_tol widen the band
    (new <= old + rel·|old| + abs for "<=", mirrored for ">=").
    timing=True marks wall-clock metrics, skipped at level="smoke"."""

    metric: str
    direction: str
    rel_tol: float = 0.0
    abs_tol: float = 0.0
    timing: bool = False


DEFAULT_RULES: Tuple[Rule, ...] = (
    Rule("violations", "empty"),
    Rule("bit_identical", "=="),
)

SCHEMA_RULES: Dict[str, Tuple[Rule, ...]] = {
    "telemetry_overhead": (
        Rule("status", "=="),
        Rule("overhead_frac", "<=", abs_tol=0.02, timing=True),
        Rule("t_on_s", "<=", rel_tol=0.3, timing=True),
        Rule("t_off_s", "<=", rel_tol=0.3, timing=True),
    ),
    "serve_latency": (
        Rule("errors", "<="),
        Rule("timeouts", "<="),
        Rule("queue_full", "<="),
        Rule("recompiles", "<="),
        Rule("not_ok", "<="),
        Rule("qps", ">=", rel_tol=0.25, timing=True),
        Rule("sequential_qps", ">=", rel_tol=0.25, timing=True),
        Rule("vs_sequential", ">=", rel_tol=0.25, timing=True),
        Rule("p99_ms", "<=", rel_tol=0.5, timing=True),
        Rule("p50_ms", "<=", rel_tol=0.5, timing=True),
    ),
    "ingest_throughput": (
        Rule("max_live_shards", "<="),
        Rule("ingest_rows_per_s", ">=", rel_tol=0.3, timing=True),
        Rule("prefetch_speedup", ">=", rel_tol=0.3, timing=True),
    ),
    "kernel_matrix": (
        Rule("status", "=="),
        Rule("n_sv", "=="),
        Rule("min_speedup", ">=", rel_tol=0.25, timing=True),
        Rule("wall_s", "<=", rel_tol=0.4, timing=True),
    ),
    "tune_sweep": (
        Rule("same_winner", "=="),
        Rule("total_saving", ">=", abs_tol=0.05),
        Rule("warm_total_updates", "<=", rel_tol=0.1),
    ),
    "mnist60k_smo_train_time": (
        Rule("value", "<=", rel_tol=0.3, timing=True),
        Rule("vs_baseline", ">=", rel_tol=0.3, timing=True),
    ),
    # round 12, the fleet: rows pair on (bench, mode, B, bucket, n, d,
    # q). Correctness metrics are exact — every fleet arm must keep the
    # host-looped control's per-head SV sets and held-out accuracy
    # byte-for-byte (sv_parity/accuracy_parity are the harness's own
    # verdicts, statuses the per-head terminations) — the sweep may
    # never start recompiling (launch economics: per-problem (C, gamma)
    # are arrays), and the aggregate-throughput metrics are
    # direction-gated at full level
    "fleet_train": (
        Rule("statuses", "=="),
        Rule("sv_parity", "=="),
        Rule("accuracy_parity", "=="),
        Rule("sv_counts", "=="),
        Rule("accuracy", "=="),
        Rule("sweep_recompiles", "<="),
        Rule("updates", "<=", rel_tol=0.1),
        Rule("agg_speedup", ">=", rel_tol=0.25, timing=True),
        Rule("train_s", "<=", rel_tol=0.35, timing=True),
        Rule("loop_train_s", "<=", rel_tol=0.35, timing=True),
        Rule("problems_per_s", ">=", rel_tol=0.25, timing=True),
    ),
    # round 13, the approximate-kernel regime: rows pair on (bench, arm,
    # n, d, D, smoke). The accuracy-delta band vs the EXACT arm is the
    # correctness claim — it is gated ABSOLUTELY (abs_tol widening only:
    # a new artifact may not drift further from the exact solution than
    # the committed one by more than the fuzz-band slack), statuses are
    # exact, the kernel-error probe may not rise beyond its sampling
    # noise and its monotone-in-D verdict is exact, update counts and
    # wall clock are direction-gated (timing rules skip at smoke level,
    # where the CI runner is not the baseline machine), and the streamed
    # arm's residency bound is a hard <=
    "approx_scale": (
        Rule("status", "=="),
        Rule("err_decreasing", "=="),
        Rule("accuracy", ">=", abs_tol=0.02),
        Rule("accuracy_delta", "<=", abs_tol=0.02),
        Rule("kmax_err", "<=", rel_tol=0.10),
        Rule("max_live_shards", "<="),
        Rule("sv_count", "==",),
        Rule("updates", "<=", rel_tol=0.15),
        Rule("train_s", "<=", rel_tol=0.35, timing=True),
    ),
    # resilient-serving round, the restart gate: rows pair on (bench,
    # arm, n, d, smoke). warm_ok is the harness's own verdict (warm arm:
    # persistent-cache misses == 0 — the ~zero-cold-start claim) and
    # score_parity pins that a cache-served executable returns the same
    # bytes; both exact. misses may only fall (the warm arm's committed 0
    # then enforces staying 0), and the wall-clock columns are
    # direction-gated at full level only
    "cold_start": (
        Rule("warm_ok", "=="),
        Rule("score_parity", "=="),
        Rule("misses", "<="),
        Rule("first_prediction_s", "<=", rel_tol=0.5, timing=True),
        Rule("warm_speedup", ">=", rel_tol=0.4, timing=True),
    ),
    # routing-tier round, the fan-out gate: rows pair on (bench, arm,
    # replicas, threads, n, smoke). lost_responses is the zero-loss
    # claim and is gated EXACT (the committed baseline's 0 then enforces
    # staying 0 — one lost response is a regression, not noise), as is
    # failover_ok (the failover arm's own verdict that the outage was
    # absorbed). no_replica may only fall. failovers/retries are
    # direction-gated with a wide band (their exact counts depend on
    # where in the stream the outage lands), and the throughput/latency
    # columns are timing rules, skipped at smoke level
    "router_fanout": (
        Rule("lost_responses", "=="),
        Rule("failover_ok", "=="),
        Rule("no_replica", "<="),
        Rule("failovers", "<=", rel_tol=1.0),
        Rule("retries", "<=", rel_tol=1.0),
        Rule("qps", ">=", rel_tol=0.3, timing=True),
        Rule("p50_ms", "<=", rel_tol=0.5, timing=True),
        Rule("p99_ms", "<=", rel_tol=0.5, timing=True),
    ),
    # round 9, the solver speed ladder: per-rung rows pair on (bench,
    # rung, n, d, q). Correctness metrics are exact — every rung must
    # keep the control's solution (sv_count/accuracy) byte-for-byte
    # across artifact generations — update counts may only fall, and the
    # wall-clock/speedup metrics are direction-gated at full level
    "solver_ladder": (
        Rule("status", "=="),
        Rule("sv_count", "=="),
        Rule("accuracy", "=="),
        Rule("updates", "<=", rel_tol=0.1),
        Rule("train_s", "<=", rel_tol=0.35, timing=True),
        Rule("speedup_vs_control", ">=", rel_tol=0.25, timing=True),
        Rule("cache_hit_rate", ">=", abs_tol=0.05),
        Rule("best_speedup", ">=", rel_tol=0.25, timing=True),
    ),
    # multi-tenant round, the coalescing economics: rows pair on
    # (bench, arm, B, bucket, n, d, smoke). Parity metrics are the
    # harness's own verdicts that every coalesced tenant kept its solo
    # control's SV sets / statuses / accuracy — exact. compiles is the
    # launch-economics claim (a coalesced fleet refresh compiles ONCE
    # where N solo daemons compile N times — per-process accounting)
    # and may only fall; updates may only fall within the warm band;
    # wall clock is direction-gated at full level only
    "tenant_refresh": (
        Rule("sv_parity", "=="),
        Rule("status_parity", "=="),
        Rule("accuracy_parity", "=="),
        Rule("statuses_converged", "=="),
        Rule("compiles", "<="),
        Rule("updates", "<=", rel_tol=0.1),
        Rule("refresh_s", "<=", rel_tol=0.4, timing=True),
        Rule("tenants_per_s", ">=", rel_tol=0.25, timing=True),
    ),
    # out-of-core pod cascade (benchmarks/pod_cascade.py): the pod arm
    # must stay bit-identical to the in-memory cascade (sv_parity folds
    # the alpha-byte check in; b_parity is bitwise), conserve leaf rows
    # and keep reader residency within the prefetch bound — all exact.
    # Worker-process overhead (pod_overhead_x, train_s) is the price of
    # the capability and is direction-gated at full level only so the
    # committed smoke baseline stays machine-portable.
    "pod_cascade": (
        Rule("sv_parity", "=="),
        Rule("b_parity", "=="),
        Rule("rows_ok", "=="),
        Rule("converged", "=="),
        Rule("accuracy", "=="),
        Rule("sv_count", "=="),
        Rule("rounds", "=="),
        Rule("max_live_shards", "<="),
        Rule("train_s", "<=", rel_tol=0.5, timing=True),
        Rule("rows_per_s", ">=", rel_tol=0.35, timing=True),
        Rule("pod_overhead_x", "<=", rel_tol=0.5, timing=True),
    ),
    # distributed observability fabric (benchmarks/obs_fabric.py): rows
    # pair on (bench, topology, P, n, smoke). The tracing capability
    # must stay FREE of model consequence — bit_identical (traced fit ==
    # untraced control: SV-ID set, alpha bytes, b) is the DEFAULT_RULES
    # exact gate — and the trace itself must stay USABLE: reparented_ok
    # (every cross-process root found its propagated parent, none
    # unresolved) and report_ok (the merged dir renders as one timeline)
    # are the fabric's own verdicts, exact. The wall-clock price of
    # tracing (overhead_frac, absolute band like telemetry_overhead's)
    # is gated at full level only so the committed smoke baseline stays
    # machine-portable.
    "obs_fabric": (
        Rule("converged", "=="),
        Rule("reparented_ok", "=="),
        Rule("report_ok", "=="),
        Rule("sv_count", "=="),
        Rule("rounds", "=="),
        Rule("unresolved_spans", "=="),
        Rule("overhead_frac", "<=", abs_tol=0.03, timing=True),
        Rule("t_on_s", "<=", rel_tol=0.5, timing=True),
        Rule("t_off_s", "<=", rel_tol=0.5, timing=True),
    ),
}


# ------------------------------------------------------------------ loading
def load_jsonl(path: str) -> List[dict]:
    records = []
    with open(path) as f:
        for i, line in enumerate(f, 1):
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except ValueError as e:
                raise ValueError(
                    f"{path}:{i}: not a JSON record ({e})"
                ) from None
            if isinstance(rec, dict):
                records.append(rec)
    return records


def schema_of(rec: dict) -> str:
    return str(rec.get("bench") or rec.get("metric") or "unknown")


def backend_of(rec: dict) -> Optional[str]:
    prov = rec.get("provenance")
    if isinstance(prov, dict) and prov.get("backend"):
        return str(prov["backend"])
    if rec.get("platform"):
        return str(rec["platform"])
    return None


def _row_key(rec: dict) -> Tuple:
    return (schema_of(rec),) + tuple(
        (k, json.dumps(rec[k], sort_keys=True, default=str))
        for k in KEY_FIELDS if k in rec
    )


# ------------------------------------------------------------------ diffing
@dataclasses.dataclass
class Finding:
    kind: str        # "regression" | "refused" | "note"
    schema: str
    metric: str
    message: str
    old: Any = None
    new: Any = None

    def asdict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class DiffResult:
    old_path: str
    new_path: str
    level: str
    rows_compared: int = 0
    checks: int = 0
    findings: List[Finding] = dataclasses.field(default_factory=list)

    @property
    def regressions(self) -> List[Finding]:
        return [f for f in self.findings if f.kind == "regression"]

    @property
    def refusals(self) -> List[Finding]:
        return [f for f in self.findings if f.kind == "refused"]

    @property
    def ok(self) -> bool:
        return not self.regressions and not self.refusals

    # ------------------------------------------------------------ renderers
    def to_json(self) -> str:
        return json.dumps({
            "old": self.old_path, "new": self.new_path,
            "level": self.level, "rows_compared": self.rows_compared,
            "checks": self.checks, "ok": self.ok,
            "findings": [f.asdict() for f in self.findings],
        }, indent=2)

    def _verdict(self) -> str:
        if self.refusals:
            return "REFUSED"
        return "PASS" if self.ok else "FAIL"

    def to_text(self) -> str:
        lines = [
            f"benchdiff: {self.old_path} -> {self.new_path} "
            f"(level={self.level})",
            f"  {self.rows_compared} row pairs, {self.checks} checks",
        ]
        for f in self.findings:
            tag = {"regression": "REGRESSION", "refused": "REFUSED",
                   "note": "note"}[f.kind]
            lines.append(f"  [{tag}] {f.schema}/{f.metric}: {f.message}")
        lines.append(f"verdict: {self._verdict()}")
        return "\n".join(lines)

    def to_markdown(self) -> str:
        lines = [
            f"### benchdiff `{self.old_path}` → `{self.new_path}`",
            "",
            f"- level: `{self.level}` — {self.rows_compared} row pairs, "
            f"{self.checks} checks",
            f"- verdict: **{self._verdict()}**",
        ]
        if self.findings:
            lines += ["", "| kind | schema | metric | old | new | detail |",
                      "|---|---|---|---|---|---|"]
            for f in self.findings:
                lines.append(
                    f"| {f.kind} | {f.schema} | {f.metric} | {f.old} | "
                    f"{f.new} | {f.message} |"
                )
        return "\n".join(lines)


def _check_rule(rule: Rule, old: dict, new: dict, schema: str,
                result: DiffResult) -> None:
    m = rule.metric
    in_old, in_new = m in old, m in new
    if not in_old and not in_new:
        return
    if in_old and not in_new:
        result.checks += 1
        result.findings.append(Finding(
            "regression", schema, m,
            "metric present in baseline but missing from new artifact",
            old=old.get(m)))
        return
    if not in_old:
        result.findings.append(Finding(
            "note", schema, m, "new metric (absent from baseline)",
            new=new.get(m)))
        return
    ov, nv = old[m], new[m]
    result.checks += 1
    if rule.direction == "empty":
        if not ov and nv:
            result.findings.append(Finding(
                "regression", schema, m,
                f"baseline had none, new artifact has {nv}",
                old=ov, new=nv))
        return
    if rule.direction == "==":
        if ov != nv:
            result.findings.append(Finding(
                "regression", schema, m, "values differ", old=ov, new=nv))
        return
    if not isinstance(ov, (int, float)) or not isinstance(nv, (int, float)) \
            or isinstance(ov, bool) or isinstance(nv, bool):
        if ov != nv:
            result.findings.append(Finding(
                "note", schema, m,
                "non-numeric values differ under a numeric rule",
                old=ov, new=nv))
        return
    band = rule.rel_tol * abs(ov) + rule.abs_tol
    if rule.direction == "<=":
        if nv > ov + band:
            result.findings.append(Finding(
                "regression", schema, m,
                f"rose beyond tolerance (allowed <= {ov + band:g})",
                old=ov, new=nv))
    elif rule.direction == ">=":
        if nv < ov - band:
            result.findings.append(Finding(
                "regression", schema, m,
                f"fell beyond tolerance (allowed >= {ov - band:g})",
                old=ov, new=nv))
    else:
        raise ValueError(f"unknown rule direction {rule.direction!r}")


def rules_for(schema: str) -> List[Rule]:
    specific = SCHEMA_RULES.get(schema, ())
    named = {r.metric for r in specific}
    return list(specific) + [r for r in DEFAULT_RULES
                             if r.metric not in named]


def diff_records(old_recs: List[dict], new_recs: List[dict],
                 old_path: str = "<old>", new_path: str = "<new>",
                 level: str = "full",
                 allow_cross_backend: bool = False) -> DiffResult:
    if level not in ("full", "smoke"):
        raise ValueError(f"level must be full|smoke, got {level!r}")
    result = DiffResult(old_path, new_path, level)

    # group rows by key, pair in file order within a key
    def group(recs):
        g: Dict[Tuple, List[dict]] = {}
        for r in recs:
            g.setdefault(_row_key(r), []).append(r)
        return g

    g_old, g_new = group(old_recs), group(new_recs)
    for key, olds in g_old.items():
        news = g_new.get(key, [])
        schema = key[0]
        for i, old in enumerate(olds):
            if i >= len(news):
                result.checks += 1
                result.findings.append(Finding(
                    "regression", schema, "<row>",
                    f"baseline row {dict(key[1:])} has no counterpart in "
                    "the new artifact"))
                continue
            new = news[i]
            result.rows_compared += 1
            ob, nb = backend_of(old), backend_of(new)
            if ob and nb and ob != nb:
                kind = "note" if allow_cross_backend else "refused"
                result.findings.append(Finding(
                    kind, schema, "provenance",
                    f"backend mismatch: baseline ran on {ob!r}, new on "
                    f"{nb!r} — cross-backend numbers are not comparable "
                    "(the r02-r05 CPU-fallback trap); re-run on the "
                    "baseline's backend or pass --allow-cross-backend "
                    "to annotate instead",
                    old=ob, new=nb))
                if kind == "refused":
                    continue
            for rule in rules_for(schema):
                if level == "smoke" and rule.timing:
                    continue
                _check_rule(rule, old, new, schema, result)
    for key, news in g_new.items():
        extra = len(news) - len(g_old.get(key, []))
        if extra > 0:
            result.findings.append(Finding(
                "note", key[0], "<row>",
                f"{extra} new row(s) with no baseline counterpart"))
    return result


def diff_files(old_path: str, new_path: str, level: str = "full",
               allow_cross_backend: bool = False) -> DiffResult:
    return diff_records(load_jsonl(old_path), load_jsonl(new_path),
                        old_path=old_path, new_path=new_path, level=level,
                        allow_cross_backend=allow_cross_backend)


def run_benchdiff(args) -> int:
    """CLI entry (`tpusvm benchdiff`): renders the verdict, exit 0/1."""
    try:
        result = diff_files(args.old, args.new, level=args.level,
                            allow_cross_backend=args.allow_cross_backend)
    except (OSError, ValueError) as e:
        print(f"benchdiff: {e}")
        return 1
    if args.format == "json":
        print(result.to_json())
    elif args.format == "markdown":
        print(result.to_markdown())
    else:
        print(result.to_text())
    return 0 if result.ok else 1
