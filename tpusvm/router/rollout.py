"""Generation-skew detection for staggered hot-swap rollouts.

A fleet rollout is N independent /admin/swap flips, one replica at a
time. Each replica's /healthz swap block already reports the generation
it is serving per model, so "where is the fleet?" is a readable vector:

    generation_vector(snapshot, "m")  ->  {url: gen or None}

and "is the rollout healthy?" is a checkable predicate on that vector:
the SKEW (max - min over replicas that answered) may not exceed the
window. window=1 is the steady staggered state — the replica being
swapped runs one generation ahead until its neighbors catch up; skew 2+
means a replica was left behind (its swap failed and rolled back while
the rollout marched on) and fanning out further would widen the split.
On detection the rollout HOLDS: no further swap is issued, the report
says who lags, and the router's /healthz carries RouterStatus.SKEW_HOLD
until the operator (or a retried rollout) resolves it.

"All replicas on gen k" — a skew-free vector with no unknowns — is the
completion predicate router-chaos-smoke gates on.

The per-replica swap POST is non-idempotent (each success advances the
generation counter) and is therefore NEVER retried — a failed swap is
recorded and the skew check decides whether the rollout may continue.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Tuple

from tpusvm.router.health import post_json
from tpusvm.status import RouterStatus


def generation_vector(snapshot, model: str) -> Dict[str, Optional[int]]:
    """{url: serving generation of `model`} from a HealthPoller
    snapshot; None for replicas that are down/never-polled or do not
    report the model (both are "unknown", not zero)."""
    out: Dict[str, Optional[int]] = {}
    for url, rec in snapshot.items():
        if rec.state == "down" or rec.polls == 0:
            out[url] = None
        else:
            out[url] = rec.generations.get(model)
    return out


def skew_of(vector: Dict[str, Optional[int]]) -> int:
    """max - min over the KNOWN generations (0 when <= 1 replica
    reports; unknowns are reported separately, not guessed at)."""
    gens = [g for g in vector.values() if g is not None]
    if len(gens) < 2:
        return 0
    return max(gens) - min(gens)


@dataclasses.dataclass
class SkewReport:
    """One skew check's verdict over a model's generation vector."""

    model: str
    vector: Dict[str, Optional[int]]
    skew: int
    window: int
    held: bool                      # skew > window: hold the rollout
    unknown: Tuple[str, ...] = ()   # replicas with no readable generation

    @property
    def laggards(self) -> Tuple[str, ...]:
        """Replicas serving the OLDEST known generation (who to chase)."""
        gens = [g for g in self.vector.values() if g is not None]
        if not gens:
            return ()
        lo = min(gens)
        return tuple(sorted(u for u, g in self.vector.items() if g == lo))

    def to_json(self) -> dict:
        return {
            "model": self.model,
            "vector": dict(sorted(self.vector.items())),
            "skew": self.skew,
            "window": self.window,
            "held": self.held,
            "unknown": list(self.unknown),
            "laggards": list(self.laggards),
        }


def check_skew(snapshot, model: str, window: int = 1) -> SkewReport:
    """Evaluate the skew predicate for `model` over a poller snapshot."""
    if window < 0:
        raise ValueError(f"skew window must be >= 0, got {window}")
    vector = generation_vector(snapshot, model)
    skew = skew_of(vector)
    unknown = tuple(sorted(u for u, g in vector.items() if g is None))
    return SkewReport(model=model, vector=vector, skew=skew,
                      window=window, held=skew > window, unknown=unknown)


def staggered_rollout(poller, model: str, path: str, window: int = 1,
                      post: Callable = post_json,
                      timeout_s: float = 60.0,
                      log_fn: Optional[Callable[[str], None]] = None
                      ) -> dict:
    """Swap `model` to `path` across the fleet, one replica at a time.

    Before EVERY per-replica swap the fleet is re-polled and the skew
    predicate re-checked: skew beyond the window holds the rollout right
    there (status SKEW_HOLD, nothing further issued). Replicas that are
    down or draining are skipped (they restore the new artifact from
    serve_state.json or pick it up on a later rollout — swapping a dead
    replica is not a thing). Each swap POST fires AT MOST ONCE (non-
    idempotent; never retried); a 409 rollback is recorded per replica
    and surfaces as skew on the next check.

    Returns {"status": RouterStatus name, "swapped": [urls], "skipped":
    [urls], "failed": {url: error}, "report": final SkewReport json}.
    """
    log = log_fn or (lambda msg: None)
    poller.poll_once()
    swapped: List[str] = []
    skipped: List[str] = []
    failed: Dict[str, str] = {}
    for url in sorted(poller.snapshot()):
        rep = check_skew(poller.snapshot(), model, window=window)
        if rep.held:
            log(f"router: rollout of {model} HELD at skew {rep.skew} "
                f"(window {window}; laggards {list(rep.laggards)})")
            return {"status": RouterStatus.SKEW_HOLD.name,
                    "swapped": swapped, "skipped": skipped,
                    "failed": failed, "report": rep.to_json()}
        rec = poller.snapshot().get(url)
        if rec is None or rec.state in ("down", "draining"):
            skipped.append(url)
            continue
        code, payload = post(url.rstrip("/") + "/admin/swap",
                             {"name": model, "path": path},
                             timeout_s=timeout_s)
        if code == 200 and payload.get("swapped"):
            swapped.append(url)
            log(f"router: rolled {model} -> generation "
                f"{payload.get('generation')} on {url}")
        else:
            failed[url] = f"HTTP {code}: {payload.get('error', payload)}"
            log(f"router: rollout swap FAILED on {url}: {failed[url]}")
        poller.poll_once()
    final = check_skew(poller.snapshot(), model, window=window)
    if final.held:
        status = RouterStatus.SKEW_HOLD
    else:
        status = RouterStatus.OK
    return {"status": status.name, "swapped": swapped,
            "skipped": skipped, "failed": failed,
            "report": final.to_json()}
