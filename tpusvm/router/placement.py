"""Deterministic rendezvous (HRW) placement: model name -> replica set.

Rendezvous ("highest random weight") hashing gives the router consistent
placement with zero coordination state: every (key, replica) pair gets a
seeded 64-bit score, and a key lives on its k highest-scoring replicas.
The properties the fleet leans on — all proven in tests/test_router.py:

  * stability under LEAVE: removing a replica re-maps ONLY the keys
    whose placement included it (every other key's score ranking is
    untouched — its top-k never mentioned the leaver);
  * stability under JOIN: a new replica steals each rank-slot with
    probability 1/(N+1), so roughly 1/N of keys move and nothing else;
  * byte-reproducibility: the score is blake2b over the seed and the
    pair's names — no process salt, no dict order, no platform word
    size — so `table_bytes` of the same (keys, replicas, k, seed) is
    byte-identical everywhere, the same discipline FaultPlan applies to
    its rng streams.

ReplicaSet is the membership object the proxy reads on its hot path:
mutable join/leave publishing IMMUTABLE `_View` snapshots (version +
replica tuple built under the lock, installed with one GIL-atomic
reference store), so a forwarding thread can read placement lock-free
and can never observe a torn half-updated member list — the invariant
the `router` conc-stress suite perturbs (analysis/conc/stress.py).
"""

from __future__ import annotations

import hashlib
import json
import threading
from typing import Callable, Dict, Iterable, Optional, Sequence, Tuple


def hrw_score(key: str, replica: str, seed: int = 0) -> int:
    """Seeded 64-bit rendezvous weight of placing `key` on `replica`.

    blake2b keyed by the (seed, replica, key) triple: platform-stable
    bytes in, platform-stable integer out. The lengths are mixed in so
    ("ab","c") and ("a","bc") cannot collide."""
    h = hashlib.blake2b(digest_size=8)
    h.update(f"{int(seed)}:{len(replica)}:{replica}:{key}".encode())
    return int.from_bytes(h.digest(), "big")


def place(key: str, replicas: Sequence[str], k: int = 1,
          seed: int = 0) -> Tuple[str, ...]:
    """The k highest-weight replicas for `key`, highest first.

    Deterministic total order: ties (astronomically unlikely) break on
    the replica name so the table stays byte-reproducible. Fewer than k
    replicas means everything hosts the key."""
    if k < 1:
        raise ValueError(f"replication factor must be >= 1, got {k}")
    ranked = sorted(replicas,
                    key=lambda r: (-hrw_score(key, r, seed), r))
    return tuple(ranked[:k])


def placement_table(keys: Iterable[str], replicas: Sequence[str],
                    k: int = 1, seed: int = 0) -> Dict[str, Tuple[str, ...]]:
    """Full key -> placed-replicas map (the auditable placement table)."""
    return {key: place(key, replicas, k=k, seed=seed) for key in keys}


def table_bytes(table: Dict[str, Tuple[str, ...]]) -> bytes:
    """Canonical byte serialization of a placement table.

    Sorted keys, no whitespace: the byte-reproducibility gate — two
    routers with the same (keys, replicas, k, seed) must produce
    identical bytes, which is what router-chaos-smoke asserts."""
    return json.dumps({k: list(v) for k, v in table.items()},
                      sort_keys=True, separators=(",", ":")).encode()


class _View:
    """One immutable membership snapshot: the unit ReplicaSet publishes.

    A reader holds exactly one _View for the duration of a placement
    decision, so version and replicas always agree — the same
    single-bundle discipline serve's `_Generation` uses."""

    __slots__ = ("version", "replicas")

    def __init__(self, version: int, replicas: Tuple[str, ...]):
        self.version = version
        self.replicas = replicas


class ReplicaSet:
    """Replica membership with lock-free torn-proof reads.

    join/leave build a fresh _View under the lock and install it with a
    single reference store; `view()` is one GIL-atomic read, so the
    forwarding hot path never takes the membership lock and never sees
    a half-updated member list. Placement parameters (replication
    factor, seed) are fixed at construction — they are part of the
    fleet's identity, not runtime state.

    `listener`, when set, is called with the NEW view under the lock
    BEFORE it is published — so a log appended by the listener is the
    true serialized flip order and any published view is already
    logged. That ordering is the contract the `router` conc-stress
    suite checks torn-free reads against.
    """

    def __init__(self, replicas: Sequence[str] = (), k: int = 1,
                 seed: int = 0,
                 listener: Optional[Callable[["_View"], None]] = None):
        if k < 1:
            raise ValueError(f"replication factor must be >= 1, got {k}")
        self.k = int(k)
        self.seed = int(seed)
        self._lock = threading.Lock()
        self._listener = listener
        first = _View(1, tuple(sorted(dict.fromkeys(replicas))))
        if listener is not None:
            listener(first)
        self._view = first

    # -------------------------------------------------------------- reads
    def view(self) -> _View:
        """The current immutable membership snapshot (lock-free)."""
        return self._view

    def replicas(self) -> Tuple[str, ...]:
        return self._view.replicas

    @property
    def version(self) -> int:
        return self._view.version

    def placement(self, key: str) -> Tuple[str, ...]:
        """Placed replicas for `key` from ONE view (never torn)."""
        v = self._view
        if not v.replicas:
            return ()
        return place(key, v.replicas, k=self.k, seed=self.seed)

    def table(self, keys: Iterable[str]) -> Dict[str, Tuple[str, ...]]:
        v = self._view
        return placement_table(keys, v.replicas, k=self.k, seed=self.seed)

    # ------------------------------------------------------------- writes
    def _install(self, replicas: Tuple[str, ...]) -> _View:
        # caller holds self._lock
        nxt = _View(self._view.version + 1, replicas)
        if self._listener is not None:
            self._listener(nxt)  # logged BEFORE publication (see class doc)
        self._view = nxt
        return nxt

    def join(self, replica: str) -> bool:
        """Add a replica; False when already a member (no version tick)."""
        with self._lock:
            cur = self._view.replicas
            if replica in cur:
                return False
            self._install(tuple(sorted(cur + (replica,))))
            return True

    def leave(self, replica: str) -> bool:
        """Remove a replica; False when not a member (no version tick)."""
        with self._lock:
            cur = self._view.replicas
            if replica not in cur:
                return False
            self._install(tuple(r for r in cur if r != replica))
            return True
