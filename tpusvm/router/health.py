"""Replica health: /healthz polling -> per-replica state machine.

The router never guesses a replica's condition from a failed forward
alone — a background poller reads each replica's /healthz (the payload
serve/server.py already exports: overall status, per-model breaker
states, the swap/generation block, SLO burn gauges, replica_id,
uptime_s) and runs a small per-replica state machine:

  ok        last poll answered "ok"
  degraded  the replica answered but reported trouble: an open/half-open
            breaker, a failed last swap, OR a burning SLO budget — the
            burn-aware admission input (a burning replica is
            DEPRIORITIZED for new placements before its breaker ever
            trips, the whole point of exporting burn rates)
  draining  the replica answered 503 "draining" (drain() ran): in-flight
            work finishes there but the router sends nothing new
  down      `down_after` consecutive poll failures (connection refused,
            timeout, garbage) — or never successfully polled at all

A single missed poll does NOT down a replica (transient blips keep
their previous state until the streak reaches `down_after`); forwarding
failures in the meantime are the proxy's failover's job.

Per-replica states are exported as gauges
(``router.replica_state{replica=...}``, coded via STATE_CODES) plus a
``router.replicas_up`` count, so the router's own /metrics tells the
fleet story.

The poll thread is owned: daemon=True AND stop() joins it (JXC205
discipline, same as serve/watch.py). `poll_once()` is the deterministic
test surface; the snapshot readers consume is an immutable dict
reference replaced whole under the poller lock.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
import urllib.error
import urllib.request
from typing import Callable, Dict, Optional, Sequence, Tuple, Union

REPLICA_STATES = ("ok", "degraded", "draining", "down")

#: gauge encoding of the state machine (router.replica_state)
STATE_CODES = {"ok": 0, "degraded": 1, "draining": 2, "down": 3}


def fetch_healthz(url: str, timeout_s: float = 2.0) -> dict:
    """GET <url>/healthz and parse the JSON payload.

    A draining replica answers 503 WITH a healthz body — the payload is
    read off the HTTPError too, so "draining" is a state, not a fetch
    failure. Anything unparseable raises (the poller counts it as a
    failed poll)."""
    try:
        with urllib.request.urlopen(url.rstrip("/") + "/healthz",
                                    timeout=timeout_s) as resp:
            raw = resp.read()
    except urllib.error.HTTPError as e:
        raw = e.read()
    obj = json.loads(raw)
    if not isinstance(obj, dict) or "status" not in obj:
        raise ValueError(f"{url}/healthz returned no status: {obj!r}")
    return obj


def post_json(url: str, obj: dict, timeout_s: float = 10.0
              ) -> Tuple[int, dict]:
    """POST a JSON body, return (code, parsed JSON payload).

    Error codes (4xx/5xx) come back as (code, payload) rather than
    raising — a 409 swap rollback is an answer, not an exception. Used
    for the NON-idempotent admin routes, so there is deliberately no
    retry here (rollout.py's per-replica swap must fire at most once)."""
    body = json.dumps(obj).encode()
    req = urllib.request.Request(
        url, data=body, headers={"Content-Type": "application/json"},
        method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            return resp.status, json.loads(resp.read() or b"{}")
    except urllib.error.HTTPError as e:
        try:
            payload = json.loads(e.read() or b"{}")
        except ValueError:
            payload = {}
        return e.code, payload


@dataclasses.dataclass
class ReplicaHealth:
    """One replica's current view, as the last polls saw it."""

    url: str
    state: str = "down"            # never successfully polled yet
    replica_id: Optional[str] = None
    uptime_s: Optional[float] = None
    generations: Dict[str, int] = dataclasses.field(default_factory=dict)
    breakers: Dict[str, str] = dataclasses.field(default_factory=dict)
    burning: Tuple[str, ...] = ()  # models with a burning SLO budget
    failures: int = 0              # CONSECUTIVE failed polls
    polls: int = 0                 # successful polls, ever
    last_error: Optional[str] = None


class HealthPoller:
    """Background /healthz poller feeding the replica state machine.

    `replicas` is a sequence of base URLs or a callable returning one
    (the router passes its ReplicaSet's live view, so joins/leaves are
    picked up on the next poll). `fetch` is injectable for tests —
    poll_once() with a stub fetch is the deterministic state-machine
    test surface."""

    def __init__(self, replicas: Union[Sequence[str], Callable],
                 interval_s: float = 1.0, down_after: int = 2,
                 timeout_s: float = 2.0,
                 fetch: Callable[..., dict] = fetch_healthz,
                 registry=None,
                 log_fn: Optional[Callable[[str], None]] = None):
        if interval_s <= 0:
            raise ValueError(f"interval_s must be > 0, got {interval_s}")
        if down_after < 1:
            raise ValueError(f"down_after must be >= 1, got {down_after}")
        if registry is None:
            from tpusvm.obs.registry import default_registry

            registry = default_registry()
        self._replicas = (replicas if callable(replicas)
                          else (lambda: tuple(replicas)))
        self.interval_s = interval_s
        self.down_after = int(down_after)
        self.timeout_s = timeout_s
        self._fetch = fetch
        self._registry = registry
        self.log = log_fn or (lambda msg: None)
        self._lock = threading.Lock()
        # url -> ReplicaHealth; REPLACED WHOLE under the lock at each
        # poll, so snapshot() hands out a dict no poll will mutate
        self._health: Dict[str, ReplicaHealth] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ polling
    def _classify(self, rec: ReplicaHealth, payload: dict) -> str:
        rec.replica_id = payload.get("replica_id", rec.replica_id)
        if payload.get("uptime_s") is not None:
            rec.uptime_s = float(payload["uptime_s"])
        rec.generations = {
            name: int(sw["generation"])
            for name, sw in (payload.get("swap") or {}).items()
            if isinstance(sw, dict) and "generation" in sw
        }
        rec.breakers = dict(payload.get("models") or {})
        rec.burning = tuple(sorted(
            name for name, st in (payload.get("slo") or {}).items()
            if isinstance(st, dict) and st.get("burning")
        ))
        status = payload.get("status")
        if status == "draining":
            return "draining"
        if status == "degraded" or rec.burning:
            # burn-aware: a burning budget deprioritizes the replica
            # even when the replica itself still says "ok" (slo_shed off)
            return "degraded"
        return "ok"

    def poll_once(self) -> Dict[str, str]:
        """One poll pass over the current membership; {url: state}."""
        urls = tuple(self._replicas())
        with self._lock:
            old = self._health
        nxt: Dict[str, ReplicaHealth] = {}
        for url in urls:
            prev = old.get(url)
            rec = dataclasses.replace(prev) if prev is not None \
                else ReplicaHealth(url=url)
            try:
                payload = self._fetch(url, timeout_s=self.timeout_s)
            except Exception as e:  # noqa: BLE001 — a dead replica is a
                # state, not a poller crash
                rec.failures += 1
                rec.last_error = f"{type(e).__name__}: {e}"
                if rec.failures >= self.down_after or rec.polls == 0:
                    if rec.state != "down":
                        self.log(f"router: replica {url} DOWN "
                                 f"({rec.last_error})")
                    rec.state = "down"
                # else: keep the previous state for the grace window
            else:
                was = rec.state
                rec.failures = 0
                rec.polls += 1
                rec.last_error = None
                rec.state = self._classify(rec, payload)
                if was == "down" and rec.state != "down" and prev is not None:
                    self.log(f"router: replica {url} back ({rec.state})")
            nxt[url] = rec
            self._registry.gauge(
                "router.replica_state", replica=url
            ).set(float(STATE_CODES[rec.state]))
        up = sum(1 for r in nxt.values() if r.state in ("ok", "degraded"))
        self._registry.gauge("router.replicas_up").set(float(up))
        # tpusvm: guarded-by=single-writer publication; only the poll thread writes _health, and it is replaced whole — the earlier read is a snapshot base, not a predicate
        with self._lock:
            self._health = nxt
        return {url: rec.state for url, rec in nxt.items()}

    def snapshot(self) -> Dict[str, ReplicaHealth]:
        """The last poll's view (the dict is never mutated after
        publication; treat the records as read-only)."""
        with self._lock:
            return self._health

    def states(self) -> Dict[str, str]:
        return {url: rec.state for url, rec in self.snapshot().items()}

    # --------------------------------------------------------- admission
    def admissible(self, placed: Sequence[str],
                   fallback: Sequence[str] = ()) -> list:
        """Forwarding order for a request placed on `placed`.

        Two tiers — the placed replicas, then the rest of the fleet
        (`fallback`; in this fleet every replica hosts every model, so
        placement is an affinity, not an exclusivity) — and within each
        tier "ok" before "degraded" (the burn-aware deprioritization).
        draining and down replicas are excluded outright; a replica the
        poller has never seen is excluded until its first good poll."""
        snap = self.snapshot()

        def tier(urls):
            ok_, deg = [], []
            for u in urls:
                rec = snap.get(u)
                if rec is None or rec.state in ("down", "draining"):
                    continue
                (deg if rec.state == "degraded" else ok_).append(u)
            return ok_ + deg

        out = tier(placed)
        seen = set(out)
        out += [u for u in tier(fallback) if u not in seen]
        return out

    # ------------------------------------------------------------ thread
    def start(self) -> "HealthPoller":
        if self._thread is not None:
            raise RuntimeError("health poller already started")
        self.poll_once()  # first view before anyone is admitted

        def run():
            while not self._stop.wait(self.interval_s):
                try:
                    self.poll_once()
                except Exception as e:  # noqa: BLE001 — keep polling
                    self.log(f"router: poll error: "
                             f"{type(e).__name__}: {e}")

        # tpusvm: guarded-by=owner-only lifecycle; start/stop run on the owning thread, the poll thread never touches _thread
        self._thread = threading.Thread(target=run, daemon=True,
                                        name="tpusvm-router-health")
        self._thread.start()
        return self

    def stop(self, timeout_s: float = 5.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout_s)
            # tpusvm: guarded-by=owner-only lifecycle; cleared after the joined thread exited
            self._thread = None
