"""The routing front door: threaded stdlib HTTP over N serve replicas.

One Router owns the three fabric pieces — ReplicaSet membership (HRW
placement), HealthPoller admission, rollout skew tracking — and exposes
them behind the same ThreadingHTTPServer shape serve/http.py uses (one
handler thread per connection, stdlib only).

Forwarding semantics (the failure-semantics table in the README):

  * a predict request is forwarded to its PLACED replica (HRW, k-way);
    on connection failure or a replica 503 the router retries the NEXT
    candidate in admission order, under the shared Retry machinery with
    DEFAULT_IO_POLICY classification — connection-level failures are
    surfaced as the retryable TransientIOError class, anything else
    propagates. The candidate list is placed replicas first, then the
    healthy rest of the fleet (placement is affinity, not exclusivity:
    every replica hosts every model);
  * replica 429 (OVERLOADED / QUEUE_FULL) maps to client 429 with the
    replica's Retry-After preserved and NO failover — backpressure is
    an answer about fleet load, and bouncing the request to the next
    replica would amplify exactly the load being shed;
  * admin routes are NON-idempotent and are never retried: the rollout
    driver issues each per-replica /admin/swap at most once
    (rollout.py), and the router's own admin surface mutates local
    state only;
  * no candidates at all -> 503 NO_REPLICA; candidates exhausted ->
    503 ALL_DOWN (tpusvm.status.RouterStatus).

Every per-replica forward attempt passes the ``router.forward`` fault
point, so a chaos plan can inject transients/latency into the fabric
itself — router-chaos-smoke runs exactly that against real replica
processes being killed and revived.

Counters on the obs registry: router.requests / router.forwards
(per-replica) / router.retries / router.failovers / router.no_replica,
plus the poller's router.replica_state / router.replicas_up gauges.
"""

from __future__ import annotations

import contextlib
import dataclasses
import http.client
import json
import os
import threading
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional, Tuple

from tpusvm import faults
from tpusvm.router.health import HealthPoller
from tpusvm.router.placement import ReplicaSet
from tpusvm.router.rollout import staggered_rollout
from tpusvm.status import RouterStatus


@dataclasses.dataclass(frozen=True)
class RouterConfig:
    """Routing-tier knobs (CLI: `tpusvm router`)."""

    replicas: Tuple[str, ...] = ()   # replica base URLs (http://h:p)
    replication: int = 2             # HRW replication factor k
    seed: int = 0                    # placement seed (byte-reproducible)
    poll_interval_s: float = 0.5     # health poll period
    down_after: int = 2              # consecutive failed polls -> down
    health_timeout_s: float = 2.0    # per-poll fetch timeout
    forward_timeout_s: float = 10.0  # per-attempt forward timeout
    skew_window: int = 1             # rollout hold threshold

    def __post_init__(self):
        if self.replication < 1:
            raise ValueError(
                f"replication must be >= 1, got {self.replication}")


def _http_post(url: str, body: bytes, timeout_s: float,
               headers: Optional[Dict[str, str]] = None
               ) -> Tuple[int, bytes, Optional[str]]:
    """One real forward attempt: (code, body, Retry-After header).

    HTTP error codes come back AS codes (a 429/503 carries a payload the
    client should see); connection-level failures — refused, reset,
    timeout, DNS — are raised as the retryable TransientIOError class so
    the shared retry policy classifies them exactly like a flaky disk.

    `headers` (trace-context injection) merge over the JSON default."""
    req = urllib.request.Request(
        url, data=body,
        headers={"Content-Type": "application/json", **(headers or {})},
        method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            return resp.status, resp.read(), resp.headers.get("Retry-After")
    except urllib.error.HTTPError as e:
        return e.code, e.read(), e.headers.get("Retry-After")
    except (urllib.error.URLError, ConnectionError, TimeoutError,
            OSError, http.client.HTTPException) as e:
        # HTTPException covers a replica dying MID-response (BadStatusLine,
        # IncompleteRead after a SIGKILL) — same failover as a refusal
        raise faults.TransientIOError(
            f"forward to {url} failed: {type(e).__name__}: {e}"
        ) from e


class _CandidatesExhausted(Exception):
    """Every admissible candidate was tried (non-retryable by design)."""


class Router:
    """Placement + admission + failover over a fleet of serve replicas.

    Thread-safety: handler threads call forward()/health() freely. The
    membership view is an immutable snapshot (ReplicaSet), the health
    view an immutable dict (HealthPoller); the only mutable Router state
    is the rollout-hold map, guarded by its own lock."""

    def __init__(self, config: RouterConfig = RouterConfig(),
                 transport: Callable = _http_post,
                 fetch=None, registry=None,
                 log_fn: Optional[Callable[[str], None]] = print,
                 tracer=None):
        if registry is None:
            from tpusvm.obs.registry import default_registry

            registry = default_registry()
        self.config = config
        self.log = log_fn or (lambda msg: None)
        self._transport = transport
        self._registry = registry
        self._tracer = tracer
        self.instance = f"router-{os.getpid()}"
        self.replica_set = ReplicaSet(config.replicas,
                                      k=config.replication,
                                      seed=config.seed)
        poll_kw = {} if fetch is None else {"fetch": fetch}
        self.poller = HealthPoller(
            lambda: self.replica_set.replicas(),
            interval_s=config.poll_interval_s,
            down_after=config.down_after,
            timeout_s=config.health_timeout_s,
            registry=registry, log_fn=self.log, **poll_kw)
        self._lock = threading.Lock()
        # model -> held SkewReport json; written only by rollout()
        self._holds: Dict[str, dict] = {}
        self._httpd = None
        self._http_thread = None
        self._c_requests = registry.counter("router.requests")
        self._c_retries = registry.counter("router.retries")
        self._c_failovers = registry.counter("router.failovers")
        self._c_no_replica = registry.counter("router.no_replica")

    # --------------------------------------------------------- placement
    def candidates(self, model: str) -> list:
        """Admission-ordered forward candidates for `model`: the HRW
        placement first, then the healthy remainder of the fleet."""
        view = self.replica_set.view()
        placed = self.replica_set.placement(model)
        return self.poller.admissible(placed, fallback=view.replicas)

    # -------------------------------------------------------- forwarding
    def forward(self, model: str, body: bytes,
                suffix: str = ":predict", ctx=None
                ) -> Tuple[int, bytes, Optional[str]]:
        """Forward a predict-class request; (code, body, Retry-After).

        Retries the next placement on connection failure or replica 503
        (one attempt per candidate, DEFAULT_IO_POLICY backoff between
        attempts); 429 returns immediately — see the module doc.

        ctx: the inbound TraceContext (the client's X-Tpusvm-Trace
        header). With a tracer attached the forward becomes a
        ``router.forward`` span carrying the inbound ctx in its attrs,
        and the OUTBOUND request carries a context minted under that
        span — the replica's serve.request span then parents into this
        router's timeline. Without a tracer the inbound context passes
        through unchanged (the router is transparent to tracing)."""
        span = contextlib.nullcontext()
        if self._tracer is not None:
            attrs = {"model": model}
            if ctx is not None:
                attrs["ctx"] = ctx.to_dict()
            span = self._tracer.span("router.forward", **attrs)
        with span:
            return self._forward(model, body, suffix, ctx)

    def _forward(self, model: str, body: bytes, suffix: str, ctx
                 ) -> Tuple[int, bytes, Optional[str]]:
        from tpusvm.obs.trace import TRACE_HEADER

        out_ctx = ctx
        if self._tracer is not None and self._tracer.role is not None:
            out_ctx = self._tracer.ctx()  # inside the router.forward span
        headers = ({TRACE_HEADER: out_ctx.to_header()}
                   if out_ctx is not None else None)
        self._c_requests.inc()
        cands = self.candidates(model)
        if not cands:
            self._c_no_replica.inc()
            return 503, json.dumps({
                "error": f"no admissible replica for model {model!r}",
                "router": RouterStatus.NO_REPLICA.name,
            }).encode(), None
        it = iter(cands)
        tried: list = []

        def _one_candidate():
            url = next(it, None)
            if url is None:
                raise _CandidatesExhausted()
            if tried:
                self._c_failovers.inc()
            tried.append(url)
            faults.point("router.forward", replica=url, model=model)
            target = url.rstrip("/") + f"/v1/models/{model}{suffix}"
            if headers is not None:
                code, data, retry_after = self._transport(
                    target, body, self.config.forward_timeout_s, headers)
            else:
                # 3-arg form kept for injected transports that predate
                # trace propagation (tests stub this signature)
                code, data, retry_after = self._transport(
                    target, body, self.config.forward_timeout_s)
            if code == 503:
                # breaker open / draining / scoring error there: the
                # next placement may well serve it — retryable
                raise faults.TransientIOError(
                    f"replica {url} answered 503")
            return url, code, data, retry_after

        policy = dataclasses.replace(faults.DEFAULT_IO_POLICY,
                                     max_attempts=len(cands))
        retry = faults.Retry(policy, op="router.forward",
                             on_retry=self._c_retries.inc)
        try:
            url, code, data, retry_after = retry(_one_candidate)
        except (_CandidatesExhausted, faults.RetryExhaustedError):
            return 503, json.dumps({
                "error": f"every candidate replica failed for "
                         f"{model!r} (tried {tried})",
                "router": RouterStatus.ALL_DOWN.name,
            }).encode(), None
        self._registry.counter("router.forwards", replica=url).inc()
        if code == 429 and retry_after is None:
            retry_after = "1"  # honest backpressure needs a hint
        return code, data, retry_after

    # ----------------------------------------------------------- rollout
    def rollout(self, model: str, path: str,
                window: Optional[int] = None) -> dict:
        """Staggered fleet rollout with skew holds (rollout.py); the
        hold state feeds this router's /healthz until cleared."""
        w = self.config.skew_window if window is None else int(window)
        out = staggered_rollout(self.poller, model, path, window=w,
                                log_fn=self.log)
        with self._lock:
            if out["status"] == RouterStatus.SKEW_HOLD.name:
                self._holds[model] = out["report"]
            else:
                self._holds.pop(model, None)
        return out

    def holds(self) -> Dict[str, dict]:
        with self._lock:
            return dict(self._holds)

    # ------------------------------------------------------------ status
    def status_code(self) -> RouterStatus:
        states = self.poller.states()
        if not self.replica_set.replicas():
            return RouterStatus.NO_REPLICA
        up = [u for u, s in states.items()
              if s in ("ok", "degraded")]
        if not up:
            # never-polled replicas report no state at all: still
            # nothing admissible, which is NO_REPLICA, not ALL_DOWN
            return (RouterStatus.ALL_DOWN if states
                    else RouterStatus.NO_REPLICA)
        if self.holds():
            return RouterStatus.SKEW_HOLD
        return RouterStatus.OK

    def health(self) -> dict:
        """The router's own /healthz payload (fleet-level view)."""
        snap = self.poller.snapshot()
        states = {u: r.state for u, r in snap.items()}
        code = self.status_code()
        if code in (RouterStatus.NO_REPLICA, RouterStatus.ALL_DOWN):
            status = "down"
        elif code == RouterStatus.SKEW_HOLD \
                or any(s != "ok" for s in states.values()):
            status = "degraded"
        else:
            status = "ok"
        view = self.replica_set.view()
        return {
            "status": status,
            "router": code.name,
            "replicas": states,
            "holds": self.holds(),
            "placement": {
                "version": view.version,
                "replicas": list(view.replicas),
                "replication": self.replica_set.k,
                "seed": self.replica_set.seed,
            },
        }

    def replica_detail(self) -> dict:
        """GET /v1/replicas: the poller's full per-replica records."""
        out = {}
        for url, rec in sorted(self.poller.snapshot().items()):
            out[url] = {
                "state": rec.state,
                "replica_id": rec.replica_id,
                "uptime_s": rec.uptime_s,
                "generations": dict(rec.generations),
                "breakers": dict(rec.breakers),
                "burning": list(rec.burning),
                "failures": rec.failures,
                "last_error": rec.last_error,
            }
        return out

    def metrics_text(self) -> str:
        return self._registry.render_text()

    # -------------------------------------------------------------- fleet
    def fleet_payload(self) -> dict:
        """This router process's own fleet snapshot payload (the same
        shape every serve replica exports at /metrics.json)."""
        from tpusvm.obs.fleet import snapshot_payload

        return snapshot_payload(
            "router", self.instance, self._registry.snapshot(),
            status={"router": self.status_code().name,
                    "replicas": self.poller.states()})

    def fleet_view(self):
        """One synchronous scrape over the CURRENT replica membership
        plus this router itself — the GET /fleet/metrics backend."""
        from tpusvm.obs.fleet import FleetCollector

        c = FleetCollector(timeout_s=self.config.health_timeout_s)
        for url in self.replica_set.replicas():
            c.add_replica(url)
        c.add_callable(self.fleet_payload, name="router")
        return c.scrape_once()

    def fleet_metrics_text(self) -> str:
        from tpusvm.obs.fleet import render_fleet_text

        return render_fleet_text(self.fleet_view())

    def fleet_metrics_json(self) -> dict:
        from tpusvm.obs.fleet import fleet_json

        return fleet_json(self.fleet_view())

    # --------------------------------------------------------- lifecycle
    def start(self) -> "Router":
        self.poller.start()
        return self

    def attach_http(self, httpd, thread=None) -> None:
        with self._lock:
            self._httpd = httpd
            self._http_thread = thread

    def close(self) -> None:
        with self._lock:
            httpd, http_thread = self._httpd, self._http_thread
            self._httpd = self._http_thread = None
        if httpd is not None:
            from tpusvm.serve.http import stop_http_server

            stop_http_server(httpd, http_thread)
        self.poller.stop()

    def __enter__(self) -> "Router":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class _RouterHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    @property
    def _router(self) -> Router:
        return self.server.tpusvm_router

    def log_message(self, fmt, *args):  # quiet by default
        if getattr(self.server, "verbose", False):
            super().log_message(fmt, *args)

    def _send(self, code: int, body: bytes, ctype: str,
              retry_after: Optional[str] = None) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        if retry_after is not None:
            self.send_header("Retry-After", retry_after)
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, obj, code: int = 200) -> None:
        self._send(code, json.dumps(obj).encode(), "application/json")

    def _read_body(self) -> bytes:
        length = int(self.headers.get("Content-Length", 0))
        return self.rfile.read(length) if length else b""

    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler API
        if self.path == "/healthz":
            health = self._router.health()
            self._send_json(health,
                            code=503 if health["status"] == "down"
                            else 200)
        elif self.path == "/metrics":
            self._send(200, self._router.metrics_text().encode(),
                       "text/plain; version=0.0.4")
        elif self.path == "/metrics.json":
            self._send_json(self._router.fleet_payload())
        elif self.path == "/fleet/metrics":
            self._send(200, self._router.fleet_metrics_text().encode(),
                       "text/plain; version=0.0.4")
        elif self.path == "/fleet/metrics.json":
            self._send_json(self._router.fleet_metrics_json())
        elif self.path == "/v1/replicas":
            self._send_json(self._router.replica_detail())
        else:
            self._send_json({"error": f"no route {self.path}"}, code=404)

    def do_POST(self):  # noqa: N802 — BaseHTTPRequestHandler API
        if self.path == "/admin/rollout":
            try:
                payload = json.loads(self._read_body() or b"{}")
                name, path = payload["name"], payload["path"]
            except (ValueError, KeyError, TypeError) as e:
                self._send_json(
                    {"error": f"bad request body (need name+path): {e}"},
                    code=400)
                return
            out = self._router.rollout(name, path,
                                       window=payload.get("window"))
            self._send_json(
                out,
                code=409 if out["status"]
                == RouterStatus.SKEW_HOLD.name else 200)
            return
        if self.path in ("/admin/join", "/admin/leave"):
            try:
                payload = json.loads(self._read_body() or b"{}")
                url = payload["url"]
            except (ValueError, KeyError, TypeError) as e:
                self._send_json(
                    {"error": f"bad request body (need url): {e}"},
                    code=400)
                return
            rs = self._router.replica_set
            changed = (rs.join(url) if self.path == "/admin/join"
                       else rs.leave(url))
            self._send_json({"changed": changed,
                             "version": rs.version,
                             "replicas": list(rs.replicas())})
            return
        if self.path.startswith("/v1/models/") and (
                self.path.endswith(":predict")):
            from tpusvm.obs.trace import TRACE_HEADER, TraceContext

            name = self.path[len("/v1/models/"):-len(":predict")]
            code, data, retry_after = self._router.forward(
                name, self._read_body(),
                ctx=TraceContext.from_header(
                    self.headers.get(TRACE_HEADER)))
            self._send(code, data, "application/json",
                       retry_after=retry_after)
            return
        self._send_json({"error": f"no route {self.path}"}, code=404)


def make_router_http(router: Router, host: str = "127.0.0.1",
                     port: int = 8470,
                     verbose: bool = False) -> ThreadingHTTPServer:
    """Bind (not yet serving) the router's HTTP front door.

    port=0 binds an ephemeral port; read httpd.server_address. Same
    ownership contract as serve/http.py: pair with start_http_thread
    and Router.close() (which stops the listener AND the poller)."""
    httpd = ThreadingHTTPServer((host, port), _RouterHandler)
    httpd.tpusvm_router = router
    httpd.verbose = verbose
    httpd.daemon_threads = True
    return httpd
