"""tpusvm.router — the multi-replica serving fabric (routing tier).

PR 14/15 made ONE serving replica unkillable (atomic hot-swap, persisted
compile cache, crash-safe state); this package is the horizontal axis the
ROADMAP's "heavy traffic" item needs: a stdlib-HTTP front door over N
`tpusvm serve` replicas — the Cascade-SVM merge-coordinator role of the
reference's MPI star topology (rank-0, PAPER.md) reborn as a
serving-plane coordinator.

  placement.py  deterministic rendezvous (HRW) hashing: model name ->
                replica set with a configurable replication factor;
                stable under join/leave (only the moved keys re-map) and
                byte-reproducible per seed, plus the torn-free
                ReplicaSet membership view the proxy reads lock-free
  health.py     background poller over every replica's /healthz feeding
                a per-replica state machine (ok / degraded / draining /
                down) with burn-aware admission: a replica whose SLO
                budget burns is deprioritized BEFORE its breaker trips
  rollout.py    generation-skew detection for staggered hot-swap
                rollouts: the per-model generation vector across
                replicas (healthz's swap block); skew beyond the window
                holds the rollout and reports instead of fanning a bad
                artifact fleet-wide
  proxy.py      the threaded HTTP front door (`tpusvm router`): forwards
                predict requests to the placed replica, fails over to
                the next placement on connection failure or 503 under
                the shared Retry/DEFAULT_IO_POLICY machinery, maps
                backpressure honestly (replica 429 -> client 429 +
                Retry-After), and serves its own /healthz + /metrics

Chaos gate: `python -m tpusvm.faults router-chaos-smoke` — real replica
processes killed and revived under multi-threaded client load; zero lost
responses, every response bitwise one of the live generations, and a
staggered rollout completing skew-free.
"""

from tpusvm.router.health import (
    REPLICA_STATES,
    STATE_CODES,
    HealthPoller,
    ReplicaHealth,
)
from tpusvm.router.placement import (
    ReplicaSet,
    hrw_score,
    place,
    placement_table,
    table_bytes,
)
from tpusvm.router.proxy import Router, RouterConfig, make_router_http
from tpusvm.router.rollout import (
    SkewReport,
    check_skew,
    generation_vector,
    skew_of,
    staggered_rollout,
)

__all__ = [
    "HealthPoller",
    "REPLICA_STATES",
    "ReplicaHealth",
    "ReplicaSet",
    "Router",
    "RouterConfig",
    "STATE_CODES",
    "SkewReport",
    "check_skew",
    "generation_vector",
    "hrw_score",
    "make_router_http",
    "place",
    "placement_table",
    "skew_of",
    "staggered_rollout",
    "table_bytes",
]
