"""One cascade leaf as a worker process (``python -m tpusvm.pod.worker``).

The worker connects back to the coordinator, loads ONLY the manifest
shards overlapping its leaf's row set (stream.ShardReader with the
``shards=`` subset — prefetch pipelined, residency bounded at
prefetch_depth + 1 shards, audited via ``max_live_shards`` in READY),
scatters those rows into the exact (slot-addressed) leaf buffer
``stream.assign.partition_from_dataset`` would have built for this
leaf — byte-identical rows, order, padding and global IDs — then
answers TRAIN requests: merge_dedup(recv, own) -> solve -> extract_svs,
the per-rank body of one cascade step. The worker is stateless across
requests (the coordinator owns all round state and ships buffers
explicitly), which is what makes SIGKILL + revive trivially resumable:
a respawned worker re-derives the identical leaf and the coordinator
re-runs the round from its round-start state.

Fault point ``pod.worker`` fires at every request entry; an injected
SimulatedKill is escalated to a REAL ``SIGKILL`` on the worker's own
pid — no atexit, no socket shutdown, no flush — so chaos runs measure
exactly what survives genuine process death.

Because leaves are host processes (not shard_map bodies) they accept
the full solver ladder: the host-side shrinking driver
(shrink_every/shrink_min/...), the K-row cache, the bf16 matmul rungs
— everything the shard_map cascade rejects.
"""

from __future__ import annotations

import argparse
import contextlib
import os
import signal
import socket
import sys

import numpy as np

from tpusvm import faults
from tpusvm.pod.protocol import recv_msg, send_msg

#: solver_opts keys routed to the host-side shrinking driver
#: (solver/shrink.py) instead of blocked_smo_solve directly
SHRINK_DRIVER_KEYS = frozenset({
    "shrink_every", "shrink_min", "shrink_gap_factor", "max_unshrinks",
})


def leaf_solve(train, cfg, accum_dtype, solver: str, solver_opts):
    """One leaf solve, shrinking-driver aware.

    With any shrink-driver knob present (solver="blocked" only — the
    coordinator validates), the solve runs under
    solver.shrink.shrinking_blocked_solve — the PR 9 ladder the
    shard_map cascade cannot host because compaction is a host-side
    segmenting loop. Otherwise this is exactly parallel.cascade._solve,
    so a knob-free pod run is solve-for-solve identical to the
    in-process cascade.
    """
    opts = dict(solver_opts or {})
    if solver == "blocked" and (SHRINK_DRIVER_KEYS & set(opts)):
        from tpusvm.solver.shrink import shrinking_blocked_solve

        return shrinking_blocked_solve(
            train.X,
            train.Y,
            valid=train.valid,
            alpha0=train.alpha,
            C=cfg.C,
            gamma=cfg.gamma,
            eps=cfg.eps,
            tau=cfg.tau,
            max_iter=cfg.max_iter,
            kernel=cfg.kernel,
            degree=cfg.degree,
            coef0=cfg.coef0,
            warm_start=True,
            accum_dtype=accum_dtype,
            **opts,
        )
    from tpusvm.parallel.cascade import _solve

    return _solve(train, cfg, accum_dtype, solver, opts)


def leaf_shards(dataset, part_mask: np.ndarray):
    """Manifest shard indices whose row ranges intersect this leaf.

    part_mask: (n_rows,) bool — True where the row belongs to the leaf.
    Contiguous assignment intersects a contiguous shard run; stratified
    deals touch every shard. Either way only these shards' bytes are
    ever read.
    """
    out = []
    for i, info in enumerate(dataset.manifest.shards):
        if part_mask[info.row_start:info.row_start + info.n_rows].any():
            out.append(i)
    return out


def load_leaf(dataset, leaf: int, n_leaves: int, stratified: bool,
              prefetch_depth: int, scale: bool, dtype, tracer=None):
    """Build this leaf's padded SVBuffer by streaming its shards.

    Byte-identical to row ``_leaf_buf(partition_from_dataset(dataset,
    n_leaves, stratified, scaler), leaf)``: same assignment
    (stream.assign.assign_rows), same scaler, same float64 staging
    before the cast to ``dtype`` — so pod SV IDs live in the same
    global row space as the in-memory and streamed cascade paths.
    Returns (part_buf: SVBuffer, rows_loaded, shards_read,
    max_live_shards).
    """
    import jax.numpy as jnp

    from tpusvm.parallel.svbuffer import SVBuffer
    from tpusvm.stream.assign import assign_rows
    from tpusvm.stream.reader import ShardReader

    n, d = dataset.n_rows, dataset.n_features
    Y_all = dataset.load_labels() if stratified else None
    asg = assign_rows(n, n_leaves, Y=Y_all, stratified=stratified)
    mask = asg.part == leaf
    subset = leaf_shards(dataset, mask)

    cap = asg.cap
    Xp = np.zeros((cap, d), np.float64)
    Yp = np.zeros((cap,), np.int32)
    ids = np.full((cap,), -1, np.int32)
    valid = np.zeros((cap,), bool)

    scaler = dataset.scaler() if scale else None
    reader = ShardReader(dataset, prefetch_depth=prefetch_depth,
                         scaler=scaler, shards=subset)
    infos = [dataset.manifest.shards[i] for i in subset]
    shard_iter = iter(reader)
    for shard_idx, info in zip(subset, infos):
        span = (tracer.span("pod.shard_prefetch", shard=int(shard_idx))
                if tracer is not None else contextlib.nullcontext())
        with span:
            X, Y = next(shard_iter)
            g = np.arange(info.row_start, info.row_start + len(X))
            sel = np.flatnonzero(mask[g])
            if not sel.size:
                continue
            s = asg.slot[g[sel]]
            Xp[s] = X[sel]
            Yp[s] = Y[sel]
            ids[s] = g[sel].astype(np.int32)
            valid[s] = True
    rows = int(valid.sum())
    buf = SVBuffer(
        X=jnp.asarray(Xp, dtype),
        Y=jnp.asarray(Yp),
        alpha=jnp.zeros((cap,), dtype),
        ids=jnp.asarray(ids),
        valid=jnp.asarray(valid),
    )
    return buf, rows, len(subset), reader.max_live_shards


def _buf_from_arrays(arrays, prefix: str):
    import jax.numpy as jnp

    from tpusvm.parallel.svbuffer import SVBuffer

    return SVBuffer(*(jnp.asarray(arrays[prefix + f])
                      for f in SVBuffer._fields))


def _buf_to_arrays(buf, prefix: str):
    from tpusvm.parallel.svbuffer import SVBuffer

    return {prefix + f: np.asarray(getattr(buf, f))
            for f in SVBuffer._fields}


def serve(sock: socket.socket, worker_id: int) -> int:
    """HELLO -> INIT -> READY, then the TRAIN request loop."""
    send_msg(sock, {"op": "hello", "worker_id": worker_id})
    meta, _ = recv_msg(sock)
    if meta["op"] != "init":
        raise RuntimeError(f"expected init, got {meta['op']!r}")

    import jax

    # the coordinator pins the worker to its own backend and x64 state
    # (env vars are unreliable here: site customization may override
    # JAX_PLATFORMS, and the x64 flip must match the coordinator's
    # resolve_accum_dtype decision for bit-identical solves)
    jax.config.update("jax_platforms", meta["platform"])
    jax.config.update("jax_enable_x64", bool(meta["x64"]))
    import jax.numpy as jnp

    from tpusvm.config import SVMConfig
    from tpusvm.parallel.svbuffer import extract_svs, merge_dedup
    from tpusvm.stream.format import open_dataset

    cfg = SVMConfig(**meta["svm_config"])
    dtype = jnp.dtype(meta["dtype"])
    accum = jnp.dtype(meta["accum_dtype"]) if meta["accum_dtype"] else None
    solver = meta["solver"]
    solver_opts = meta["solver_opts"] or {}
    train_cap = int(meta["train_cap"])
    sv_cap = int(meta["sv_cap"])

    # cross-process tracing (optional INIT key — pre-trace coordinators
    # simply don't send it): this worker opens its OWN trace file in the
    # coordinator's trace dir, named by worker id AND pid so a revived
    # worker starts a fresh file, carrying the coordinator's propagated
    # context in its meta record for the merged report to re-parent by
    tracer = None
    tmeta = meta.get("trace")
    if tmeta:
        from tpusvm.obs.trace import TraceContext, Tracer

        tracer = Tracer(
            os.path.join(tmeta["dir"],
                         f"worker{worker_id}.p{os.getpid()}.jsonl"),
            role="pod-worker",
            ctx=TraceContext.from_dict(tmeta.get("ctx")),
            max_bytes=tmeta.get("max_bytes"),
            argv=[f"pod.worker:{worker_id}"],
        )

    from tpusvm.obs.registry import default_registry

    reg = default_registry()
    dataset = open_dataset(meta["data"])
    load_span = (tracer.span("pod.leaf_load", phase=True,
                             leaf=int(meta["leaf"]))
                 if tracer is not None else contextlib.nullcontext())
    with load_span:
        part_buf, rows, shards_read, live_hwm = load_leaf(
            dataset, int(meta["leaf"]), int(meta["n_leaves"]),
            bool(meta["stratified"]), int(meta["prefetch_depth"]),
            bool(meta["scale"]), dtype, tracer=tracer,
        )
    reg.gauge("pod.worker_rows").set(float(rows))
    reg.gauge("pod.live_shards").set(float(live_hwm))
    reg.counter("pod.shards_read").inc(shards_read)
    send_msg(sock, {
        "op": "ready",
        "worker_id": worker_id,
        "rows": rows,
        "shards_read": shards_read,
        "max_live_shards": int(live_hwm),
    })

    from tpusvm.pod.protocol import extract_ctx

    while True:
        meta, arrays = recv_msg(sock)
        op = meta["op"]
        # the fault point fires BEFORE any span opens, so a SimulatedKill
        # escalating to SIGKILL leaves no torn span line in the trace —
        # the killed worker's file simply truncates at its last request
        faults.point("pod.worker", op=op, worker=worker_id,
                     req=meta.get("req"))
        if op == "shutdown":
            if tracer is not None:
                tracer.metrics_snapshot(reg.snapshot())
                tracer.close()
            send_msg(sock, {"op": "bye", "worker_id": worker_id})
            return 0
        if op == "snapshot":
            send_msg(sock, {"op": "snapshot_reply",
                            "req": meta.get("req"),
                            "worker_id": worker_id,
                            "pid": os.getpid(),
                            "snapshot": reg.snapshot()})
            continue
        if op != "train":
            raise RuntimeError(f"unknown pod request {op!r}")
        reg.counter("pod.worker_requests").inc()
        rctx = extract_ctx(meta)
        span_attrs = {"req": meta.get("req"), "phase": True}
        if rctx is not None:
            # re-parents this request under the coordinator's pod.round
            span_attrs["ctx"] = rctx.to_dict()
        train_span = (tracer.span("pod.leaf_train", **span_attrs)
                      if tracer is not None else contextlib.nullcontext())
        with train_span:
            recv_buf = _buf_from_arrays(arrays, "recv_")
            own = (part_buf if meta["use_partition"]
                   else _buf_from_arrays(arrays, "own_"))
            merge_span = (tracer.span("pod.merge")
                          if tracer is not None
                          else contextlib.nullcontext())
            with merge_span:
                train, mcount = merge_dedup(recv_buf, own, train_cap)
            solve_span = (tracer.span("pod.solve")
                          if tracer is not None
                          else contextlib.nullcontext())
            with solve_span:
                res = leaf_solve(train, cfg, accum, solver, solver_opts)
            sv, svcount = extract_svs(train, res.alpha, cfg.sv_tol,
                                      sv_cap)
        send_msg(
            sock,
            {
                "op": "result",
                "req": meta["req"],
                "worker_id": worker_id,
                "merged_count": int(mcount),
                "sv_count": int(svcount),
                "n_iter": int(res.n_iter),
                "status": int(res.status),
                "b": float(res.b),
            },
            _buf_to_arrays(sv, "sv_"),
        )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tpusvm.pod.worker",
        description="pod cascade leaf worker (spawned by the coordinator)",
    )
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--worker-id", type=int, required=True)
    ap.add_argument("--faults", default=None,
                    help="JSON fault plan for chaos runs (initial spawn "
                         "only; the coordinator revives without it)")
    args = ap.parse_args(argv)
    if args.faults:
        faults.activate(faults.load_plan(args.faults))
    sock = socket.create_connection((args.host, args.port), timeout=120)
    sock.settimeout(None)
    try:
        return serve(sock, args.worker_id)
    except faults.SimulatedKill:
        # escalate to REAL process death: no flush, no socket shutdown,
        # no atexit — what the coordinator observes is a genuine SIGKILL
        os.kill(os.getpid(), signal.SIGKILL)
        raise  # unreachable
    finally:
        sock.close()


if __name__ == "__main__":
    sys.exit(main())
