"""Pod cascade coordinator: drive N leaf workers to the SV fixed point.

``pod_fit`` is ``parallel.cascade.cascade_fit`` with the mesh replaced
by processes: the coordinator owns ALL round state (the global SV
buffer, each rank's working SV set) and ships buffers explicitly over
the framed-message protocol, while workers are stateless per request —
each TRAIN is one cascade step body (merge_dedup -> solve ->
extract_svs) against either the worker's resident leaf partition
(step/layer 1) or an explicitly shipped buffer (deeper tree steps).
The star topology's layer-2 union runs IN the coordinator through
``parallel.cascade.star_merge`` — the same helper the in-process host
round uses — followed by a local merged solve, mirroring the
reference's rank-0 retrain (mpi_svm_main2.cpp:540-621).

Identical merges, identical solves, identical diagnostics layout,
identical convergence/overflow/checkpoint logic as cascade_fit's host
rounds — the parity gates (tests/test_pod.py) compare the two engines'
SV-ID sets and accuracies exactly.

Failure semantics:
  * worker death (real SIGKILL or injected ``pod.worker`` kill) is
    detected as a socket error, the worker is respawned (WITHOUT its
    chaos plan — revival must not re-kill), re-derives its leaf
    bit-identically, and the in-flight round re-runs from its
    round-start state — value-identical because round inputs are
    untouched until a round commits;
  * coordinator death between rounds (``pod.round``) resumes from the
    fsync_replace'd checkpoint (pod/state.py, ``pod.merge``) written
    after every round;
  * stale replies from an aborted round are discarded by request
    sequence numbers.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import socket
import subprocess
import sys
import time
import warnings
from typing import Any, Dict, List, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from tpusvm import faults
from tpusvm.config import CascadeConfig, SVMConfig, resolve_accum_dtype
from tpusvm.pod.protocol import attach_ctx, recv_msg, send_msg
from tpusvm.pod.state import (
    check_pod_round_state_config,
    load_pod_round_state,
    save_pod_round_state,
)
from tpusvm.status import Status


class PodResult(NamedTuple):
    """Final global model + run/fleet telemetry.

    The model fields match CascadeResult; the pod extras are the
    provenance (topology, n_leaves) serialized with pod/cascade-trained
    artifacts, the per-worker residency high-water marks the bounded-RSS
    audit asserts on, and the revive count chaos runs check."""

    sv_X: np.ndarray
    sv_Y: np.ndarray
    sv_alpha: np.ndarray
    sv_ids: np.ndarray
    b: float
    rounds: int
    converged: bool
    history: List[Dict[str, Any]]
    topology: str
    n_leaves: int
    worker_rows: tuple
    worker_max_live_shards: tuple
    revives: int


class _WorkerDied(RuntimeError):
    def __init__(self, worker_id: int, why: str):
        super().__init__(f"pod worker {worker_id} died: {why}")
        self.worker_id = worker_id


class _Worker:
    """One leaf worker's process + connection + residency telemetry."""

    def __init__(self, worker_id: int):
        self.worker_id = worker_id
        self.proc: Optional[subprocess.Popen] = None
        self.sock: Optional[socket.socket] = None
        self.rows = 0
        self.max_live_shards = 0

    def close(self) -> None:
        if self.sock is not None:
            with contextlib.suppress(OSError):
                self.sock.close()
            self.sock = None
        if self.proc is not None:
            with contextlib.suppress(OSError):
                self.proc.terminate()
            with contextlib.suppress(Exception):
                self.proc.wait(timeout=10)
            self.proc = None


class _Pod:
    """The worker fleet: spawn/handshake/revive + framed request plumbing."""

    def __init__(self, data: str, n_leaves: int, init_meta: dict,
                 prefetch_depth: int,
                 worker_faults: Optional[Dict[int, str]] = None,
                 tracer=None):
        self.data = data
        self.n_leaves = n_leaves
        self.init_meta = init_meta
        self.prefetch_depth = prefetch_depth
        self.worker_faults = dict(worker_faults or {})
        self.tracer = tracer
        self.workers = [_Worker(r) for r in range(n_leaves)]
        self.revives = 0
        self._req = 0
        self.listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.listener.bind(("127.0.0.1", 0))
        self.listener.listen(n_leaves)
        self.listener.settimeout(120)
        self.port = self.listener.getsockname()[1]

    # ------------------------------------------------------------ spawn
    def _spawn_proc(self, r: int, with_faults: bool) -> subprocess.Popen:
        import tpusvm

        argv = [
            sys.executable, "-m", "tpusvm.pod.worker",
            "--host", "127.0.0.1", "--port", str(self.port),
            "--worker-id", str(r),
        ]
        if with_faults and r in self.worker_faults:
            argv += ["--faults", self.worker_faults[r]]
        env = dict(os.environ)
        pkg_root = os.path.dirname(os.path.dirname(tpusvm.__file__))
        env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
        return subprocess.Popen(argv, env=env)

    def _handshake(self, pending: List[int], with_faults: bool) -> None:
        """Spawn `pending` workers, accept their HELLOs, INIT, READY."""
        for r in pending:
            self.workers[r].proc = self._spawn_proc(r, with_faults)
        waiting = set(pending)
        while waiting:
            conn, _ = self.listener.accept()
            conn.settimeout(None)
            meta, _ = recv_msg(conn)
            wid = int(meta["worker_id"])
            if meta["op"] != "hello" or wid not in waiting:
                conn.close()
                continue
            self.workers[wid].sock = conn
            waiting.discard(wid)
        for r in pending:
            send_msg(self.workers[r].sock,
                     dict(self.init_meta, op="init", leaf=r))
        for r in pending:
            meta, _ = recv_msg(self.workers[r].sock)
            if meta["op"] != "ready":
                raise RuntimeError(
                    f"pod worker {r}: expected ready, got {meta['op']!r}"
                )
            w = self.workers[r]
            w.rows = int(meta["rows"])
            hwm = int(meta["max_live_shards"])
            w.max_live_shards = max(w.max_live_shards, hwm)
            # the bounded-RSS contract, asserted on every (re)spawn: a
            # leaf never holds more than the prefetch pipeline's permits
            if hwm > self.prefetch_depth + 1:
                raise RuntimeError(
                    f"pod worker {r} residency audit failed: "
                    f"max_live_shards={hwm} > prefetch_depth+1="
                    f"{self.prefetch_depth + 1}"
                )

    def start(self) -> None:
        self._handshake(list(range(self.n_leaves)), with_faults=True)

    def revive_dead(self) -> List[int]:
        """Respawn every dead worker (no chaos plan) and re-handshake."""
        dead = []
        for w in self.workers:
            alive = (w.proc is not None and w.proc.poll() is None
                     and w.sock is not None)
            if not alive:
                w.close()
                dead.append(w.worker_id)
        if dead:
            self.revives += len(dead)
            self._handshake(dead, with_faults=False)
        return dead

    # --------------------------------------------------------- requests
    def send_train(self, r: int, recv_buf, own_buf=None) -> int:
        """Ship one TRAIN request; returns its sequence number."""
        from tpusvm.pod.worker import _buf_to_arrays

        self._req += 1
        req = self._req
        arrays = _buf_to_arrays(recv_buf, "recv_")
        if own_buf is not None:
            arrays.update(_buf_to_arrays(own_buf, "own_"))
        meta = {
            "op": "train",
            "req": req,
            "use_partition": own_buf is None,
        }
        if self.tracer is not None and self.tracer.role is not None:
            # per-request context: the worker's train span re-parents
            # under the coordinator's CURRENT open span (pod.round)
            meta = attach_ctx(meta, self.tracer.ctx())
        try:
            send_msg(self.workers[r].sock, meta, arrays)
        except (OSError, ConnectionError) as e:
            raise _WorkerDied(r, repr(e)) from e
        return req

    def collect(self, r: int, req: int):
        """Receive rank r's RESULT for request `req`, skipping stale
        replies left over from an aborted (revived) round."""
        from tpusvm.pod.worker import _buf_from_arrays

        while True:
            try:
                meta, arrays = recv_msg(self.workers[r].sock)
            except (OSError, ConnectionError) as e:
                raise _WorkerDied(r, repr(e)) from e
            if meta.get("op") != "result" or meta.get("req") != req:
                continue
            return meta, _buf_from_arrays(arrays, "sv_")

    def snapshots(self, timeout_s: float = 10.0) -> List[dict]:
        """Fetch every live worker's registry snapshot over the socket
        (the SNAPSHOT op). Dead/unresponsive workers are skipped — this
        is telemetry, not training; it must never fail a fit."""
        out: List[dict] = []
        for w in self.workers:
            if w.sock is None:
                continue
            self._req += 1
            req = self._req
            try:
                w.sock.settimeout(timeout_s)
                send_msg(w.sock, {"op": "snapshot", "req": req})
                while True:
                    meta, _ = recv_msg(w.sock)
                    if meta.get("op") == "snapshot_reply" \
                            and meta.get("req") == req:
                        out.append({"worker_id": w.worker_id,
                                    "pid": meta.get("pid"),
                                    "snapshot": meta["snapshot"]})
                        break
            except (OSError, ConnectionError, KeyError, ValueError):
                continue
            finally:
                with contextlib.suppress(OSError):
                    w.sock.settimeout(None)
        return out

    def shutdown(self) -> None:
        for w in self.workers:
            if w.sock is not None:
                with contextlib.suppress(OSError, ConnectionError):
                    send_msg(w.sock, {"op": "shutdown"})
            w.close()
        with contextlib.suppress(OSError):
            self.listener.close()


# ------------------------------------------------------------- rounds
def _tree_round(pod: _Pod, global_sv, *, n_leaves: int):
    """One classical-cascade round over the worker fleet.

    The host round's rank loop (parallel.cascade._tree_round_host) with
    each rank's step body executed by its worker; within a step all
    active ranks' requests are shipped before any reply is read, so
    distinct workers solve concurrently — the SPMD parallelism of the
    device round, process-shaped."""
    n_steps = n_leaves.bit_length()
    own: dict = {}
    recv = {r: global_sv for r in range(n_leaves)}
    mc = np.zeros((n_leaves, n_steps), np.int64)
    sc = np.zeros((n_leaves, n_steps), np.int64)
    it = np.zeros((n_leaves, n_steps), np.int64)
    st = np.full((n_leaves, n_steps), -1, np.int64)
    b = None
    step, si = 1, 0
    while step <= n_leaves:
        active = list(range(0, n_leaves, step))
        reqs = {
            r: pod.send_train(
                r, recv[r], own_buf=None if step == 1 else own[r])
            for r in active
        }
        for r in active:
            meta, sv = pod.collect(r, reqs[r])
            own[r] = sv
            mc[r, si] = meta["merged_count"]
            sc[r, si] = meta["sv_count"]
            it[r, si] = meta["n_iter"]
            st[r, si] = meta["status"]
            if r == 0:
                b = meta["b"]
        if step < n_leaves:
            for r in range(step, n_leaves, 2 * step):
                recv[r - step] = own[r]
        step *= 2
        si += 1
    diag = {"merged_count": mc, "sv_count": sc, "iters": it, "status": st}
    return own[0], b, diag


def _star_round(pod: _Pod, global_sv, *, n_leaves: int, merged_cap: int,
                full_merged_cap: int, sv_cap: int, cfg, accum_dtype,
                solver, solver_opts):
    """One modified-cascade round: worker layer 1, coordinator layer 2.

    Layer 2 reuses parallel.cascade.star_merge and a local solve — the
    reference's rank-0 retrain runs where the round state lives. A
    union overflowing a tight merged_cap is re-merged at the full
    concatenation bound BEFORE the solve (the in-process cascade
    reaches the same state by re-running the round); the widened cap is
    returned and kept for the remaining rounds.

    Returns (new_global, b, diag, merged_cap)."""
    from tpusvm.parallel.cascade import star_merge
    from tpusvm.parallel.svbuffer import extract_svs
    from tpusvm.pod.worker import leaf_solve

    reqs = {r: pod.send_train(r, global_sv) for r in range(n_leaves)}
    svs, layer1 = [], []
    for r in range(n_leaves):
        meta, sv = pod.collect(r, reqs[r])
        svs.append(sv)
        layer1.append((meta["merged_count"], meta["sv_count"],
                       meta["n_iter"], meta["status"]))
    merged, merged_count = star_merge(svs, merged_cap)
    if merged_cap < full_merged_cap and int(merged_count) > merged_cap:
        warnings.warn(
            f"pod star round: worker-SV union of {int(merged_count)} "
            f"rows overflowed the star merge buffer ({merged_cap}); "
            f"retrying the merge at the full concatenation capacity "
            f"{full_merged_cap} (set star_merge_capacity to avoid the "
            "recompile)",
            RuntimeWarning,
            stacklevel=2,
        )
        merged_cap = full_merged_cap
        merged, merged_count = star_merge(svs, merged_cap)
    res2 = leaf_solve(merged, cfg, accum_dtype, solver, solver_opts)
    new_global, gcount = extract_svs(merged, res2.alpha, cfg.sv_tol,
                                     sv_cap)
    diag = {
        "merged_count": np.array(
            [[m, int(merged_count)] for m, _, _, _ in layer1], np.int64),
        "sv_count": np.array(
            [[s, int(gcount)] for _, s, _, _ in layer1], np.int64),
        "iters": np.array(
            [[i, int(res2.n_iter)] for _, _, i, _ in layer1], np.int64),
        "status": np.array(
            [[s, int(res2.status)] for _, _, _, s in layer1], np.int64),
    }
    return new_global, float(res2.b), diag, merged_cap


# -------------------------------------------------------------- pod_fit
def pod_fit(
    data: str,
    svm_config: SVMConfig = SVMConfig(),
    cascade_config: CascadeConfig = CascadeConfig(),
    dtype=None,
    accum_dtype="auto",
    verbose: bool = False,
    checkpoint_path: Optional[str] = None,
    resume: bool = False,
    solver: str = "pair",
    solver_opts: Optional[dict] = None,
    stratified: bool = False,
    prefetch_depth: int = 2,
    scale: bool = True,
    worker_faults: Optional[Dict[int, str]] = None,
    max_revives: int = 8,
    tracer=None,
    trace_dir: Optional[str] = None,
    trace_max_bytes: Optional[int] = None,
) -> PodResult:
    """Train a binary SVM with the pod (multi-process) cascade.

    data: a sharded dataset directory (stream.ingest/append); each of
    the cascade_config.n_shards leaves becomes one worker process that
    streams only ITS manifest shards. scale=True (default) applies the
    manifest-fitted global MinMaxScaler in every worker — the
    reference's scale-before-scatter, matching fit_cascade_stream.

    solver/solver_opts: the full single-chip ladder. Unlike
    cascade_fit, the host-side shrinking driver knobs (shrink_every,
    shrink_min, ...) are ACCEPTED with solver="blocked" — leaves are
    host processes, so solver.shrink's segmenting loop runs fine there.

    checkpoint_path/resume: per-round coordinator checkpoint through
    pod/state.py (fsync_replace; fault point ``pod.merge``); resume
    refuses a checkpoint from a different n_shards/topology.

    worker_faults: {worker_id: fault-plan path} applied to those
    workers' INITIAL spawn only (chaos runs); a revived worker never
    carries a plan, so an at_hit kill cannot loop forever.

    max_revives: total worker revivals tolerated before the fit gives
    up (a worker that dies deterministically on every respawn would
    otherwise re-run the round forever).

    trace_dir: cross-process tracing — requires a `tracer` constructed
    with a role (it minted identity propagates). Every worker opens its
    own Tracer in this directory (one file per worker PID — a revived
    worker starts a fresh file) with the coordinator's TraceContext
    from the INIT frame, and each TRAIN frame carries the current
    pod.round span's context, so `tpusvm report <trace_dir>` stitches
    the whole fit into one timeline. Tracing is observation only: the
    traced fit is bit-identical to an untraced control
    (benchmarks/obs_fabric.py gates this).
    """
    from tpusvm.parallel.svbuffer import SVBuffer, empty
    from tpusvm.stream.assign import assign_rows
    from tpusvm.stream.format import open_dataset

    if solver not in ("pair", "blocked"):
        raise ValueError(f"unknown solver {solver!r}")
    from tpusvm.pod.worker import SHRINK_DRIVER_KEYS

    driver_keys = sorted(SHRINK_DRIVER_KEYS & set(solver_opts or ()))
    if driver_keys and solver != "blocked":
        raise ValueError(
            f"solver_opts {driver_keys} belong to the shrinking driver, "
            "which wraps the blocked solver; pass solver='blocked' to "
            "use shrinking pod leaves"
        )
    accum = resolve_accum_dtype(accum_dtype)
    if dtype is None:
        dtype = jnp.float32
    dtype = jnp.dtype(dtype)
    cc = cascade_config
    n_leaves = cc.n_shards
    sv_cap = cc.sv_capacity

    dataset = open_dataset(data)
    n, d = dataset.n_rows, dataset.n_features
    Y_all = dataset.load_labels() if stratified else None
    asg = assign_rows(n, n_leaves, Y=Y_all, stratified=stratified)
    chunk = asg.cap
    train_cap = chunk + sv_cap
    merged_cap = cc.resolved_star_merge_capacity()
    full_merged_cap = n_leaves * sv_cap

    global_sv = empty(sv_cap, d, dtype)
    prev_ids: set = set()
    history: List[Dict[str, Any]] = []
    converged = False
    rounds = 0
    b = 0.0
    start_round = 1

    if resume and checkpoint_path is not None \
            and os.path.exists(checkpoint_path):
        check_pod_round_state_config(checkpoint_path, n_leaves,
                                     cc.topology)
        global_sv, prev_ids, start_round, b = load_pod_round_state(
            checkpoint_path, dtype
        )
        if global_sv.capacity != sv_cap or global_sv.X.shape[1] != d:
            raise ValueError(
                "pod checkpoint shapes do not match this run: capacity "
                f"{global_sv.capacity} vs {sv_cap}, d "
                f"{global_sv.X.shape[1]} vs {d}"
            )
        if verbose:
            print(f"resuming pod cascade from round {start_round} "
                  f"({len(prev_ids)} SVs in checkpoint)")
        rounds = start_round - 1
        if start_round > svm_config.max_rounds:
            warnings.warn(
                f"pod checkpoint is already at round {rounds} >= "
                f"max_rounds={svm_config.max_rounds}; returning the "
                "checkpointed model without training (raise max_rounds "
                "to continue)",
                RuntimeWarning,
                stacklevel=2,
            )

    init_meta = {
        "data": os.path.abspath(data),
        "n_leaves": n_leaves,
        "stratified": bool(stratified),
        "prefetch_depth": int(prefetch_depth),
        "scale": bool(scale),
        "platform": jax.default_backend(),
        "x64": bool(jax.config.jax_enable_x64),
        "dtype": dtype.name,
        "accum_dtype": None if accum is None else jnp.dtype(accum).name,
        "svm_config": dataclasses.asdict(svm_config),
        "solver": solver,
        "solver_opts": dict(solver_opts or {}),
        "train_cap": int(train_cap),
        "sv_cap": int(sv_cap),
    }
    fit_span = None
    if trace_dir is not None:
        if tracer is None or tracer.role is None:
            raise ValueError(
                "trace_dir needs a tracer constructed with role= (the "
                "workers parent their spans under its minted context)")
        os.makedirs(trace_dir, exist_ok=True)
    if tracer is not None:
        # opened manually (closed in the outer finally) so the whole
        # fit — spawn, rounds, revivals, shutdown — is one span the
        # workers' propagated contexts parent under
        fit_span = tracer.span("pod.fit", phase=True,
                               topology=cc.topology, n_leaves=n_leaves)
        fit_span.__enter__()
    if trace_dir is not None:
        init_meta["trace"] = {
            "dir": os.path.abspath(trace_dir),
            "max_bytes": trace_max_bytes,
            "ctx": tracer.ctx().to_dict(),
        }
    pod = _Pod(data, n_leaves, init_meta, prefetch_depth,
               worker_faults=worker_faults, tracer=tracer)

    new_global = jax.tree.map(np.asarray, global_sv)
    round_retry = faults.Retry(faults.DEFAULT_IO_POLICY, op="pod.round")
    try:
        pod.start()
        if sum(w.rows for w in pod.workers) != n:
            raise RuntimeError(
                f"pod leaves loaded {sum(w.rows for w in pod.workers)} "
                f"rows, manifest says {n} (assignment bug?)"
            )
        for rnd in range(start_round, svm_config.max_rounds + 1):
            # chaos hook mirroring cascade.round: a kill here dies
            # between rounds; resume must reproduce the uninterrupted
            # trajectory from the checkpoint
            round_retry(faults.point, "pod.round", round=rnd)
            t0 = time.perf_counter()
            round_span = (tracer.span("pod.round", round=rnd)
                          if tracer else contextlib.nullcontext())
            with round_span:
                while True:
                    try:
                        if cc.topology == "tree":
                            out_global, b_r, diag = _tree_round(
                                pod, global_sv, n_leaves=n_leaves)
                        else:
                            out_global, b_r, diag, merged_cap = \
                                _star_round(
                                    pod, global_sv, n_leaves=n_leaves,
                                    merged_cap=merged_cap,
                                    full_merged_cap=full_merged_cap,
                                    sv_cap=sv_cap, cfg=svm_config,
                                    accum_dtype=accum, solver=solver,
                                    solver_opts=solver_opts)
                        break
                    except _WorkerDied as e:
                        if pod.revives >= max_revives:
                            raise RuntimeError(
                                f"pod gave up after {pod.revives} worker "
                                f"revivals (last: {e})"
                            ) from e
                        revived = pod.revive_dead()
                        if verbose:
                            print(f"round {rnd}: revived workers "
                                  f"{revived}, re-running the round")
                        # round inputs (global_sv) are untouched until
                        # the round commits, so the re-run is
                        # bit-identical to an undisturbed round
                        continue
                new_global = jax.tree.map(np.asarray, out_global)
                b = float(b_r)
            dt = time.perf_counter() - t0
            rounds = rnd

            if cc.topology == "tree":
                if diag["merged_count"].max() > train_cap:
                    raise RuntimeError(
                        f"pod train buffer overflow: "
                        f"{diag['merged_count'].max()} > capacity "
                        f"{train_cap}; increase sv_capacity"
                    )
            else:
                if diag["merged_count"][:, 0].max() > train_cap:
                    raise RuntimeError(
                        f"pod train buffer overflow: "
                        f"{diag['merged_count'][:, 0].max()} > capacity "
                        f"{train_cap}"
                    )
            if diag["sv_count"].max() > sv_cap:
                raise RuntimeError(
                    f"SV buffer overflow: {diag['sv_count'].max()} SVs > "
                    f"capacity {sv_cap}; increase sv_capacity"
                )

            ids_arr = np.asarray(new_global.ids)[
                np.asarray(new_global.valid)]
            ids_now = set(ids_arr.tolist())
            history.append({
                "round": rnd,
                "sv_count": len(ids_now),
                "sv_ids": np.sort(ids_arr),
                "b": b,
                "time_s": dt,
                "iters": diag["iters"],
                "status": diag["status"],
            })
            if tracer is not None:
                tracer.event(
                    "pod.round",
                    round=rnd,
                    sv_count=len(ids_now),
                    b=b,
                    time_s=dt,
                    topology=cc.topology,
                    merged_count=diag["merged_count"].tolist(),
                    leaf_sv_count=diag["sv_count"].tolist(),
                    iters=diag["iters"].tolist(),
                    status=diag["status"].tolist(),
                )
                # the report's shared convergence surface (the same
                # record cascade_fit emits), so `tpusvm report` renders
                # a pod trace's round table without a special case
                # worst status over the leaves that solved this round
                # (-1 marks a leaf with no diagnostic — skip it)
                sts = [int(s)
                       for s in np.asarray(diag["status"]).ravel()
                       if int(s) >= 0]
                tracer.event(
                    "convergence.round",
                    round=rnd,
                    updates=int(np.asarray(diag["iters"]).sum()),
                    active=len(ids_now),
                    status=Status(max(sts)).name if sts else "n/a",
                )
            bad = diag["status"][
                diag["status"] >= int(Status.INFEASIBLE_UV)]
            if bad.size:
                warnings.warn(
                    f"pod round {rnd}: solver bail-outs on some leaves "
                    f"(statuses "
                    f"{sorted(set(Status(int(s)).name for s in bad))}); "
                    "the merged model may be partially optimised",
                    RuntimeWarning,
                    stacklevel=2,
                )
            if verbose:
                print(
                    f"=== Round {rnd} === SV count = {len(ids_now)}, "
                    f"b = {b:.15f}, {dt:.3f}s"
                )

            if not ids_now:
                raise RuntimeError(
                    "pod cascade produced an empty global support-vector "
                    "set — all per-leaf solves found no working set (is "
                    "the data sorted by label, making leaves "
                    "single-class?); statuses: "
                    f"{diag['status'].tolist()}"
                )

            if ids_now == prev_ids:
                converged = True
            prev_ids = ids_now

            if checkpoint_path is not None:
                save_pod_round_state(checkpoint_path, new_global,
                                     prev_ids, rnd, b, n_leaves,
                                     cc.topology)

            if converged:
                break
            global_sv = SVBuffer(
                *(jnp.asarray(getattr(new_global, f))
                  for f in SVBuffer._fields))
    finally:
        if tracer is not None:
            # fleet telemetry, best-effort: every live worker's registry
            # snapshot (label-tagged, merged with the coordinator's own)
            # lands in the trace before the fleet is torn down
            with contextlib.suppress(Exception):
                from tpusvm.obs.fleet import merge_fleet, snapshot_payload
                from tpusvm.obs.registry import default_registry

                parts = [snapshot_payload(
                    "pod-worker", f"w{s['worker_id']}", s["snapshot"],
                    pid=s.get("pid")) for s in pod.snapshots()]
                parts.append(snapshot_payload(
                    "pod-coordinator", "coordinator",
                    default_registry().snapshot()))
                tracer.metrics_snapshot(merge_fleet(parts))
        pod.shutdown()
        if fit_span is not None:
            fit_span.__exit__(None, None, None)

    mask = np.asarray(new_global.valid)
    return PodResult(
        sv_X=np.asarray(new_global.X)[mask],
        sv_Y=np.asarray(new_global.Y)[mask],
        sv_alpha=np.asarray(new_global.alpha)[mask],
        sv_ids=np.asarray(new_global.ids)[mask],
        b=b,
        rounds=rounds,
        converged=converged,
        history=history,
        topology=cc.topology,
        n_leaves=n_leaves,
        worker_rows=tuple(w.rows for w in pod.workers),
        worker_max_live_shards=tuple(
            w.max_live_shards for w in pod.workers),
        revives=pod.revives,
    )
