"""Pod-scale out-of-core cascade: coordinator + worker subprocesses.

The reference's MPI cascade (PAPER.md L4) as a process-transport tier:
a coordinator drives N worker subprocesses over stdlib sockets
(length-prefixed framed messages, tpusvm.pod.protocol), each worker
being one cascade LEAF that loads only its manifest shards via
stream.ShardReader (prefetch pipelined against solver compute, never a
full-array materialization) and trains with the single-chip solvers.
SV sets merge through parallel.svbuffer.merge_dedup semantics
bit-for-bit under both reference topologies (binary tree and star),
iterating rounds until the global SV-ID set stabilizes — the same
fixed point as parallel.cascade.cascade_fit, which stays the
in-process parity control.

Because leaves are host-driven processes (not shard_map bodies), they
inherit the full solver ladder the shard_map cascade had to reject:
the shrinking driver, the K-row cache, the bf16 rungs — anything
blocked_smo_solve/shrinking_blocked_solve accepts.

Crash safety: the coordinator checkpoints inter-round state through
fsync_replace (pod/state.py, fault point ``pod.merge``), a killed
worker is revived and the in-flight round re-runs from its round-start
state bit-identically (``pod.worker``), and a killed coordinator
resumes from the checkpoint (``pod.round``) — all exercised by
``python -m tpusvm.faults pod-chaos-smoke``.
"""

from tpusvm.pod.coordinator import PodResult, pod_fit

__all__ = ["PodResult", "pod_fit"]
