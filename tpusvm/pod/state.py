"""Durable pod round-state checkpoint (fault point ``pod.merge``).

The cascade's inter-round state — the global SV buffer, the previous
round's ID set, b — written with the full dura discipline: staged to a
``.tmp`` sibling, committed by ``fsync_replace`` (flush THEN rename),
so a kill at any instant leaves either the previous complete
checkpoint or the new complete checkpoint, never a torn file. This is
the one durability upgrade over parallel.cascade.save_round_state
(plain os.replace): a pod run spans processes and is expected to be
killed, so its checkpoint is registered kill-safe in the dura model
(analysis/dura/model.py DURABLE_MODULES) and covered by the derived
crash-window matrix's ``pod_round`` scenario.

The stored config (n_leaves, topology) is checked on resume: a
checkpoint written under a different partitioning or merge topology is
refused with a config error instead of silently walking a different
cascade.
"""

from __future__ import annotations

import os

import jax.numpy as jnp
import numpy as np

from tpusvm import faults
from tpusvm.parallel.svbuffer import SVBuffer
from tpusvm.utils.durable import fsync_replace

POD_CKPT_VERSION = 1


def save_pod_round_state(path: str, global_sv: SVBuffer, prev_ids,
                         rnd: int, b: float, n_leaves: int,
                         topology: str) -> None:
    """Atomically commit one round's inter-round state."""
    faults.point("pod.merge", path=path, round=rnd)
    tmp = path + ".tmp"
    np.savez_compressed(
        tmp,
        ckpt_version=POD_CKPT_VERSION,
        round=rnd,
        b=b,
        prev_ids=np.asarray(sorted(prev_ids), np.int32),
        n_leaves=n_leaves,
        topology=topology,
        sv_X=np.asarray(global_sv.X),
        sv_Y=np.asarray(global_sv.Y),
        sv_alpha=np.asarray(global_sv.alpha),
        sv_ids=np.asarray(global_sv.ids),
        sv_valid=np.asarray(global_sv.valid),
    )
    # np.savez appends .npz to the temp name; flush-then-rename commit
    fsync_replace(tmp + ".npz", path)


def check_pod_round_state_config(path: str, n_leaves: int,
                                 topology: str) -> None:
    """Refuse a checkpoint written under a different pod config."""
    with np.load(path, allow_pickle=False) as z:
        if int(z["n_leaves"]) != n_leaves:
            raise ValueError(
                f"pod checkpoint config mismatch: it was written for "
                f"n_leaves={int(z['n_leaves'])}, this run partitions "
                f"into {n_leaves}; resume with the original leaf count "
                "or start fresh without resume"
            )
        if str(z["topology"]) != topology:
            raise ValueError(
                f"pod checkpoint config mismatch: it was written for "
                f"topology={str(z['topology'])!r}, this run uses "
                f"{topology!r}; resume with the original topology or "
                "start fresh without resume"
            )


def load_pod_round_state(path: str, dtype=jnp.float32):
    """Returns (global_sv: SVBuffer, prev_ids: set, next_round: int, b)."""
    with np.load(path, allow_pickle=False) as z:
        if int(z["ckpt_version"]) != POD_CKPT_VERSION:
            raise ValueError(
                f"unsupported pod checkpoint version "
                f"{int(z['ckpt_version'])}"
            )
        buf = SVBuffer(
            X=jnp.asarray(z["sv_X"], dtype),
            Y=jnp.asarray(z["sv_Y"]),
            # keep the stored dual dtype: in mixed-precision runs alpha
            # is float64 between rounds, and truncating it would make
            # the resumed trajectory diverge from an uninterrupted run
            alpha=jnp.asarray(z["sv_alpha"]),
            ids=jnp.asarray(z["sv_ids"]),
            valid=jnp.asarray(z["sv_valid"]),
        )
        return (
            buf,
            set(z["prev_ids"].tolist()),
            int(z["round"]) + 1,
            float(z["b"]),
        )
