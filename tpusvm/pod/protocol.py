"""Length-prefixed framed messages over stdlib sockets.

The pod tier's wire format — the reference's MPI_Send/MPI_Recv pairs
(mpi_svm_main3.cpp tags 10-24) become one framed request/reply shape:

    [4-byte BE frame length] [4-byte BE meta length] [meta JSON] [npz]

The npz section is a standard uncompressed ``np.savez`` archive of the
message's arrays (empty when a message carries none), so dtypes and
shapes round-trip exactly: an SVBuffer shipped through a frame comes
back bit-identical, which is what keeps the pod cascade's dedup-by-ID
merges and its ID-set convergence test byte-equal to the in-process
cascade. Meta is a small JSON object (op names, counts, scalars).

Framing is explicit-length on purpose: a worker SIGKILLed mid-write
leaves a SHORT frame, which the reader surfaces as ConnectionError
(peer death), never as a truncated-but-parsed message.

Trace context rides in the meta object under an optional ``ctx`` key
(`attach_ctx`/`extract_ctx`) — meta is free-form JSON, so old peers
that predate the key simply ignore it and old frames (no key) parse
unchanged; `extract_ctx` degrades junk to None rather than raising.
"""

from __future__ import annotations

import io
import json
import socket
import struct
from typing import Dict, Optional, Tuple

import numpy as np

#: refuse absurd frames (corrupt length prefix) before allocating
MAX_FRAME_BYTES = 1 << 31


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    """Read exactly n bytes or raise ConnectionError (peer died)."""
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError(
                f"peer closed mid-frame ({len(buf)}/{n} bytes)"
            )
        buf.extend(chunk)
    return bytes(buf)


def send_msg(sock: socket.socket, meta: dict,
             arrays: Optional[Dict[str, np.ndarray]] = None) -> None:
    """Send one framed message: meta JSON + optional npz array section."""
    mb = json.dumps(meta, sort_keys=True).encode()
    if arrays:
        bio = io.BytesIO()
        np.savez(bio, **arrays)
        ab = bio.getvalue()
    else:
        ab = b""
    frame = struct.pack(">I", len(mb)) + mb + ab
    sock.sendall(struct.pack(">I", len(frame)) + frame)


def attach_ctx(meta: dict, ctx) -> dict:
    """Return a copy of meta carrying a TraceContext under ``ctx``.

    No-op passthrough when ctx is None, so call sites don't branch."""
    if ctx is None:
        return meta
    out = dict(meta)
    out["ctx"] = ctx.to_dict()
    return out


def extract_ctx(meta: dict):
    """The TraceContext carried in a frame's meta, or None (absent key,
    pre-ctx peer, or malformed payload — never an exception)."""
    from tpusvm.obs.trace import TraceContext

    return TraceContext.from_dict(meta.get("ctx"))


def recv_msg(sock: socket.socket
             ) -> Tuple[dict, Dict[str, np.ndarray]]:
    """Receive one framed message -> (meta, arrays)."""
    (total,) = struct.unpack(">I", _recv_exact(sock, 4))
    if total < 4 or total > MAX_FRAME_BYTES:
        raise ConnectionError(f"bad frame length {total}")
    frame = _recv_exact(sock, total)
    (mlen,) = struct.unpack(">I", frame[:4])
    if mlen > total - 4:
        raise ConnectionError(
            f"bad meta length {mlen} in {total}-byte frame"
        )
    meta = json.loads(frame[4:4 + mlen].decode())
    blob = frame[4 + mlen:]
    arrays: Dict[str, np.ndarray] = {}
    if blob:
        with np.load(io.BytesIO(blob), allow_pickle=False) as z:
            arrays = {k: z[k] for k in z.files}
    return meta, arrays
