"""Seeded schedule-perturbation race harness (`conc-stress`).

The dynamic arm of the concurrency auditor: the static rules (JXC201-206)
prove lock DISCIPLINE; this harness hunts the races discipline cannot
express, by amplifying thread interleavings deterministically-by-seed.

How it works — the fault-injection design (tpusvm.faults) applied to
scheduling:

  * every perturbation SITE (lock acquire/release, queue handoff,
    scoring callback, ...) owns an independent decision stream; decision
    k at site s is a pure function of (seed, s, k) via crc32, exactly
    the per-rule rng derivation FaultPlan uses. The expanded plan — the
    SCHEDULE LOG — is therefore byte-identical for a given seed on every
    platform, which is what `--seed S` reproduces;
  * decisions are none / yield (sleep(0): release the GIL at the site) /
    micro-sleep (1-500us: hold the site open long enough for another
    thread to interleave). A plain test crosses a racy window once in
    ten thousand runs; a perturbed schedule parks a thread INSIDE the
    window, so the race fires in a handful of iterations;
  * the harness wraps the REAL objects' private locks/queues/semaphores
    with perturbing delegates (white-box injection — the objects'
    production code is untouched) and drives them from multiple threads
    while checking the objects' own advertised invariants.

Suites (run all: `python -m tpusvm.analysis conc-stress`):

  registry  obs.registry concurrent counter/histogram/gauge writes:
            final totals exact, every mid-write snapshot internally
            consistent AND mergeable (the asserted merge algebra), values
            monotone across snapshots;
  batcher   serve MicroBatcher submit vs drain vs close under load:
            every submitted future resolves with a legal status — never
            dropped, never None (the close-under-load test, perturbed);
  reader    stream ShardReader: residency NEVER exceeds the
            prefetch_depth + 1 permit bound, and every shard arrives
            exactly once, in order;
  breaker   faults CircuitBreaker hammered from many threads: the
            emitted transition sequence is legal for the three-state
            machine (closed -tripped-> open -half_open-> half_open
            -recovered/reopened-> ...), and trip/recovery counters match
            the event log;
  swap      serve ModelRegistry's versioned hot-swap: swapper threads
            flip entries while readers call get_versioned() with the
            registry lock perturbed across the generation flip — a
            reader must never observe a torn pair (the returned entry's
            own generation stamp disagreeing with the generation the
            registry reports), generations must be monotone per reader,
            and the final count must equal 1 + successful swaps;
  router    router ReplicaSet membership: mutator threads join/leave
            replicas with the membership lock perturbed across the view
            flip while a reader spins on view()/placement — a reader
            must never observe a torn view (a published version whose
            member tuple disagrees with the serialized flip log), view
            versions must be monotone per reader, placement must be a
            pure function of the view, and the final version must equal
            1 + applied membership changes;
  racy      a DELIBERATELY broken fixture (read-modify-write with no
            lock) the harness must catch — the self-test proving the
            perturber actually amplifies races (`--self-test`).

Any violation report carries the seed that reproduces it.
"""

from __future__ import annotations

import dataclasses
import threading
import time
import zlib
from typing import Callable, Dict, List, Optional, Sequence

DEFAULT_SEED = 0

# canonical perturbation sites per suite: the schedule log expands these
SUITE_SITES = {
    "registry": ("registry.lock.acquire", "registry.lock.release",
                 "registry.scrape"),
    "batcher": ("batcher.q.put", "batcher.q.get", "batcher.score",
                "batcher.submit", "batcher.lifecycle"),
    "reader": ("reader.permits.acquire", "reader.permits.release",
               "reader.q.put", "reader.q.get", "reader.load",
               "reader.consume"),
    "breaker": ("breaker.step",),
    "swap": ("swap.lock.acquire", "swap.lock.release", "swap.read",
             "swap.flip"),
    "router": ("router.lock.acquire", "router.lock.release",
               "router.read", "router.mutate", "router.flip"),
    "racy": ("racy.rmw",),
}


class SchedulePerturber:
    """Deterministic-by-seed scheduling noise.

    perturb(site) consumes the next decision of `site`'s stream; the
    decision is a pure function of (seed, site, k) so the expanded plan
    (`plan_lines`) is byte-identical across runs and platforms — the
    reproducibility contract behind "report the seed"."""

    def __init__(self, seed: int = DEFAULT_SEED, p_sleep: float = 0.20,
                 p_yield: float = 0.30, max_sleep_us: int = 400):
        self.seed = int(seed)
        self.p_sleep = p_sleep
        self.p_yield = p_yield
        self.max_sleep_us = max_sleep_us
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {}

    def decide(self, site: str, k: int):
        """(action, sleep_us) for event k at `site` — pure, no state."""
        h = zlib.crc32(f"{self.seed}:{site}:{k}".encode()) & 0xFFFFFFFF
        r = h / 2**32
        if r < self.p_sleep:
            return "sleep", 1 + h % self.max_sleep_us
        if r < self.p_sleep + self.p_yield:
            return "yield", 0
        return "none", 0

    def perturb(self, site: str) -> None:
        with self._lock:
            k = self._counts.get(site, 0)
            self._counts[site] = k + 1
        action, us = self.decide(site, k)
        if action == "sleep":
            time.sleep(us * 1e-6)
        elif action == "yield":
            time.sleep(0)  # release the GIL at the site

    def consumed(self) -> Dict[str, int]:
        with self._lock:
            return dict(sorted(self._counts.items()))

    def plan_lines(self, sites: Sequence[str], n: int) -> List[str]:
        """The deterministic schedule log: the first `n` decisions of
        each site, independent of the interleaving that consumed them."""
        lines = []
        for site in sorted(sites):
            for k in range(n):
                action, us = self.decide(site, k)
                lines.append(f"{site} {k} {action} {us}")
        return lines


# ------------------------------------------------------------- wrappers
class PerturbLock:
    """Lock delegate perturbing at acquire/release. Drop-in for the
    threading.Lock the obs registry shares across its metric wrappers."""

    def __init__(self, perturber: SchedulePerturber, site: str,
                 inner=None):
        self._inner = inner if inner is not None else threading.Lock()
        self._p = perturber
        self._site = site

    def acquire(self, *args, **kwargs):
        self._p.perturb(self._site + ".acquire")
        return self._inner.acquire(*args, **kwargs)

    def release(self):
        self._inner.release()
        self._p.perturb(self._site + ".release")

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class PerturbSemaphore:
    """Semaphore delegate perturbing at the permit handoff points."""

    def __init__(self, inner, perturber: SchedulePerturber, site: str):
        self._inner = inner
        self._p = perturber
        self._site = site

    def acquire(self, *args, **kwargs):
        self._p.perturb(self._site + ".acquire")
        return self._inner.acquire(*args, **kwargs)

    def release(self, *args, **kwargs):
        self._inner.release(*args, **kwargs)
        self._p.perturb(self._site + ".release")


class PerturbQueue:
    """Queue delegate perturbing before/after every handoff. Wraps the
    object's existing queue INSTANCE so a worker thread already blocked
    on the inner queue still observes wrapped puts."""

    def __init__(self, inner, perturber: SchedulePerturber, site: str):
        self._inner = inner
        self._p = perturber
        self._site = site

    def put(self, item, *args, **kwargs):
        self._p.perturb(self._site + ".put")
        self._inner.put(item, *args, **kwargs)

    def put_nowait(self, item):
        self._p.perturb(self._site + ".put")
        self._inner.put_nowait(item)

    def get(self, *args, **kwargs):
        item = self._inner.get(*args, **kwargs)
        self._p.perturb(self._site + ".get")
        return item

    def get_nowait(self):
        item = self._inner.get_nowait()
        self._p.perturb(self._site + ".get")
        return item

    def qsize(self):
        return self._inner.qsize()

    def __getattr__(self, name):
        return getattr(self._inner, name)


# --------------------------------------------------------------- report
@dataclasses.dataclass
class StressReport:
    """Outcome of one suite run; `schedule` is the deterministic seeded
    plan (same seed => byte-identical), `events` the consumed counts."""

    suite: str
    seed: int
    violations: List[str]
    events: Dict[str, int]
    schedule: List[str]
    elapsed_s: float

    @property
    def ok(self) -> bool:
        return not self.violations

    def render(self) -> str:
        head = (f"conc-stress {self.suite}: "
                f"{'ok' if self.ok else 'VIOLATION'} seed={self.seed} "
                f"events={sum(self.events.values())} "
                f"elapsed={self.elapsed_s:.2f}s")
        lines = [head]
        for v in self.violations:
            lines.append(f"  {self.suite}: {v}")
        if self.violations:
            lines.append(
                f"  reproduce: python -m tpusvm.analysis conc-stress "
                f"--suite {self.suite} --seed {self.seed}")
        return "\n".join(lines)


def _run_threads(fns: List[Callable[[], None]],
                 timeout_s: float = 60.0) -> List[str]:
    """Run the thunks on owned (joined) threads; worker exceptions come
    back as violations instead of dying silently on a daemon thread."""
    errors: List[str] = []
    elock = threading.Lock()

    def wrap(fn):
        def run():
            try:
                fn()
            except BaseException as e:  # noqa: BLE001 — reported, not lost
                with elock:
                    errors.append(f"worker raised {type(e).__name__}: {e}")
        return run

    threads = [threading.Thread(target=wrap(fn), daemon=True)
               for fn in fns]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=timeout_s)
        if t.is_alive():
            errors.append("worker thread failed to finish in "
                          f"{timeout_s}s (possible deadlock)")
    return errors


def _report(suite: str, perturber: SchedulePerturber,
            violations: List[str], t0: float,
            plan_events: int = 32) -> StressReport:
    return StressReport(
        suite=suite, seed=perturber.seed, violations=violations,
        events=perturber.consumed(),
        schedule=perturber.plan_lines(SUITE_SITES[suite], plan_events),
        elapsed_s=time.perf_counter() - t0,
    )


# ---------------------------------------------------------------- suites
def stress_registry(seed: int = DEFAULT_SEED, iters: int = 300,
                    threads: int = 4) -> StressReport:
    """obs.registry under concurrent writes + mid-write snapshots.

    Invariants: exact final totals (counter adds are never lost), every
    snapshot — including ones taken mid-write — is internally consistent
    (histogram bucket counts sum to its count) and satisfies the merge
    algebra (commutative, self-merge well-formed), and counter values
    are monotone across the snapshot sequence."""
    from tpusvm.obs.registry import MetricsRegistry, merge_snapshots

    p = SchedulePerturber(seed)
    t0 = time.perf_counter()
    reg = MetricsRegistry()
    # wrap BEFORE the first metric is created: every wrapper stores this
    # (now perturbing) shared lock
    reg._lock = PerturbLock(p, "registry.lock", inner=reg._lock)
    c = reg.counter("conc.hits")
    h = reg.histogram("conc.lat", bounds=(0.5, 1.5))
    g = reg.gauge("conc.depth")
    violations: List[str] = []
    stop = threading.Event()
    snaps: List[dict] = []

    def writer(t):
        def run():
            for i in range(iters):
                c.inc()
                h.observe((t + i) % 3)
                g.set_max(t * iters + i)
        return run

    def scraper():
        while not stop.is_set():
            snaps.append(reg.snapshot())
            p.perturb("registry.scrape")

    sthread = threading.Thread(target=scraper, daemon=True)
    sthread.start()
    violations += _run_threads([writer(t) for t in range(threads)])
    stop.set()
    sthread.join(timeout=30.0)
    snaps.append(reg.snapshot())

    def entry(snap, name):
        for e in snap["metrics"]:
            if e["name"] == name:
                return e
        return None

    total = threads * iters
    final = snaps[-1]
    ce = entry(final, "conc.hits")
    if ce is None or ce["value"] != total:
        violations.append(
            f"counter lost updates: {ce and ce['value']} != {total}")
    he = entry(final, "conc.lat")
    if he is None or he["count"] != total or sum(he["counts"]) != total:
        violations.append(
            f"histogram lost observations: count={he and he['count']} "
            f"buckets={he and sum(he['counts'])} != {total}")
    ge = entry(final, "conc.depth")
    if ge is None or ge["value"] != (threads - 1) * iters + iters - 1:
        violations.append(
            f"gauge high-water wrong: {ge and ge['value']}")
    prev = -1
    for i, s in enumerate(snaps):
        hed = entry(s, "conc.lat")
        if hed is not None and sum(hed["counts"]) != hed["count"]:
            violations.append(
                f"snapshot {i} torn mid-write: histogram bucket sum "
                f"{sum(hed['counts'])} != count {hed['count']}")
        ced = entry(s, "conc.hits")
        if ced is not None:
            if ced["value"] < prev:
                violations.append(
                    f"snapshot {i} counter went backwards: "
                    f"{ced['value']} < {prev}")
            prev = ced["value"]
        try:
            merge_snapshots(s)  # mid-write snapshots must stay mergeable
        except ValueError as e:
            violations.append(f"snapshot {i} unmergeable: {e}")
    if len(snaps) >= 2:
        a, b = snaps[len(snaps) // 2], snaps[-1]
        if merge_snapshots(a, b) != merge_snapshots(b, a):
            violations.append("merge algebra not commutative on "
                              "mid-run snapshots")
    return _report("registry", p, violations, t0)


def stress_batcher(seed: int = DEFAULT_SEED, iters: int = 30,
                   threads: int = 4) -> StressReport:
    """MicroBatcher submit vs drain vs close under perturbed handoffs.

    Invariant: no dropped futures — every submit resolves to a
    ServeResult with a legal status, even while drain() and close() race
    the clients; after close the queue is swept empty."""
    import numpy as np

    from tpusvm.serve.batcher import MicroBatcher
    from tpusvm.status import ServeStatus

    p = SchedulePerturber(seed)
    t0 = time.perf_counter()

    def run_batch(X):
        p.perturb("batcher.score")
        s = X.sum(axis=1)
        return s, np.where(s > 0, 1, -1)

    b = MicroBatcher(run_batch, max_batch=8, max_delay_s=0.001,
                     queue_size=64, timeout_s=10.0)
    b._q = PerturbQueue(b._q, p, "batcher.q")
    results: List[List[object]] = [[] for _ in range(threads)]

    def client(t):
        def run():
            for _ in range(iters):
                p.perturb("batcher.submit")
                results[t].append(b.submit(np.ones(4) * (t + 1)))
        return run

    def done() -> int:
        return sum(len(r) for r in results)

    def lifecycle():
        # let real batches flow, then race drain/close against the
        # remaining clients (the perturber decides the exact lag)
        deadline = time.monotonic() + 10.0
        while done() < (threads * iters) // 2 and \
                time.monotonic() < deadline:
            p.perturb("batcher.lifecycle")
            time.sleep(0.0005)
        b.drain(timeout_s=10.0)
        for _ in range(3):
            p.perturb("batcher.lifecycle")
        b.close()

    violations = _run_threads([client(t) for t in range(threads)]
                              + [lifecycle])
    b.close()  # idempotent
    got = sum(len(r) for r in results)
    if got != threads * iters:
        violations.append(
            f"dropped futures: {got} results for {threads * iters} "
            "submits")
    legal = set(ServeStatus)
    for t, rs in enumerate(results):
        for r in rs:
            if r is None:
                violations.append(f"client {t} got a None result")
            elif ServeStatus(r.status) not in legal:
                violations.append(
                    f"client {t} got illegal status {r.status!r}")
    if b._q.qsize() != 0:
        violations.append(
            f"queue not swept after close: {b._q.qsize()} items remain")
    return _report("batcher", p, violations, t0)


class _StubShardInfo:
    def __init__(self, i):
        self.filename = f"shard_{i:05d}.npz"


class _StubManifest:
    def __init__(self, n):
        self.shards = [_StubShardInfo(i) for i in range(n)]


class _StubDataset:
    """Duck-typed stand-in for stream.format.ShardedDataset: in-memory
    shards, perturbed loads — the reader's residency accounting is what
    is under test, not the file format."""

    def __init__(self, n_shards: int, rows: int, d: int, perturb):
        import numpy as np

        self.n_shards = n_shards
        self.manifest = _StubManifest(n_shards)
        self._perturb = perturb
        self._shards = [
            (np.full((rows, d), float(i)), np.full(rows, i % 2 * 2 - 1))
            for i in range(n_shards)
        ]

    def load_shard(self, i: int, verify: bool = False):
        self._perturb("reader.load")
        return self._shards[i]


def stress_reader(seed: int = DEFAULT_SEED, n_shards: int = 12,
                  depth: int = 2) -> StressReport:
    """ShardReader residency bound under perturbed permits and handoffs.

    Invariant: live shards never exceed prefetch_depth + 1 (sampled
    concurrently AND via the reader's own high-water mark), every shard
    arrives exactly once in manifest order."""
    from tpusvm.obs.registry import MetricsRegistry
    from tpusvm.stream.reader import ShardReader

    p = SchedulePerturber(seed)
    t0 = time.perf_counter()
    ds = _StubDataset(n_shards, rows=8, d=4, perturb=p.perturb)
    reader = ShardReader(ds, prefetch_depth=depth,
                         metrics=MetricsRegistry())
    # worker starts on first iteration, so the swaps below are safe
    reader._permits = PerturbSemaphore(reader._permits, p,
                                      "reader.permits")
    reader._q = PerturbQueue(reader._q, p, "reader.q")
    violations: List[str] = []
    stop = threading.Event()
    sampled_max = [0]

    def sampler():
        while not stop.is_set():
            sampled_max[0] = max(sampled_max[0], reader.live_shards)
            p.perturb("reader.consume")

    sthread = threading.Thread(target=sampler, daemon=True)
    sthread.start()
    seen = []
    for X, Y in reader:
        seen.append(int(X[0, 0]))
        p.perturb("reader.consume")
    stop.set()
    sthread.join(timeout=30.0)
    bound = depth + 1
    if reader.max_live_shards > bound:
        violations.append(
            f"residency bound broken: max_live_shards="
            f"{reader.max_live_shards} > prefetch_depth+1={bound}")
    if sampled_max[0] > bound:
        violations.append(
            f"sampled residency {sampled_max[0]} > bound {bound}")
    if seen != list(range(n_shards)):
        violations.append(
            f"shard order/coverage broken: {seen} != "
            f"{list(range(n_shards))}")
    return _report("reader", p, violations, t0)


def stress_breaker(seed: int = DEFAULT_SEED, iters: int = 150,
                   threads: int = 4) -> StressReport:
    """CircuitBreaker transition legality under concurrent drivers.

    The listener runs under the breaker's own lock, so the event log IS
    the true serialized transition order; replaying it through the
    three-state machine catches any illegal emission. Counters must
    match the log exactly."""
    from tpusvm.faults.breaker import CircuitBreaker

    p = SchedulePerturber(seed)
    t0 = time.perf_counter()
    clock_lock = threading.Lock()
    now = [0.0]

    def clock():
        with clock_lock:
            now[0] += 0.01
            return now[0]

    events: List[str] = []

    def listener(event):
        # called under the breaker lock: append order is transition order
        events.append(event)

    br = CircuitBreaker(threshold=3, cooldown_s=0.05, clock=clock,
                        listener=listener, name="stress")

    def driver(t):
        def run():
            for i in range(iters):
                p.perturb("breaker.step")
                h = zlib.crc32(f"{seed}:drv{t}:{i}".encode())
                if br.allow():
                    if h % 5 < 2:
                        br.record_failure()
                    else:
                        br.record_success()
        return run

    violations = _run_threads([driver(t) for t in range(threads)])
    legal = {"closed": {"tripped"},
             "open": {"half_open"},
             "half_open": {"recovered", "reopened"}}
    nxt = {"tripped": "open", "half_open": "half_open",
           "recovered": "closed", "reopened": "open"}
    state = "closed"
    for i, ev in enumerate(events):
        if ev not in legal[state]:
            violations.append(
                f"illegal transition event[{i}]={ev!r} from state "
                f"{state!r} (log: {events[max(0, i - 3):i + 1]})")
            break
        state = nxt[ev]
    d = br.describe()
    if d["trips"] != events.count("tripped"):
        violations.append(
            f"trip counter {d['trips']} != tripped events "
            f"{events.count('tripped')}")
    if d["recoveries"] != events.count("recovered"):
        violations.append(
            f"recovery counter {d['recoveries']} != recovered events "
            f"{events.count('recovered')}")
    return _report("breaker", p, violations, t0)


def stress_swap(seed: int = DEFAULT_SEED, iters: int = 120,
                threads: int = 4) -> StressReport:
    """serve ModelRegistry versioned swap: the generation flip perturbed.

    The REAL registry object (serve/registry.py) hammered with its lock
    wrapped by PerturbLock: `threads` swapper threads flip fresh stub
    entries in while one reader thread spins on get_versioned().
    Invariants — the atomic-hot-swap contract the serving runtime
    builds on:

      * no torn pair: get_versioned's (entry, generation) always agree
        with the entry's own `.generation` stamp (swap writes both in
        ONE lock region; a torn implementation parks exactly where the
        perturber sleeps);
      * monotone: generations observed by the reader never decrease;
      * exact count: the final generation is 1 + total swaps (no flip
        lost, none double-counted)."""
    from tpusvm.serve.registry import ModelRegistry

    p = SchedulePerturber(seed)
    t0 = time.perf_counter()
    reg = ModelRegistry()
    reg._lock = PerturbLock(p, "swap.lock", inner=reg._lock)

    class _Stub:
        """Duck-typed ModelEntry: the registry reads .name and stamps
        .generation; nothing else is touched by add/swap/get."""

        __slots__ = ("name", "generation", "tag")

        def __init__(self, tag):
            self.name = "m"
            self.generation = 1
            self.tag = tag

    reg.add(_Stub(("init", 0)))
    violations: List[str] = []
    vlock = threading.Lock()
    stop = threading.Event()

    def swapper(t):
        def run():
            for i in range(iters):
                reg.swap(_Stub((t, i)))
                p.perturb("swap.flip")
        return run

    def reader():
        last = 0
        while not stop.is_set():
            e, gen = reg.get_versioned("m")
            p.perturb("swap.read")
            if e.generation != gen:
                with vlock:
                    violations.append(
                        f"torn read: entry stamped generation "
                        f"{e.generation} but registry reported {gen} "
                        f"(tag {e.tag})")
            if gen < last:
                with vlock:
                    violations.append(
                        f"generation went backwards: {gen} after {last}")
            last = gen

    rthread = threading.Thread(target=reader, daemon=True)
    rthread.start()
    violations += _run_threads([swapper(t) for t in range(threads)])
    stop.set()
    rthread.join(timeout=30.0)
    final = reg.generation("m")
    want = 1 + threads * iters
    if final != want:
        violations.append(
            f"final generation {final} != 1 + {threads * iters} swaps")
    return _report("swap", p, violations, t0)


def stress_router(seed: int = DEFAULT_SEED, iters: int = 150,
                  threads: int = 4) -> StressReport:
    """router ReplicaSet membership: the view flip perturbed.

    The REAL membership object (router/placement.py) with its lock
    wrapped by PerturbLock: `threads` mutator threads join/leave unique
    replicas while one reader spins on view() + placement. The listener
    — called under the lock BEFORE publication, ReplicaSet's documented
    contract — appends each flipped view to a log, so the log IS the
    serialized flip order. Invariants — the lock-free-read contract the
    proxy's forwarding hot path builds on:

      * no torn view: every observed (version, replicas) pair equals
        the logged pair for that version (a view assembled outside the
        lock parks exactly where the perturber sleeps);
      * monotone: view versions observed by the reader never decrease;
      * pure placement: placing a key against a captured view is
        repeatable and stays inside that view's members;
      * exact count: the final version is 1 + applied membership
        changes (no flip lost, none double-counted)."""
    from tpusvm.router.placement import ReplicaSet, place

    p = SchedulePerturber(seed)
    t0 = time.perf_counter()
    log: Dict[int, tuple] = {}
    llock = threading.Lock()

    def listener(view):
        with llock:
            log[view.version] = view.replicas
        p.perturb("router.flip")

    rs = ReplicaSet([f"http://seed{i}" for i in range(4)], k=2, seed=7,
                    listener=listener)
    rs._lock = PerturbLock(p, "router.lock", inner=rs._lock)
    violations: List[str] = []
    vlock = threading.Lock()
    stop = threading.Event()
    applied = [0] * threads

    def mutator(t):
        def run():
            for i in range(iters):
                url = f"http://m{t}-{i}"
                if rs.join(url):
                    applied[t] += 1
                p.perturb("router.mutate")
                if rs.leave(url):
                    applied[t] += 1
        return run

    def reader():
        last = 0
        while not stop.is_set():
            v = rs.view()
            p.perturb("router.read")
            with llock:
                logged = log.get(v.version)
            if logged != v.replicas:
                with vlock:
                    violations.append(
                        f"torn view: version {v.version} published "
                        f"{v.replicas} but the flip log recorded "
                        f"{logged}")
            if v.version < last:
                with vlock:
                    violations.append(
                        f"view version went backwards: {v.version} "
                        f"after {last}")
            last = v.version
            placed = place("m", v.replicas, k=rs.k, seed=rs.seed)
            if placed != place("m", v.replicas, k=rs.k, seed=rs.seed) \
                    or not set(placed) <= set(v.replicas):
                with vlock:
                    violations.append(
                        f"placement of a captured view is not pure: "
                        f"{placed} over {v.replicas}")

    rthread = threading.Thread(target=reader, daemon=True)
    rthread.start()
    violations += _run_threads([mutator(t) for t in range(threads)])
    stop.set()
    rthread.join(timeout=30.0)
    final = rs.version
    want = 1 + sum(applied)
    if final != want:
        violations.append(
            f"final view version {final} != 1 + {sum(applied)} applied "
            "membership changes")
    return _report("router", p, violations, t0)


# ----------------------------------------------------------- self-test
class RacyTally:
    """DELIBERATELY racy: classic read-modify-write with no lock. The
    perturbation point sits inside the race window, so a seeded schedule
    parks one thread between the read and the write and another thread's
    update is lost — the fixture the harness must provably catch."""

    def __init__(self):
        self.total = 0

    def add(self, perturb) -> None:
        v = self.total
        perturb("racy.rmw")
        self.total = v + 1


def stress_racy(seed: int = DEFAULT_SEED, iters: int = 60,
                threads: int = 4) -> StressReport:
    """The known-bad fixture: MUST report a violation under perturbation
    (asserted by tests and `conc-stress --self-test`)."""
    p = SchedulePerturber(seed)
    t0 = time.perf_counter()
    tally = RacyTally()

    def worker():
        for _ in range(iters):
            tally.add(p.perturb)

    violations = _run_threads([worker for _ in range(threads)])
    expected = threads * iters
    if tally.total != expected:
        violations.append(
            f"lost {expected - tally.total} of {expected} updates "
            "(unguarded read-modify-write)")
    return _report("racy", p, violations, t0)


SUITES: Dict[str, Callable[..., StressReport]] = {
    "registry": stress_registry,
    "batcher": stress_batcher,
    "reader": stress_reader,
    "breaker": stress_breaker,
    "swap": stress_swap,
    "router": stress_router,
    "racy": stress_racy,
}

# the real-object suites --smoke runs (racy is the self-test, expected
# to FAIL — it proves the harness catches what it exists to catch)
REAL_SUITES = ("registry", "batcher", "reader", "breaker", "swap",
               "router")


def self_test(seeds: Sequence[int] = range(8)) -> Optional[StressReport]:
    """First seed whose schedule makes the racy fixture lose updates
    (None if no seed catches it — a harness regression)."""
    for s in seeds:
        rep = stress_racy(seed=s)
        if not rep.ok:
            return rep
    return None
