"""tpusvm.analysis.conc — the two-armed concurrency auditor.

Static arm (``python -m tpusvm.analysis conc``): an AST pass that builds
a per-class concurrency model — attributes assigned in ``__init__``,
lock/semaphore/condition/event/queue fields, ``with self._lock:``
guarded regions, methods reachable from ``threading.Thread`` targets —
and reports the lock-discipline rules JXC201-206 with the shared Finding
type, reporters and fingerprinted baseline
(``.tpusvm-conc-baseline.json``, committed EMPTY). Pure stdlib, no jax.

Dynamic arm (``python -m tpusvm.analysis conc-stress``): a deterministic
schedule-perturbation harness — seeded lock/queue/semaphore wrappers
inject yields and micro-sleeps at acquire/release/handoff points —
driven against the five real hot objects (obs MetricsRegistry, serve
MicroBatcher, stream ShardReader, faults CircuitBreaker) with their own
invariants asserted; any violation reports the reproducing seed.
"""

from tpusvm.analysis.conc.lint import (  # noqa: F401
    conc_lint_file,
    conc_lint_paths,
    conc_lint_source,
)
from tpusvm.analysis.conc.rules import (  # noqa: F401
    CONC_RULE_SUMMARIES,
    all_conc_rules,
)

__all__ = [
    "CONC_RULE_SUMMARIES",
    "all_conc_rules",
    "conc_lint_file",
    "conc_lint_paths",
    "conc_lint_source",
]
