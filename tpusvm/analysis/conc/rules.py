"""JXC201-206 — the lock-discipline rules over the per-class model.

The host-side threading layer (serve's micro-batcher + HTTP workers,
stream's prefetch producer, tune's fold pool, the circuit breaker, the
obs registry/tracer) is hand-rolled ``threading`` plumbing; these rules
machine-check the disciplines that code relies on, the way JX001-010
check tracing discipline and JXIR101-106 check the emitted IR:

  JXC201  shared mutable attribute written outside any lock in a
          thread-spawning class
  JXC202  lock-acquisition-order cycle across methods (potential
          deadlock)
  JXC203  blocking call while holding a lock (queue get/put, join,
          Semaphore.acquire, Event.wait, time.sleep, HTTP, device
          block_until_ready)
  JXC204  non-atomic check-then-act: read under a lock, decide, write
          under a REACQUIRED lock
  JXC205  thread created without daemon= and without join ownership
  JXC206  Event/Condition wait without a predicate re-check

Suppression: the shared ``# tpusvm: disable=JXC20x`` comments work, but
the idiomatic form is ``# tpusvm: guarded-by=<invariant>`` — it
suppresses the JXC finding on its line AND forces the author to name the
invariant that makes the code safe (single-writer confinement, one-way
latch, GIL-atomic store, ...). An empty invariant is not a suppression.

These rules live in their own registry (``all_conc_rules``) and run
under ``python -m tpusvm.analysis conc`` with their own baseline
(``.tpusvm-conc-baseline.json``) — the tracing linter's default sweep is
unchanged. Like the AST linter, this module is pure stdlib and imports
no JAX; the no-jax CI lint job lists and runs it.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from tpusvm.analysis.conc.model import (
    ConcModel,
    _self_attr,
    attr_reads,
    attr_writes,
)
from tpusvm.analysis.core import Finding, snippet_at
from tpusvm.analysis.registry import Rule

CONC_RULES: Dict[str, Rule] = {}


def conc_register(cls):
    inst = cls()
    if not inst.id:
        raise ValueError(f"conc rule {cls.__name__} has no id")
    if inst.id in CONC_RULES:
        raise ValueError(f"duplicate conc rule id {inst.id}")
    CONC_RULES[inst.id] = inst
    return cls


def all_conc_rules() -> Dict[str, Rule]:
    return dict(sorted(CONC_RULES.items()))


CONC_RULE_SUMMARIES = {
    "JXC201": ("shared mutable attribute written outside any lock in a "
               "thread-spawning class"),
    "JXC202": ("lock-acquisition-order cycle across methods — two code "
               "paths take the same locks in opposite orders (potential "
               "deadlock)"),
    "JXC203": ("blocking call (queue get/put, join, Semaphore.acquire, "
               "Event.wait, time.sleep, HTTP, block_until_ready) while "
               "holding a lock"),
    "JXC204": ("non-atomic check-then-act: state read under a lock, "
               "decision taken, then written under a REACQUIRED lock"),
    "JXC205": ("thread created without daemon= and without join "
               "ownership (leaks past interpreter exit / test teardown)"),
    "JXC206": ("Event/Condition wait without a predicate re-check "
               "(unchecked timed-wait result, or Condition.wait outside "
               "a while loop)"),
}


def _finding(rule_id: str, ctx, node: ast.AST, message: str) -> Finding:
    return Finding(
        rule=rule_id, path=ctx.path, line=node.lineno,
        col=node.col_offset + 1, message=message,
        snippet=snippet_at(ctx.lines, node.lineno),
    )


# --------------------------------------------------------------- JXC201
@conc_register
class UnguardedSharedWrite(Rule):
    id = "JXC201"
    summary = CONC_RULE_SUMMARIES["JXC201"]

    def check_model(self, model: ConcModel):
        ctx = model.ctx
        for cm in model.classes:
            if not cm.spawns_threads:
                continue
            for name, method in cm.methods.items():
                if name == "__init__":
                    # construction happens-before the spawned thread's
                    # first read (Thread.start is a fence)
                    continue
                for attr, node in attr_writes(method):
                    if attr not in cm.init_attrs:
                        continue
                    if cm.attr_kind(attr) is not None:
                        continue  # the primitive itself, not guarded state
                    if cm.locks_held.get(id(node)):
                        continue
                    side = ("worker" if name in cm.worker_methods
                            else "client")
                    yield _finding(
                        self.id, ctx, node,
                        f"shared attribute {attr!r} (initialised in "
                        f"__init__) is written without holding a lock in "
                        f"{cm.name}.{name} ({side}-side) while the class "
                        f"spawns threads (targets: "
                        f"{sorted(cm.thread_targets) or '?'}); guard the "
                        "write or annotate the invariant with "
                        "`# tpusvm: guarded-by=...`",
                    )


# --------------------------------------------------------------- JXC202
@conc_register
class LockOrderCycle(Rule):
    id = "JXC202"
    summary = CONC_RULE_SUMMARIES["JXC202"]

    def check_model(self, model: ConcModel):
        ctx = model.ctx
        for cm in model.classes:
            adj: Dict[str, Set[str]] = {}
            for e in cm.lock_edges:
                adj.setdefault(e.outer, set()).add(e.inner)

            def reaches(src: str, dst: str) -> bool:
                seen, stack = set(), [src]
                while stack:
                    cur = stack.pop()
                    if cur == dst:
                        return True
                    if cur in seen:
                        continue
                    seen.add(cur)
                    stack.extend(adj.get(cur, ()))
                return False

            reported = set()
            for e in cm.lock_edges:
                if (e.outer, e.inner) in reported:
                    continue
                if reaches(e.inner, e.outer):
                    reported.add((e.outer, e.inner))
                    yield _finding(
                        self.id, ctx, e.node,
                        f"{cm.name} acquires {e.inner!r} while holding "
                        f"{e.outer!r}, but another path acquires them in "
                        "the opposite order — two threads on the "
                        "opposing paths deadlock; pick one global "
                        "acquisition order",
                    )


# --------------------------------------------------------------- JXC203
_SLEEP_CALLS = {"time.sleep"}
_HTTP_CALLS = {
    "urllib.request.urlopen",
    "requests.get", "requests.post", "requests.put", "requests.request",
    "http.client.HTTPConnection", "socket.create_connection",
}


@conc_register
class BlockingUnderLock(Rule):
    id = "JXC203"
    summary = CONC_RULE_SUMMARIES["JXC203"]

    def _blocking_reason(self, cm, ctx, node: ast.Call,
                         held: frozenset) -> Optional[str]:
        resolved = ctx.resolve_call(node)
        if resolved in _SLEEP_CALLS:
            return "time.sleep blocks the holder"
        if resolved in _HTTP_CALLS:
            return f"{resolved} does network I/O"
        if not isinstance(node.func, ast.Attribute):
            return None
        meth = node.func.attr
        recv_attr = _self_attr(node.func.value)
        if meth == "block_until_ready":
            return "device sync (block_until_ready) stalls on the accelerator"
        if recv_attr is None:
            return None
        kind = cm.attr_kind(recv_attr)
        if meth in ("get", "put") and kind == "queue":
            # block=False is the non-blocking spelling of get/put
            if any(kw.arg == "block" and isinstance(kw.value, ast.Constant)
                   and kw.value.value is False for kw in node.keywords):
                return None
            return (f"queue.{meth} on self.{recv_attr} can block "
                    "indefinitely")
        if meth == "acquire" and kind in ("lock", "semaphore", "condition"):
            return (f"{kind}.acquire on self.{recv_attr} blocks while a "
                    "lock is held")
        if meth == "join" and kind == "thread":
            return f"joining self.{recv_attr} blocks on another thread"
        if meth == "wait" and kind == "event":
            return (f"Event.wait on self.{recv_attr} blocks; unlike "
                    "Condition.wait it does NOT release the held lock")
        if meth == "wait" and kind == "condition" and recv_attr not in held:
            # waiting on a DIFFERENT condition than the held lock keeps
            # the held lock across the sleep; cond.wait on the held
            # condition is the correct pattern (it releases)
            return (f"Condition.wait on self.{recv_attr} while holding a "
                    "different lock")
        return None

    def check_model(self, model: ConcModel):
        ctx = model.ctx
        for cm in model.classes:
            for method in cm.methods.values():
                for node in ast.walk(method):
                    if not isinstance(node, ast.Call):
                        continue
                    held = cm.locks_held.get(id(node)) or frozenset()
                    if not held:
                        continue
                    reason = self._blocking_reason(cm, ctx, node, held)
                    if reason:
                        yield _finding(
                            self.id, ctx, node,
                            f"blocking call while holding "
                            f"{sorted(held)}: {reason} — every other "
                            "thread contending for the lock stalls "
                            "behind it; move the blocking call outside "
                            "the guarded region",
                        )


# --------------------------------------------------------------- JXC204
@conc_register
class CheckThenActReacquire(Rule):
    id = "JXC204"
    summary = CONC_RULE_SUMMARIES["JXC204"]

    def check_model(self, model: ConcModel):
        ctx = model.ctx
        for cm in model.classes:
            for method in cm.methods.values():
                # with-blocks in source order, per lock field
                blocks: Dict[str, List[ast.With]] = {}
                for node in ast.walk(method):
                    if not isinstance(node, ast.With):
                        continue
                    for item in node.items:
                        attr = _self_attr(item.context_expr)
                        if attr is not None:
                            blocks.setdefault(attr, []).append(node)
                for lock, withs in blocks.items():
                    if len(withs) < 2:
                        continue
                    withs.sort(key=lambda w: w.lineno)
                    for i, early in enumerate(withs):
                        read = attr_reads(early)
                        read -= set(cm.sync_fields) | cm.queue_fields
                        if not read:
                            continue
                        for late in withs[i + 1:]:
                            writes = {a for a, _ in attr_writes(late)}
                            stale = read & writes
                            if not stale:
                                continue
                            if self._rechecks(late, stale):
                                continue
                            yield _finding(
                                self.id, ctx, late,
                                f"check-then-act across reacquisition of "
                                f"self.{lock}: {sorted(stale)} read under "
                                "the lock above, decided on, then "
                                "written here under a fresh acquisition "
                                "— the state may have changed in "
                                "between; re-check the predicate under "
                                "THIS lock or hold it across the "
                                "decision",
                            )

    @staticmethod
    def _rechecks(block: ast.With, attrs: Set[str]) -> bool:
        """A test inside the later block that re-reads the attr is the
        correct double-checked pattern — not a finding."""
        for node in ast.walk(block):
            if isinstance(node, (ast.If, ast.While)) and \
                    attr_reads(node.test) & attrs:
                return True
        return False


# --------------------------------------------------------------- JXC205
@conc_register
class UnownedThread(Rule):
    id = "JXC205"
    summary = CONC_RULE_SUMMARIES["JXC205"]

    def check_model(self, model: ConcModel):
        ctx = model.ctx
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and ctx.resolve_call(node) == "threading.Thread"):
                continue
            if any(kw.arg == "daemon" for kw in node.keywords):
                continue
            scope = model.enclosing_function(node) or ctx.tree
            if self._scope_joins(scope):
                continue
            yield _finding(
                self.id, ctx, node,
                "thread created without daemon= and never joined in its "
                "owning scope — it outlives interpreter shutdown intent "
                "and leaks past test teardown; pass daemon=True or own "
                "its lifetime with join()",
            )

    @staticmethod
    def _scope_joins(scope: ast.AST) -> bool:
        """Any `<name>.join(...)` in the scope counts as join ownership
        (str.join on literals does not)."""
        for node in ast.walk(scope):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "join" and \
                    not isinstance(node.func.value,
                                   (ast.Constant, ast.JoinedStr)):
                return True
        return False


# --------------------------------------------------------------- JXC206
@conc_register
class WaitWithoutRecheck(Rule):
    id = "JXC206"
    summary = CONC_RULE_SUMMARIES["JXC206"]

    def check_model(self, model: ConcModel):
        ctx = model.ctx
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "wait"):
                continue
            recv = node.func.value
            attr = recv.attr if isinstance(recv, ast.Attribute) else None
            if attr is None:
                continue
            kind = model.module_attr_kinds.get(attr)
            if kind == "condition":
                if not model.in_while_loop(node):
                    yield _finding(
                        self.id, ctx, node,
                        f"Condition.wait on {attr!r} outside a while "
                        "loop — wakeups are advisory (spurious wakeup / "
                        "stolen predicate); loop on the predicate: "
                        "`while not pred: cond.wait()`",
                    )
            elif kind == "event":
                if node.args and model.is_statement_expr(node):
                    yield _finding(
                        self.id, ctx, node,
                        f"timed Event.wait on {attr!r} with the result "
                        "discarded — on timeout the event is NOT set and "
                        "execution proceeds as if it were; branch on the "
                        "return value or re-check the predicate",
                    )
