"""CLIs for the concurrency auditor.

  python -m tpusvm.analysis conc [paths...]      the static arm (JXC201-
                                                 206; pure stdlib ast, no
                                                 jax — runs in the lint
                                                 job)
  python -m tpusvm.analysis conc-stress [...]    the dynamic arm (seeded
                                                 schedule-perturbation
                                                 suites over the real
                                                 hot objects)

Exit codes match the linter: 0 = clean (modulo baseline), 1 = findings /
violations, 2 = usage error.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from tpusvm.analysis.baseline import load_baseline, write_baseline
from tpusvm.analysis.core import _parse_rule_list

DEFAULT_CONC_BASELINE_NAME = ".tpusvm-conc-baseline.json"
DEFAULT_PATHS = ("tpusvm", "benchmarks", "scripts", "bench.py")


# ------------------------------------------------------------ static arm
def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tpusvm.analysis conc",
        description=("lock-discipline linter for the host-side threading "
                     "layer (rules JXC201-JXC206)"),
    )
    p.add_argument("paths", nargs="*", default=list(DEFAULT_PATHS),
                   help="files/directories to lint "
                        f"(default: {' '.join(DEFAULT_PATHS)})")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--select", default="",
                   help="comma-separated JXC rule ids to run")
    p.add_argument("--ignore", default="",
                   help="comma-separated JXC rule ids to skip")
    p.add_argument("--baseline", default=DEFAULT_CONC_BASELINE_NAME,
                   help="baseline file of grandfathered findings "
                        f"(default: {DEFAULT_CONC_BASELINE_NAME}; "
                        "missing file = empty baseline)")
    p.add_argument("--no-baseline", action="store_true")
    p.add_argument("--write-baseline", action="store_true",
                   help="write all current findings to the baseline "
                        "file and exit 0")
    p.add_argument("--list-rules", action="store_true")
    return p


def main(argv=None) -> int:
    from tpusvm.analysis.conc.lint import conc_lint_paths
    from tpusvm.analysis.conc.rules import all_conc_rules

    args = build_parser().parse_args(argv)

    if args.list_rules:
        for rid, rule in all_conc_rules().items():
            print(f"{rid}  {rule.summary}")
        return 0

    select = _parse_rule_list(args.select) or None
    ignore = _parse_rule_list(args.ignore) or None
    baseline = None
    if not args.no_baseline and not args.write_baseline:
        try:
            baseline = load_baseline(args.baseline) or None
        except ValueError as e:
            print(f"tpusvm-conc: {e}", file=sys.stderr)
            return 2

    missing = [p for p in args.paths if not Path(p).exists()]
    if missing:
        print(f"tpusvm-conc: no such path(s): {', '.join(missing)}",
              file=sys.stderr)
        return 2

    try:
        result = conc_lint_paths(args.paths, select=select, ignore=ignore,
                                 baseline=baseline)
    except ValueError as e:
        print(f"tpusvm-conc: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        write_baseline(args.baseline, result.findings)
        print(f"tpusvm-conc: wrote {len(result.findings)} finding(s) to "
              f"{args.baseline}")
        return 0

    if args.format == "json":
        import json
        from collections import Counter

        counts = Counter(f.rule for f in result.findings)
        print(json.dumps({
            "version": 1,
            "tool": "tpusvm.analysis.conc",
            "files_scanned": result.files_scanned,
            "rules": {rid: r.summary
                      for rid, r in all_conc_rules().items()},
            "findings": [f.to_dict() for f in result.findings],
            "counts": dict(sorted(counts.items())),
            "suppressed": len(result.suppressed),
            "baselined": len(result.baselined),
        }, indent=2))
    else:
        from tpusvm.analysis.report import render_text

        print(render_text(result))
    return result.exit_code


# ----------------------------------------------------------- dynamic arm
def build_stress_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tpusvm.analysis conc-stress",
        description=("seeded schedule-perturbation race harness over the "
                     "repo's real threaded objects (registry / batcher / "
                     "reader / breaker)"),
    )
    p.add_argument("--seed", type=int, default=0,
                   help="schedule seed; a violation report names the "
                        "seed that reproduces it (default 0)")
    p.add_argument("--suite", action="append", default=[],
                   help="suite to run (repeatable; default: the five "
                        "real-object suites)")
    p.add_argument("--list-suites", action="store_true")
    p.add_argument("--self-test", action="store_true",
                   help="assert the harness CATCHES the deliberately "
                        "racy fixture (exit 1 if no seed in 0..7 "
                        "triggers it)")
    p.add_argument("--smoke", action="store_true",
                   help="CI gate: all five real-object suites clean at "
                        "the fixed seed AND the self-test catches the "
                        "racy fixture")
    return p


def stress_main(argv=None) -> int:
    args = build_stress_parser().parse_args(argv)
    from tpusvm.analysis.conc.stress import (
        REAL_SUITES,
        SUITES,
        self_test,
    )

    if args.list_suites:
        for name, fn in SUITES.items():
            doc = (fn.__doc__ or "").strip().splitlines()[0]
            print(f"{name}  {doc}")
        return 0

    suites = args.suite or list(REAL_SUITES)
    unknown = [s for s in suites if s not in SUITES]
    if unknown:
        print(f"tpusvm-conc-stress: unknown suite(s) {unknown}; known: "
              f"{sorted(SUITES)}", file=sys.stderr)
        return 2

    failed = False
    for name in suites:
        rep = SUITES[name](seed=args.seed)
        print(rep.render())
        if not rep.ok and name != "racy":
            failed = True
        if name == "racy" and not rep.ok:
            # the known-bad fixture violating is the EXPECTED outcome;
            # surfacing it is informational, not a failure
            print("  (racy is the known-bad fixture: a violation here "
                  "means the harness works)")

    if args.self_test or args.smoke:
        caught = self_test()
        if caught is None:
            print("tpusvm-conc-stress: SELF-TEST FAILED — no seed in "
                  "0..7 makes the racy fixture lose updates; the "
                  "perturber is not amplifying races", file=sys.stderr)
            failed = True
        else:
            print(f"self-test ok: racy fixture caught at seed="
                  f"{caught.seed} ({caught.violations[0]})")

    return 1 if failed else 0
