"""Per-class concurrency model extracted from one module's AST.

The JXC rules reason about a small, explicit vocabulary that this module
computes once per file (reusing the alias resolution of the tracing
linter's ModuleContext so `import threading as T` and
`from threading import Lock` both resolve):

  * which attributes a class initialises in ``__init__`` (its shared
    state — anything a spawned thread can reach through ``self``);
  * which of those attributes are synchronisation primitives
    (Lock/RLock/Semaphore/Condition/Event), queues, or Thread objects;
  * which source regions hold which locks (``with self._lock:`` blocks,
    including nesting — the input to the lock-order graph);
  * which methods run on a spawned thread (``threading.Thread(
    target=self.x)`` targets, closed over the ``self.y()`` call graph,
    so a helper called only from the worker is worker-side too).

The model is a lexical approximation in the same spirit as the tracing
taint model: it does not follow values across classes or modules, and a
lock acquired via explicit ``.acquire()``/``.release()`` pairs (rather
than ``with``) is not credited as a guard — both documented limits that
keep the false-positive rate workable on this repo.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

from tpusvm.analysis.context import ModuleContext

# factory call -> primitive kind; resolved through the module's aliases
SYNC_FACTORIES = {
    "threading.Lock": "lock",
    "threading.RLock": "lock",
    "threading.Semaphore": "semaphore",
    "threading.BoundedSemaphore": "semaphore",
    "threading.Condition": "condition",
    "threading.Event": "event",
}

QUEUE_FACTORIES = {
    "queue.Queue",
    "queue.LifoQueue",
    "queue.PriorityQueue",
    "queue.SimpleQueue",
}

THREAD_FACTORY = "threading.Thread"


def _self_attr(node: ast.AST) -> Optional[str]:
    """'x' when node is `self.x`, else None."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


@dataclasses.dataclass
class LockEdge:
    """`with self.outer:` lexically encloses `with self.inner:`."""

    outer: str
    inner: str
    node: ast.With


@dataclasses.dataclass
class ClassConcModel:
    """Everything the JXC rules need to know about one class."""

    node: ast.ClassDef
    name: str
    init_attrs: Dict[str, int]          # attr -> lineno first set in __init__
    sync_fields: Dict[str, str]         # attr -> lock|semaphore|condition|event
    queue_fields: Set[str]
    thread_fields: Set[str]             # attrs assigned from threading.Thread
    thread_targets: Set[str]            # method names passed as Thread target=
    spawns_threads: bool
    methods: Dict[str, ast.FunctionDef]
    # id(ast node) -> frozenset of lock-field names held at that node
    locks_held: Dict[int, frozenset]
    lock_edges: List[LockEdge]
    worker_methods: Set[str]            # thread targets + their self-call closure

    def attr_kind(self, attr: str) -> Optional[str]:
        if attr in self.sync_fields:
            return self.sync_fields[attr]
        if attr in self.queue_fields:
            return "queue"
        if attr in self.thread_fields:
            return "thread"
        return None


class ConcModel:
    """Module-level concurrency model: one ClassConcModel per class, plus
    the module-wide attr-name -> primitive-kind map that lets rules type
    `req.event.wait(...)` when `event` is an Event field of ANOTHER class
    in the same file (the batcher's per-request events are the motivating
    case)."""

    def __init__(self, ctx: ModuleContext):
        self.ctx = ctx
        self.parents: Dict[int, ast.AST] = {}
        for parent in ast.walk(ctx.tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[id(child)] = parent
        self.classes: List[ClassConcModel] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                self.classes.append(self._build_class(node))
        # attr name -> kind, across every class in the module (collisions
        # keep the first kind seen; names are overwhelmingly consistent)
        self.module_attr_kinds: Dict[str, str] = {}
        for cm in self.classes:
            for attr, kind in cm.sync_fields.items():
                self.module_attr_kinds.setdefault(attr, kind)

    # ------------------------------------------------------------- helpers
    def parent_chain(self, node: ast.AST):
        cur = self.parents.get(id(node))
        while cur is not None:
            yield cur
            cur = self.parents.get(id(cur))

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        for p in self.parent_chain(node):
            if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                return p
        return None

    def is_statement_expr(self, call: ast.Call) -> bool:
        """True when the call's value is discarded (a bare Expr stmt)."""
        parent = self.parents.get(id(call))
        return isinstance(parent, ast.Expr)

    def in_while_loop(self, node: ast.AST) -> bool:
        fn = self.enclosing_function(node)
        for p in self.parent_chain(node):
            if p is fn:
                return False
            if isinstance(p, (ast.While, ast.For)):
                return True
        return False

    # -------------------------------------------------------- class model
    def _build_class(self, cls: ast.ClassDef) -> ClassConcModel:
        ctx = self.ctx
        methods: Dict[str, ast.FunctionDef] = {}
        for stmt in cls.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                methods[stmt.name] = stmt

        init_attrs: Dict[str, int] = {}
        sync_fields: Dict[str, str] = {}
        queue_fields: Set[str] = set()
        thread_fields: Set[str] = set()
        init = methods.get("__init__")
        if init is not None:
            for node in ast.walk(init):
                if isinstance(node, ast.Assign):
                    targets = node.targets
                elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
                    targets = [node.target]
                else:
                    continue
                for t in targets:
                    attr = _self_attr(t)
                    if attr is None:
                        continue
                    init_attrs.setdefault(attr, node.lineno)
                    if isinstance(node, ast.Assign) or \
                            (isinstance(node, ast.AnnAssign) and node.value):
                        value = node.value
                        resolved = (ctx.resolve_call(value)
                                    if isinstance(value, ast.Call) else None)
                        if resolved in SYNC_FACTORIES:
                            sync_fields[attr] = SYNC_FACTORIES[resolved]
                        elif resolved in QUEUE_FACTORIES:
                            queue_fields.add(attr)
                        elif resolved == THREAD_FACTORY:
                            thread_fields.add(attr)

        spawns = False
        thread_targets: Set[str] = set()
        for node in ast.walk(cls):
            if isinstance(node, ast.Call) and \
                    ctx.resolve_call(node) == THREAD_FACTORY:
                spawns = True
                for kw in node.keywords:
                    if kw.arg == "target":
                        target = _self_attr(kw.value)
                        if target is not None:
                            thread_targets.add(target)

        locks_held: Dict[int, frozenset] = {}
        lock_edges: List[LockEdge] = []
        for m in methods.values():
            self._walk_guards(m, frozenset(), locks_held, lock_edges)

        # worker closure: thread targets + every method reachable from one
        # through self.<method>() calls
        worker = set(thread_targets)
        frontier = list(worker)
        while frontier:
            name = frontier.pop()
            m = methods.get(name)
            if m is None:
                continue
            for node in ast.walk(m):
                if isinstance(node, ast.Call):
                    callee = _self_attr(node.func)
                    if callee in methods and callee not in worker:
                        worker.add(callee)
                        frontier.append(callee)

        return ClassConcModel(
            node=cls, name=cls.name, init_attrs=init_attrs,
            sync_fields=sync_fields, queue_fields=queue_fields,
            thread_fields=thread_fields, thread_targets=thread_targets,
            spawns_threads=spawns, methods=methods,
            locks_held=locks_held, lock_edges=lock_edges,
            worker_methods=worker,
        )

    def _walk_guards(self, node: ast.AST, held: frozenset,
                     locks_held: Dict[int, frozenset],
                     edges: List[LockEdge]) -> None:
        """Record the set of `with self.X:`-held locks at every node."""
        locks_held[id(node)] = held
        children_held = held
        if isinstance(node, ast.With):
            acquired = []
            for item in node.items:
                attr = _self_attr(item.context_expr)
                if attr is not None:
                    acquired.append(attr)
            if acquired:
                for outer in held:
                    for inner in acquired:
                        if inner != outer:
                            edges.append(LockEdge(outer, inner, node))
                children_held = held | frozenset(acquired)
        for child in ast.iter_child_nodes(node):
            # nested defs start lock-free: a closure runs when called,
            # not where it is defined
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                self._walk_guards(child, frozenset(), locks_held, edges)
            else:
                self._walk_guards(child, children_held, locks_held, edges)


def attr_writes(fn: ast.AST) -> List[Tuple[str, ast.AST]]:
    """(attr, assignment-node) for every `self.attr = ...` /
    `self.attr op= ...` in `fn` (nested defs included — they still touch
    the same object)."""
    out: List[Tuple[str, ast.AST]] = []
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        else:
            continue
        for t in targets:
            attr = _self_attr(t)
            if attr is not None:
                out.append((attr, node))
    return out


def attr_reads(root: ast.AST) -> Set[str]:
    """Attr names of every `self.attr` LOAD under `root`."""
    out: Set[str] = set()
    for node in ast.walk(root):
        attr = _self_attr(node)
        if attr is not None and isinstance(node.ctx, ast.Load):
            out.add(attr)
    return out
