"""Checked-in baseline: grandfathered findings that do not fail the gate.

The baseline file is JSON; entries match findings by
(rule, path, fingerprint) — fingerprints hash the rule + path + source
snippet (NOT the line number), so unrelated edits above a grandfathered
finding do not invalidate it, while editing the flagged line itself
does. Regenerate with `python -m tpusvm.analysis ... --write-baseline`.

An empty or missing baseline means the tree must lint fully clean — the
state this repo ships in.
"""

from __future__ import annotations

import json
import os
from pathlib import Path
from typing import List, Set, Tuple

from tpusvm.analysis.core import Finding

BASELINE_VERSION = 1
DEFAULT_BASELINE_NAME = ".tpusvm-lint-baseline.json"

Key = Tuple[str, str, str]  # (rule, path, fingerprint)


def load_baseline(path) -> Set[Key]:
    p = Path(path)
    if not p.exists():
        return set()
    doc = json.loads(p.read_text(encoding="utf-8"))
    if doc.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"unsupported baseline version {doc.get('version')!r} in "
            f"{path} (expected {BASELINE_VERSION})"
        )
    return {(e["rule"], e["path"], e["fingerprint"])
            for e in doc.get("findings", [])}


def write_baseline(path, findings: List[Finding]) -> None:
    doc = {
        "version": BASELINE_VERSION,
        "tool": "tpusvm.analysis",
        "findings": [
            {"rule": f.rule, "path": f.path, "fingerprint": f.fingerprint,
             # line + snippet are informational for the human reviewer;
             # matching uses only (rule, path, fingerprint)
             "line": f.line, "snippet": f.snippet}
            for f in sorted(findings,
                            key=lambda f: (f.path, f.line, f.rule))
        ],
    }
    tmp = Path(str(path) + ".tmp")
    tmp.write_text(json.dumps(doc, indent=2) + "\n", encoding="utf-8")
    os.replace(tmp, path)
