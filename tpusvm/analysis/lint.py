"""Lint driver: run the registered rules over sources, files, or trees.

Public API:

  lint_source(source, path)  -> (findings, suppressed)   one string
  lint_file(path)            -> (findings, suppressed)   one file
  lint_paths(paths)          -> LintResult               files + dirs

Suppression comments (`# tpusvm: disable=JX00x`) are honoured here — a
rule never needs to know about them. Parse failures surface as a single
JX000 finding so a syntactically-broken file fails the gate instead of
silently passing.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Set, Tuple

from tpusvm.analysis.core import (
    Finding,
    file_suppressions,
    fingerprint_findings,
    is_suppressed,
    iter_python_files,
)
from tpusvm.analysis.registry import select_rules


@dataclasses.dataclass
class LintResult:
    findings: List[Finding]
    suppressed: List[Finding]
    baselined: List[Finding]
    files_scanned: int

    @property
    def exit_code(self) -> int:
        return 1 if self.findings else 0


def lint_source(source: str, path: str = "<string>",
                select: Optional[Set[str]] = None,
                ignore: Optional[Set[str]] = None,
                ) -> Tuple[List[Finding], List[Finding]]:
    """Lint one source string; returns (active, suppressed) findings."""
    from tpusvm.analysis.context import ModuleContext

    rules = select_rules(select, ignore)
    try:
        ctx = ModuleContext(path, source)
    except SyntaxError as e:
        return fingerprint_findings([Finding(
            rule="JX000", path=path, line=e.lineno or 1,
            col=(e.offset or 0) + 1,
            message=f"file does not parse: {e.msg}",
        )]), []
    raw: List[Finding] = []
    for rule in rules:
        raw.extend(rule.check(ctx))
    raw.sort(key=lambda f: (f.line, f.col, f.rule))
    raw = fingerprint_findings(raw)
    file_rules = file_suppressions(ctx.lines)
    active, suppressed = [], []
    for f in raw:
        (suppressed if is_suppressed(f, ctx.lines, file_rules)
         else active).append(f)
    return active, suppressed


def lint_file(path, select=None, ignore=None):
    with open(path, encoding="utf-8") as fh:
        source = fh.read()
    return lint_source(source, str(path), select, ignore)


def lint_paths(paths, select=None, ignore=None,
               baseline: Optional[Set[Tuple[str, str, str]]] = None,
               ) -> LintResult:
    """Lint every .py file under `paths`.

    `baseline` is a set of (rule, path, fingerprint) triples (see
    tpusvm.analysis.baseline); matching findings are reported separately
    and do not fail the gate.
    """
    findings: List[Finding] = []
    suppressed: List[Finding] = []
    baselined: List[Finding] = []
    files = iter_python_files(paths)
    for f in files:
        active, supp = lint_file(f, select, ignore)
        suppressed.extend(supp)
        for finding in active:
            key = (finding.rule, finding.path, finding.fingerprint)
            if baseline and key in baseline:
                baselined.append(finding)
            else:
                findings.append(finding)
    return LintResult(findings=findings, suppressed=suppressed,
                      baselined=baselined, files_scanned=len(files))
