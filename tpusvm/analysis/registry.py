"""Rule registry: every JX rule registers itself at import time.

A rule is a stateless object with an `id` (JXnnn), a one-line `summary`,
and `check(ctx) -> Iterable[Finding]` over one ModuleContext. Rules live
in tpusvm/analysis/rules/ (one module per rule); importing
tpusvm.analysis.rules populates the registry.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Set

RULES: Dict[str, "Rule"] = {}


class Rule:
    id: str = ""
    summary: str = ""

    def check(self, ctx) -> Iterable:
        raise NotImplementedError


def register(cls):
    """Class decorator: instantiate and register a rule by its id."""
    inst = cls()
    if not inst.id:
        raise ValueError(f"rule {cls.__name__} has no id")
    if inst.id in RULES:
        raise ValueError(f"duplicate rule id {inst.id}")
    RULES[inst.id] = inst
    return cls


def all_rules() -> Dict[str, Rule]:
    # importing the rules package has the side effect of registering
    # every rule; deferred so `import tpusvm.analysis.registry` alone
    # stays cheap and cycle-free
    import tpusvm.analysis.rules  # noqa: F401

    return dict(sorted(RULES.items()))


def select_rules(select: Optional[Set[str]] = None,
                 ignore: Optional[Set[str]] = None) -> List[Rule]:
    rules = all_rules()
    unknown = (set(select or ()) | set(ignore or ())) - set(rules)
    if unknown:
        raise ValueError(f"unknown rule id(s): {sorted(unknown)}; "
                         f"known: {sorted(rules)}")
    picked = [r for rid, r in rules.items()
              if (not select or rid in select)
              and (not ignore or rid not in ignore)]
    return picked
