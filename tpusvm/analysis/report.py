"""Text and JSON reporters for lint results.

The JSON document is a stable machine-readable schema (version 1, tested
by tests/test_analysis.py::test_json_report_schema):

  {
    "version": 1,
    "tool": "tpusvm.analysis",
    "files_scanned": <int>,
    "rules": {"JX001": "<summary>", ...},
    "findings": [{"rule", "path", "line", "col", "message",
                  "snippet", "fingerprint"}, ...],
    "counts": {"JX001": <int>, ...},         # active findings per rule
    "suppressed": <int>,
    "baselined": <int>
  }
"""

from __future__ import annotations

import json
from collections import Counter

from tpusvm.analysis.lint import LintResult
from tpusvm.analysis.registry import all_rules

JSON_SCHEMA_VERSION = 1


def render_text(result: LintResult) -> str:
    lines = [f.render() for f in result.findings]
    counts = Counter(f.rule for f in result.findings)
    tail = (", ".join(f"{r}: {n}" for r, n in sorted(counts.items()))
            or "clean")
    extras = []
    if result.suppressed:
        extras.append(f"{len(result.suppressed)} suppressed inline")
    if result.baselined:
        extras.append(f"{len(result.baselined)} in baseline")
    extra = f" ({'; '.join(extras)})" if extras else ""
    lines.append(
        f"tpusvm-lint: {len(result.findings)} finding(s) in "
        f"{result.files_scanned} file(s) — {tail}{extra}"
    )
    return "\n".join(lines)


def render_json(result: LintResult) -> str:
    counts = Counter(f.rule for f in result.findings)
    doc = {
        "version": JSON_SCHEMA_VERSION,
        "tool": "tpusvm.analysis",
        "files_scanned": result.files_scanned,
        "rules": {rid: rule.summary
                  for rid, rule in all_rules().items()},
        "findings": [f.to_dict() for f in result.findings],
        "counts": dict(sorted(counts.items())),
        "suppressed": len(result.suppressed),
        "baselined": len(result.baselined),
    }
    return json.dumps(doc, indent=2, sort_keys=False)
