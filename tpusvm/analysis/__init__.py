"""tpusvm.analysis — JAX tracing-safety & TPU-hazard linter.

An AST-based static analyzer purpose-built for this codebase's failure
classes: silent recompilation, host-device sync, dtype drift, and solver
flags the config resolver would ignore. Run it with

    python -m tpusvm.analysis tpusvm/ benchmarks/

Rules (see README "Static analysis" for the full contract):

  JX001  Python if/while on a traced value inside jit/scan bodies
  JX002  implicit host-device sync (.item(), float(), np.asarray,
         .block_until_ready() in hot loops)
  JX003  data-dependent shapes under jit (boolean-mask indexing,
         one-arg jnp.where, nonzero/unique without size=)
  JX004  dtype drift (constructors without dtype=, bare float literals
         on kernel paths)
  JX005  jitted functions closing over module-level ndarrays
  JX006  mutated module-global config read inside a traced function
  JX007  leftover jax.debug.print/breakpoint() on kernel paths
  JX008  pallas_* flag combinations the resolved solver config ignores
         (driven by tpusvm.config.PALLAS_FLAG_RULES)
  JX009  host callbacks / tracer materialisation inside lax loop bodies
  JX010  raw @ / jnp.dot / jnp.einsum / lax.dot_general outside
         tpusvm/ops and tpusvm/kernels (contraction precision never
         resolved)

The package imports no JAX: it is stdlib `ast` over source text, so the
CI lint gate runs without accelerator dependencies.

`python -m tpusvm.analysis ir-audit` runs the jaxpr-level semantic
auditor (tpusvm.analysis.ir, rules JXIR101-106): it traces the repo's
real jit entry points and machine-checks precision routing, dtype
provenance, loop-carry stability, TPU tile alignment, loop-body host
callbacks, and weak-scalar recompile hazards at the IR the compiler
actually solves. That subcommand DOES need jax; everything else here
stays accelerator-free.

`python -m tpusvm.analysis conc` runs the lock-discipline linter
(tpusvm.analysis.conc, rules JXC201-206) over the host-side threading
layer — unguarded shared writes, lock-order cycles, blocking calls
under locks, check-then-act reacquisition, unowned threads, unchecked
waits — with its own empty-committed baseline; `conc-stress` is its
dynamic arm, a seeded schedule-perturbation race harness over the real
threaded objects (needs numpy/jax; any violation reports the
reproducing seed).
"""

from tpusvm.analysis.core import Finding  # noqa: F401
from tpusvm.analysis.lint import (  # noqa: F401
    LintResult,
    lint_file,
    lint_paths,
    lint_source,
)
from tpusvm.analysis.registry import all_rules  # noqa: F401

__all__ = [
    "Finding",
    "LintResult",
    "all_rules",
    "lint_file",
    "lint_paths",
    "lint_source",
]
