"""CLIs for the durability auditor.

  python -m tpusvm.analysis dura [paths...]       the static arm
                                                  (JXD301-306; pure
                                                  stdlib ast, no jax —
                                                  runs in the lint job)
  python -m tpusvm.analysis dura-matrix [...]     the dynamic arm: kill
                                                  windows derived from
                                                  the static model, run
                                                  through the recovery
                                                  scenarios (needs
                                                  numpy/jax — test job)

Exit codes match the linter: 0 = clean (modulo baseline), 1 = findings /
lost artifacts, 2 = usage error.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from tpusvm.analysis.baseline import load_baseline, write_baseline
from tpusvm.analysis.core import _parse_rule_list

DEFAULT_DURA_BASELINE_NAME = ".tpusvm-dura-baseline.json"
DEFAULT_PATHS = ("tpusvm", "benchmarks", "scripts", "bench.py")


# ------------------------------------------------------------ static arm
def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tpusvm.analysis dura",
        description=("crash-safety & atomicity auditor for the durable-"
                     "state write protocols (rules JXD301-JXD306)"),
    )
    p.add_argument("paths", nargs="*", default=list(DEFAULT_PATHS),
                   help="files/directories to lint "
                        f"(default: {' '.join(DEFAULT_PATHS)})")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--select", default="",
                   help="comma-separated JXD rule ids to run")
    p.add_argument("--ignore", default="",
                   help="comma-separated JXD rule ids to skip")
    p.add_argument("--baseline", default=DEFAULT_DURA_BASELINE_NAME,
                   help="baseline file of grandfathered findings "
                        f"(default: {DEFAULT_DURA_BASELINE_NAME}; "
                        "missing file = empty baseline)")
    p.add_argument("--no-baseline", action="store_true")
    p.add_argument("--write-baseline", action="store_true",
                   help="write all current findings to the baseline "
                        "file and exit 0")
    p.add_argument("--list-rules", action="store_true")
    return p


def main(argv=None) -> int:
    from tpusvm.analysis.dura.lint import dura_lint_paths
    from tpusvm.analysis.dura.rules import all_dura_rules

    args = build_parser().parse_args(argv)

    if args.list_rules:
        for rid, rule in all_dura_rules().items():
            print(f"{rid}  {rule.summary}")
        return 0

    select = _parse_rule_list(args.select) or None
    ignore = _parse_rule_list(args.ignore) or None
    baseline = None
    if not args.no_baseline and not args.write_baseline:
        try:
            baseline = load_baseline(args.baseline) or None
        except ValueError as e:
            print(f"tpusvm-dura: {e}", file=sys.stderr)
            return 2

    missing = [p for p in args.paths if not Path(p).exists()]
    if missing:
        print(f"tpusvm-dura: no such path(s): {', '.join(missing)}",
              file=sys.stderr)
        return 2

    try:
        result = dura_lint_paths(args.paths, select=select, ignore=ignore,
                                 baseline=baseline)
    except ValueError as e:
        print(f"tpusvm-dura: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        write_baseline(args.baseline, result.findings)
        print(f"tpusvm-dura: wrote {len(result.findings)} finding(s) to "
              f"{args.baseline}")
        return 0

    if args.format == "json":
        import json
        from collections import Counter

        counts = Counter(f.rule for f in result.findings)
        print(json.dumps({
            "version": 1,
            "tool": "tpusvm.analysis.dura",
            "files_scanned": result.files_scanned,
            "rules": {rid: r.summary
                      for rid, r in all_dura_rules().items()},
            "findings": [f.to_dict() for f in result.findings],
            "counts": dict(sorted(counts.items())),
            "suppressed": len(result.suppressed),
            "baselined": len(result.baselined),
        }, indent=2))
    else:
        from tpusvm.analysis.report import render_text

        print(render_text(result))
    return result.exit_code


# ----------------------------------------------------------- dynamic arm
def build_matrix_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tpusvm.analysis dura-matrix",
        description=("derived crash-window matrix: every write-guarding "
                     "fault point from the static model, killed at every "
                     "hit a control run takes, with the recovery "
                     "contract asserted after each kill"),
    )
    p.add_argument("--seed", type=int, default=0,
                   help="scenario data seed; the generated plan names "
                        "it, so any window reproduces (default 0)")
    p.add_argument("--scenario", action="append", default=[],
                   help="scenario to run (repeatable; default: all)")
    p.add_argument("--list-scenarios", action="store_true")
    p.add_argument("--list-windows", action="store_true",
                   help="derive and print the kill-window plan without "
                        "running the chaos arm")
    p.add_argument("--max-windows", type=int, default=None,
                   help="cap kill windows per (scenario, point) "
                        "(default: unlimited; --smoke uses 2)")
    p.add_argument("--out", default=None,
                   help="write the generated plan document (JSON) here")
    p.add_argument("--smoke", action="store_true",
                   help="CI gate: every scenario, windows capped at 2 "
                        "per point, zero lost/torn artifacts required")
    return p


def matrix_main(argv=None) -> int:
    args = build_matrix_parser().parse_args(argv)
    from tpusvm.analysis.dura.matrix import (
        SCENARIOS,
        derive_plan,
        render_plan,
        run_matrix,
    )

    if args.list_scenarios:
        for name, sc in SCENARIOS.items():
            print(f"{name}  points={','.join(sorted(sc.points))}  "
                  f"{sc.doc}")
        return 0

    names = args.scenario or None
    unknown = [s for s in (names or []) if s not in SCENARIOS]
    if unknown:
        print(f"tpusvm-dura-matrix: unknown scenario(s) {unknown}; "
              f"known: {sorted(SCENARIOS)}", file=sys.stderr)
        return 2

    max_windows = args.max_windows
    if args.smoke and max_windows is None:
        max_windows = 2

    try:
        plan = derive_plan(seed=args.seed, scenarios=names,
                           max_windows=max_windows)
    except RuntimeError as e:
        print(f"tpusvm-dura-matrix: {e}", file=sys.stderr)
        return 1

    if args.out:
        import json
        import os

        from tpusvm.utils.durable import fsync_replace

        tmp = args.out + ".tmp"
        with open(tmp, "w") as f:
            f.write(render_plan(plan))
        fsync_replace(tmp, args.out)
        print(f"tpusvm-dura-matrix: wrote plan to {args.out}")

    if args.list_windows:
        print(render_plan(plan))
        return 0

    report = run_matrix(plan)
    print(report.render())
    return 0 if report.ok else 1
