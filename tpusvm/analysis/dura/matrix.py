"""The derived crash-window matrix (``python -m tpusvm.analysis dura-matrix``).

The chaos tests that existed before this PR each hand-picked their kill
points, so a new durable write path silently shipped with zero kill
coverage until someone remembered to write a smoke for it. Here the
windows are MACHINE-DERIVED from the same static model the JXD rules
query:

  1. ``derive_points()`` re-runs DuraModel over every registered durable
     module and keeps each ``faults.point`` literal whose enclosing
     scope also performs a durable write or rename-commit — the
     *write-guarding* points. Read-side points (``cache.read``,
     ``stream.read_shard``) fall out automatically.
  2. Every derived point must be claimed by some recovery scenario
     below; an unclaimed point is a hard error (``RuntimeError``), so
     chaos coverage can never lag the code — adding a guarded write
     path without teaching the matrix about it fails CI.
  3. For each scenario a CONTROL run executes under an ACTIVE but
     empty ``FaultPlan`` (rules=[]), which counts every point hit
     without injecting anything. Each (point, hit-ordinal) pair becomes
     one kill window: a generated ``FaultRule(kind="kill", at_hit=k)``.
  4. ``run_matrix`` replays each window — run until ``SimulatedKill``,
     then recover exactly as a restarted process would
     (``execute(resume=True)``) — and asserts the recovered artifact
     digest equals the control digest: zero lost or torn artifacts.

Everything is parameterised by one seed; ``render_plan`` is
byte-identical for a given seed, and any single window reproduces with
``--scenario <name>`` plus the window's (point, at_hit) from the plan.

This module needs numpy/jax at execute time (the recovery scenarios
train and serialize for real) — it is the test-job arm; the lint job
only ever imports the static arm.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import tempfile
from pathlib import Path
from typing import Callable, Dict, FrozenSet, List, Optional

from tpusvm.analysis.dura.model import DURABLE_MODULES, DuraModel


class MatrixError(AssertionError):
    """A recovery contract was violated inside a scenario execute()."""


# --------------------------------------------------------------- digests
def _digest(obj) -> str:
    """sha256 over a canonical JSON rendering (dicts sorted)."""
    return hashlib.sha256(
        json.dumps(obj, sort_keys=True).encode()
    ).hexdigest()


def _arr(a) -> str:
    import numpy as np

    a = np.asarray(a)
    h = hashlib.sha256()
    h.update(str(a.dtype).encode())
    h.update(str(a.shape).encode())
    h.update(np.ascontiguousarray(a).tobytes())
    return h.hexdigest()


# -------------------------------------------------- derived point universe
def derive_points(root: Optional[Path] = None) -> Dict[str, List[str]]:
    """Write-guarding fault points, derived from the static model.

    Maps point name -> list of "module.py:line" sites. A point literal
    counts when its innermost enclosing scope (function, else module)
    also holds a durable write or a rename-commit — the static
    definition of "this point guards a write protocol"."""
    from tpusvm.analysis.context import ModuleContext

    if root is None:
        root = Path(__file__).resolve().parents[3]
    out: Dict[str, List[str]] = {}
    for suffix in sorted(DURABLE_MODULES):
        path = Path(root) / suffix
        try:
            source = path.read_text(encoding="utf-8")
            ctx = ModuleContext(str(path), source)
        except (OSError, SyntaxError):
            continue
        model = DuraModel(ctx)
        scope_by_id = {id(s.node): s for s in model.scopes}
        for call, lit in model.point_calls:
            if lit is None:
                continue
            chain = model.enclosing_functions(call)
            owner = chain[0] if chain else model.ctx.tree
            scope = scope_by_id.get(id(owner))
            if scope is None or not (scope.writes or scope.replaces):
                continue
            out.setdefault(lit, []).append(f"{suffix}:{call.lineno}")
    return out


# ------------------------------------------------------------- scenarios
@dataclasses.dataclass(frozen=True)
class Scenario:
    """One recovery contract: points it claims + an execute that either
    completes and returns a state digest, or dies at an injected kill
    and is re-run with resume=True the way a restarted process would."""

    name: str
    points: FrozenSet[str]
    doc: str
    execute: Callable[[str, int, bool], str]


def _ingest_exec(workdir: str, seed: int, resume: bool) -> str:
    import numpy as np

    from tpusvm.status import StreamStatus
    from tpusvm.stream.format import ingest_arrays, open_dataset

    rng = np.random.default_rng(1000 + seed)
    X = rng.normal(size=(120, 6)).astype(np.float64)
    Y = np.where(rng.random(120) < 0.5, 1, -1).astype(np.int64)
    ds = os.path.join(workdir, "ds")
    ingest_arrays(ds, X, Y, rows_per_shard=32, resume=resume)
    d = open_dataset(ds)
    bad = [s.name for s in d.validate() if s != StreamStatus.OK]
    if bad:
        raise MatrixError(f"ingest recovery left torn shards: {bad}")
    return _digest(d.manifest.to_json())


def _append_exec(workdir: str, seed: int, resume: bool) -> str:
    import numpy as np

    from tpusvm.status import StreamStatus
    from tpusvm.stream.append import append_blocks
    from tpusvm.stream.format import ingest_arrays, open_dataset

    rng = np.random.default_rng(2000 + seed)
    Xb = rng.normal(size=(80, 5)).astype(np.float64)
    Yb = np.where(rng.random(80) < 0.5, 1, -1).astype(np.int64)
    batches = []
    for _ in range(3):
        Xa = rng.normal(size=(24, 5)).astype(np.float64)
        Ya = np.where(rng.random(24) < 0.5, 1, -1).astype(np.int64)
        batches.append((Xa, Ya))
    ds = os.path.join(workdir, "ds")
    if not resume:
        # the committed base dataset the append session reopens; its own
        # kill coverage is the ingest scenario's job
        ingest_arrays(ds, Xb, Yb, rows_per_shard=32)
    append_blocks(ds, batches, resume=resume)
    d = open_dataset(ds)
    bad = [s.name for s in d.validate() if s != StreamStatus.OK]
    if bad:
        raise MatrixError(f"append recovery left torn shards: {bad}")
    if d.manifest.n_rows != 80 + 3 * 24:
        raise MatrixError(
            f"append recovery lost/duplicated rows: manifest says "
            f"{d.manifest.n_rows}, expected {80 + 3 * 24}"
        )
    return _digest(d.manifest.to_json())


def _checkpoint_exec(workdir: str, seed: int, resume: bool) -> str:
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp
    import numpy as np

    from tpusvm.data import MinMaxScaler, rings
    from tpusvm.solver.checkpoint import checkpointed_blocked_solve
    from tpusvm.status import Status

    # the convergence-proven kill-resume-smoke problem (rings n=400):
    # the scenario seed drives the PLAN, not the data — bit-identity of
    # resumed vs. uninterrupted trajectories is the contract under test
    X, Y = rings(n=400, seed=11)
    Xs = jnp.asarray(MinMaxScaler().fit_transform(X), jnp.float32)
    Yd = jnp.asarray(Y)
    ck = os.path.join(workdir, "ck.npz")
    res = checkpointed_blocked_solve(
        Xs, Yd, checkpoint_path=ck, checkpoint_every=4, resume=resume,
        C=10.0, gamma=10.0, q=16, accum_dtype=jnp.float64,
    )
    if Status(int(res.status)) != Status.CONVERGED:
        raise MatrixError(
            f"resumed solve ended {Status(int(res.status)).name}"
        )
    return _digest({
        "alpha": _arr(np.asarray(res.alpha)),
        "b": float(res.b),
    })


def _model_save_exec(workdir: str, seed: int, resume: bool) -> str:
    import numpy as np

    from tpusvm.config import SVMConfig
    from tpusvm.models.serialization import load_model, save_model

    path = os.path.join(workdir, "model.npz")
    if resume and os.path.exists(path):
        load_model(path)  # whatever survived the kill must parse whole
    rng = np.random.default_rng(3000 + seed)
    cfg = SVMConfig(C=2.0, gamma=0.25)
    for rev in (1, 2):  # two commits -> two kill windows per control run
        state = {
            "alpha": rng.normal(size=32).astype(np.float64),
            "sv_X": rng.normal(size=(32, 4)).astype(np.float32),
            "sv_Y": np.where(rng.random(32) < 0.5, 1, -1).astype(np.int32),
            "b": np.float64(0.125 * rev),
        }
        save_model(path, state, cfg)
    got_state, got_cfg = load_model(path)
    return _digest({
        "state": {k: _arr(v) for k, v in sorted(got_state.items())},
        "config": repr(got_cfg),
    })


def _serve_state_exec(workdir: str, seed: int, resume: bool) -> str:
    from tpusvm.serve.cache import (
        load_serve_state,
        read_cache_manifest,
        record_signatures,
        save_serve_state,
    )

    cache_dir = os.path.join(workdir, "cache")
    os.makedirs(cache_dir, exist_ok=True)
    path = os.path.join(workdir, "serve_state.json")
    if resume and os.path.exists(path):
        load_serve_state(path)  # a torn registry must be impossible
    record_signatures(cache_dir, [f"sig-a-{seed}"])
    record_signatures(cache_dir, [f"sig-a-{seed}", f"sig-b-{seed}"])
    for gen in (1, 2):
        save_serve_state(
            path,
            {"m": {"path": None, "generation": gen}},
            cache_dir=cache_dir,
        )
    state = load_serve_state(path)
    manifest = read_cache_manifest(cache_dir)
    return _digest({
        "models": state["models"],
        "signatures": sorted(manifest["signatures"]),
    })


def _autopilot_state_exec(workdir: str, seed: int, resume: bool) -> str:
    from tpusvm.autopilot.state import AutopilotState, load_state, save_state

    path = os.path.join(workdir, "autopilot.json")
    if resume and os.path.exists(path):
        load_state(path)  # CRC + version gate must pass on any survivor
    for rev in (1, 2):
        save_state(path, AutopilotState(seed=seed + rev))
    got = dataclasses.asdict(load_state(path))
    return _digest(got)


def _tenant_store_exec(workdir: str, seed: int, resume: bool) -> str:
    import numpy as np

    from tpusvm.solver.blocked import _OuterState
    from tpusvm.tenants.store import (
        TenantRecord,
        TenantsState,
        load_fleet_checkpoint,
        load_store,
        save_fleet_checkpoint,
        save_store,
    )

    path = os.path.join(workdir, "tenants_store.json")
    ck = os.path.join(workdir, "fleet.ck.npz")
    fp = {"launch": seed}
    if resume:
        # CRC + version gates must pass on any survivor of the kill —
        # both durable artifacts share the tenants.store point
        if os.path.exists(path):
            load_store(path)
        if os.path.exists(ck):
            load_fleet_checkpoint(ck, fp)
    rng = np.random.default_rng(7000 + seed)
    for rev in (1, 2):
        st = TenantsState(seed=seed, tick=rev, tenants={
            "a": TenantRecord(tenant_id="a", positive_label=1,
                              C=1.0, gamma=0.5, generation=rev),
            "b": TenantRecord(tenant_id="b", positive_label=2,
                              C=10.0, gamma=1.5, row_mod=2,
                              row_ofs=rev % 2),
        })
        save_store(path, st)
        carry = _OuterState(*(
            np.asarray(rng.normal(size=(2, 8)), np.float32)
            for _ in _OuterState._fields))
        save_fleet_checkpoint(ck, carry, fp)
    got = load_store(path).to_json()
    back = load_fleet_checkpoint(ck, fp)
    return _digest({"store": got,
                    "carry": [_arr(getattr(back, f))
                              for f in _OuterState._fields]})


def _cascade_ckpt_exec(workdir: str, seed: int, resume: bool) -> str:
    import jax.numpy as jnp
    import numpy as np

    from tpusvm.parallel.cascade import load_round_state, save_round_state
    from tpusvm.parallel.svbuffer import SVBuffer

    path = os.path.join(workdir, "round.npz")
    if resume and os.path.exists(path):
        load_round_state(path)  # version gate + shapes must parse whole
    rng = np.random.default_rng(4000 + seed)
    cap, dim = 16, 4
    for rnd in (1, 2):
        buf = SVBuffer(
            X=jnp.asarray(rng.normal(size=(cap, dim)), jnp.float32),
            Y=jnp.asarray(np.where(rng.random(cap) < 0.5, 1, -1)),
            alpha=jnp.asarray(rng.random(cap), jnp.float32),
            ids=jnp.arange(cap, dtype=jnp.int32),
            valid=jnp.asarray(rng.random(cap) < 0.75),
        )
        save_round_state(path, buf, prev_ids={1, 2, 3}, rnd=rnd,
                         b=0.5 * rnd, n_shards=4, topology="binary")
    sv, prev_ids, next_round, b = load_round_state(path)
    return _digest({
        "sv": [_arr(np.asarray(x)) for x in sv],
        "prev_ids": sorted(prev_ids),
        "next_round": int(next_round),
        "b": float(b),
    })


def _pod_round_exec(workdir: str, seed: int, resume: bool) -> str:
    import jax.numpy as jnp
    import numpy as np

    from tpusvm.parallel.svbuffer import SVBuffer
    from tpusvm.pod.state import load_pod_round_state, save_pod_round_state

    path = os.path.join(workdir, "pod_round.npz")
    if resume and os.path.exists(path):
        load_pod_round_state(path)  # version gate + shapes parse whole
    rng = np.random.default_rng(4100 + seed)
    cap, dim = 16, 4
    for rnd in (1, 2):
        buf = SVBuffer(
            X=jnp.asarray(rng.normal(size=(cap, dim)), jnp.float32),
            Y=jnp.asarray(np.where(rng.random(cap) < 0.5, 1, -1)),
            alpha=jnp.asarray(rng.random(cap), jnp.float32),
            ids=jnp.arange(cap, dtype=jnp.int32),
            valid=jnp.asarray(rng.random(cap) < 0.75),
        )
        save_pod_round_state(path, buf, prev_ids={1, 2, 3}, rnd=rnd,
                             b=0.5 * rnd, n_leaves=4, topology="tree")
    sv, prev_ids, next_round, b = load_pod_round_state(path)
    return _digest({
        "sv": [_arr(np.asarray(x)) for x in sv],
        "prev_ids": sorted(prev_ids),
        "next_round": int(next_round),
        "b": float(b),
    })


SCENARIOS: Dict[str, Scenario] = {
    s.name: s for s in (
        Scenario(
            name="ingest",
            points=frozenset({"ingest.write_shard", "stream.journal"}),
            doc="fresh sharded ingest killed mid-shard/journal, resumed "
                "from the v1 journal; manifest + shard checksums must "
                "match an uninterrupted ingest",
            execute=_ingest_exec,
        ),
        Scenario(
            name="append",
            points=frozenset({"stream.append"}),
            doc="tail-shard append session killed at journal writes and "
                "both commit transitions, resumed with the same batch "
                "replay; exactly-once (no lost/duplicated rows)",
            execute=_append_exec,
        ),
        Scenario(
            name="checkpoint",
            points=frozenset({"solver.outer_checkpoint"}),
            doc="checkpointed blocked solve killed at checkpoint writes, "
                "resumed; bit-identical alpha/b to an uninterrupted run",
            execute=_checkpoint_exec,
        ),
        Scenario(
            name="model_save",
            points=frozenset({"models.save"}),
            doc="model artifact saved twice, killed mid-commit; whatever "
                "file survives must load whole (no torn npz)",
            execute=_model_save_exec,
        ),
        Scenario(
            name="serve_state",
            points=frozenset({"serve.state_write"}),
            doc="serve registry + cache-manifest writes killed "
                "mid-commit; survivors parse whole and a re-run "
                "converges to the control state",
            execute=_serve_state_exec,
        ),
        Scenario(
            name="autopilot_state",
            points=frozenset({"autopilot.state"}),
            doc="autopilot supervisor state killed mid-commit; the CRC "
                "fingerprint + version gate must pass on any survivor",
            execute=_autopilot_state_exec,
        ),
        Scenario(
            name="tenant_store",
            points=frozenset({"tenants.store"}),
            doc="tenant registry + fleet segment checkpoint killed "
                "mid-commit; the CRC/fingerprint + version gates must "
                "pass on any survivor and the recovered pair matches "
                "the control digests",
            execute=_tenant_store_exec,
        ),
        Scenario(
            name="pod_round",
            points=frozenset({"pod.merge"}),
            doc="pod coordinator round checkpoint killed mid-commit; "
                "survivor loads whole (a torn write leaves the previous "
                "round) and a resumed coordinator matches the control",
            execute=_pod_round_exec,
        ),
        Scenario(
            name="cascade_ckpt",
            points=frozenset({"cascade.checkpoint"}),
            doc="cascade round checkpoint killed mid-commit; survivor "
                "loads whole and a re-run matches the control rounds",
            execute=_cascade_ckpt_exec,
        ),
    )
}


# ------------------------------------------------------------ derivation
def derive_plan(seed: int = 0,
                scenarios: Optional[List[str]] = None,
                max_windows: Optional[int] = None,
                root: Optional[Path] = None) -> dict:
    """Control-run the scenarios and emit the kill-window plan.

    Raises RuntimeError when the derived point universe is not fully
    claimed by the scenario registry (coverage may never lag the code)
    or when a claimed point takes zero hits in its scenario's control
    run (a dead claim is as bad as a missing one)."""
    from tpusvm import faults

    derived = derive_points(root)
    claimed = frozenset().union(*(s.points for s in SCENARIOS.values()))
    unclaimed = sorted(set(derived) - claimed)
    if unclaimed:
        sites = {p: derived[p] for p in unclaimed}
        raise RuntimeError(
            f"write-guarding fault point(s) {unclaimed} have no recovery "
            f"scenario (sites: {sites}); extend "
            "tpusvm/analysis/dura/matrix.py SCENARIOS so the crash-window "
            "matrix covers them"
        )
    names = list(scenarios) if scenarios else sorted(SCENARIOS)
    windows: List[dict] = []
    for name in names:
        sc = SCENARIOS[name]
        counter = faults.FaultPlan([], seed=seed)
        with tempfile.TemporaryDirectory() as td:
            with faults.active(counter):
                sc.execute(td, seed, False)
        for point in sorted(sc.points):
            hits = counter.hits(point)
            if hits <= 0:
                raise RuntimeError(
                    f"scenario {name!r} claims fault point {point!r} but "
                    "its control run never hit it; the claim is stale — "
                    "fix the scenario or the point registration"
                )
            cap = hits if max_windows is None else min(hits, max_windows)
            for k in range(1, cap + 1):
                windows.append({
                    "scenario": name,
                    "point": point,
                    "at_hit": k,
                    "control_hits": hits,
                })
    return {
        "format_version": 1,
        "kind": "tpusvm-dura-matrix-plan",
        "seed": seed,
        "derived_points": {p: sorted(v) for p, v in derived.items()},
        "scenarios": names,
        "windows": windows,
    }


def render_plan(plan: dict) -> str:
    """Canonical (byte-stable per seed) rendering of a derived plan."""
    return json.dumps(plan, indent=1, sort_keys=True) + "\n"


# --------------------------------------------------------------- running
@dataclasses.dataclass
class WindowResult:
    scenario: str
    point: str
    at_hit: int
    ok: bool
    detail: str


@dataclasses.dataclass
class MatrixReport:
    seed: int
    results: List[WindowResult]

    @property
    def ok(self) -> bool:
        return all(r.ok for r in self.results)

    def render(self) -> str:
        lines = []
        n_bad = sum(1 for r in self.results if not r.ok)
        for r in self.results:
            mark = "ok  " if r.ok else "FAIL"
            lines.append(f"{mark} {r.scenario:<16} {r.point:<24} "
                         f"at_hit={r.at_hit:<3} {r.detail}")
        lines.append(
            f"tpusvm-dura-matrix: {len(self.results)} kill window(s), "
            f"{n_bad} failure(s), seed={self.seed}"
        )
        return "\n".join(lines)


def run_matrix(plan: dict) -> MatrixReport:
    """Replay every window in the plan: kill, recover, compare digests."""
    from tpusvm import faults

    seed = int(plan["seed"])
    results: List[WindowResult] = []
    by_scenario: Dict[str, List[dict]] = {}
    for w in plan["windows"]:
        by_scenario.setdefault(w["scenario"], []).append(w)
    for name in sorted(by_scenario):
        sc = SCENARIOS[name]
        with tempfile.TemporaryDirectory() as td:
            control = sc.execute(td, seed, False)
        for w in by_scenario[name]:
            rule = faults.FaultRule(point=w["point"], kind="kill",
                                    at_hit=int(w["at_hit"]))
            kill_plan = faults.FaultPlan([rule], seed=seed)
            with tempfile.TemporaryDirectory() as td:
                died = False
                try:
                    with faults.active(kill_plan):
                        sc.execute(td, seed, False)
                except faults.SimulatedKill:
                    died = True
                if not died:
                    results.append(WindowResult(
                        name, w["point"], int(w["at_hit"]), False,
                        "kill rule never fired (control hits drifted)"))
                    continue
                try:
                    recovered = sc.execute(td, seed, True)
                except Exception as e:  # noqa: BLE001 — report, don't die
                    results.append(WindowResult(
                        name, w["point"], int(w["at_hit"]), False,
                        f"recovery raised {type(e).__name__}: {e}"))
                    continue
                if recovered == control:
                    results.append(WindowResult(
                        name, w["point"], int(w["at_hit"]), True,
                        "recovered == control"))
                else:
                    results.append(WindowResult(
                        name, w["point"], int(w["at_hit"]), False,
                        "recovered state digest diverged from control"))
    return MatrixReport(seed=seed, results=results)
