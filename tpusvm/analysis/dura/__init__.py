"""tpusvm.analysis.dura — the crash-safety & atomicity auditor.

Static arm (`python -m tpusvm.analysis dura`, rules JXD301-306): an AST
pass over the durable-state modules that models every write protocol —
final-path writes, temp+os.replace pairs, journal transitions,
format-version fields — and machine-checks the disciplines the chaos
tests rely on. Pure stdlib like the JX/JXC linters (no jax, no numpy:
even `faults/injection.py` is AST-parsed, not imported), so it runs in
the no-jax CI lint job with its own empty committed baseline
(`.tpusvm-dura-baseline.json`).

Dynamic arm (`python -m tpusvm.analysis dura-matrix`): kill windows are
DERIVED from the static model — every write-guarding fault point times
every hit it takes in a control run becomes a generated FaultPlan kill
rule — and the recovery scenarios run over that matrix, so chaos
coverage can never lag the code (test-job; needs numpy/jax).
"""

from tpusvm.analysis.dura.lint import (
    dura_lint_file,
    dura_lint_paths,
    dura_lint_source,
)
from tpusvm.analysis.dura.model import (
    DURABLE_MODULES,
    DuraModel,
    registered_points,
)
from tpusvm.analysis.dura.rules import DURA_RULE_SUMMARIES, all_dura_rules

__all__ = [
    "DURABLE_MODULES",
    "DURA_RULE_SUMMARIES",
    "DuraModel",
    "all_dura_rules",
    "dura_lint_file",
    "dura_lint_paths",
    "dura_lint_source",
    "registered_points",
]
