"""Lint driver for the durability rules (JXD301-306).

Mirrors tpusvm.analysis.conc.lint: shared Finding type, LintResult,
fingerprints, file discovery, plus the `# tpusvm: durable-by=<invariant>`
annotation on top of the shared disable comments. Durable-by
suppressions require non-empty invariant text — the annotation exists
to DOCUMENT why the site is crash-safe, so an empty one does not
suppress.
"""

from __future__ import annotations

from typing import List, Optional, Set, Tuple

from tpusvm.analysis.context import ModuleContext
from tpusvm.analysis.core import (
    Finding,
    durable_by_annotation,
    file_suppressions,
    fingerprint_findings,
    is_suppressed,
    iter_python_files,
)
from tpusvm.analysis.dura.model import DuraModel
from tpusvm.analysis.dura.rules import all_dura_rules
from tpusvm.analysis.lint import LintResult


def _select(select: Optional[Set[str]], ignore: Optional[Set[str]]):
    rules = all_dura_rules()
    unknown = (set(select or ()) | set(ignore or ())) - set(rules)
    if unknown:
        raise ValueError(f"unknown dura rule id(s): {sorted(unknown)}; "
                         f"known: {sorted(rules)}")
    return [r for rid, r in rules.items()
            if (not select or rid in select)
            and (not ignore or rid not in ignore)]


def dura_lint_source(source: str, path: str = "<string>",
                     select: Optional[Set[str]] = None,
                     ignore: Optional[Set[str]] = None,
                     ) -> Tuple[List[Finding], List[Finding]]:
    """Run the JXD rules on one source string -> (active, suppressed)."""
    rules = _select(select, ignore)
    try:
        ctx = ModuleContext(path, source)
    except SyntaxError as e:
        return fingerprint_findings([Finding(
            rule="JXD300", path=path, line=e.lineno or 1,
            col=(e.offset or 0) + 1,
            message=f"file does not parse: {e.msg}",
        )]), []
    model = DuraModel(ctx)
    raw: List[Finding] = []
    for rule in rules:
        raw.extend(rule.check_model(model))
    raw.sort(key=lambda f: (f.line, f.col, f.rule))
    raw = fingerprint_findings(raw)
    file_rules = file_suppressions(ctx.lines)
    active, suppressed = [], []
    for f in raw:
        if is_suppressed(f, ctx.lines, file_rules) or \
                durable_by_annotation(ctx.lines, f.line) is not None:
            suppressed.append(f)
        else:
            active.append(f)
    return active, suppressed


def dura_lint_file(path, select=None, ignore=None):
    with open(path, encoding="utf-8") as fh:
        source = fh.read()
    return dura_lint_source(source, str(path), select, ignore)


def dura_lint_paths(paths, select=None, ignore=None,
                    baseline: Optional[Set[Tuple[str, str, str]]] = None,
                    ) -> LintResult:
    """Lint every .py file under `paths` with the JXD rules; `baseline`
    is the same (rule, path, fingerprint) grandfathering set the tracing
    linter uses, read from .tpusvm-dura-baseline.json by the CLI."""
    findings: List[Finding] = []
    suppressed: List[Finding] = []
    baselined: List[Finding] = []
    files = iter_python_files(paths)
    for f in files:
        active, supp = dura_lint_file(f, select, ignore)
        suppressed.extend(supp)
        for finding in active:
            key = (finding.rule, finding.path, finding.fingerprint)
            if baseline and key in baseline:
                baselined.append(finding)
            else:
                findings.append(finding)
    return LintResult(findings=findings, suppressed=suppressed,
                      baselined=baselined, files_scanned=len(files))
