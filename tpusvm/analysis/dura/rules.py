"""JXD301-306 — the crash-safety rules over the write-protocol model.

PRs 7/14/15 made every durable artifact here crash-safe by HAND —
staged-temp + os.replace writes, versioned journals, checkpointed
solves — but the discipline was enforced only by the chaos tests that
happened to exist. These rules machine-check it, the way JX001-010
check tracing discipline and JXC201-206 check lock discipline:

  JXD301  write to a committed final path without the staged-temp +
          os.replace protocol (torn-file hazard)
  JXD302  temp staged in a different directory than its replace target
          (cross-device rename is copy+delete: atomicity lost)
  JXD303  durable-state rename-commit site not covered by a registered
          fault point (coverage cross-checked against faults/injection
          POINTS — derived, not hand-listed); also any faults.point
          literal naming an unregistered point
  JXD304  format-versioned writer whose module reader never gates the
          version field
  JXD305  journal/commit ordering hazard: the journal deleted before
          the artifact it covers is committed
  JXD306  durable write without flush-before-rename where the module
          claims kill-safety (the sanctioned spelling is
          tpusvm.utils.durable.fsync_replace)

Suppression: the shared ``# tpusvm: disable=JXD30x`` comments work, but
the idiomatic form is ``# tpusvm: durable-by=<invariant>`` — it
suppresses AND names the crash-safety invariant that makes the site
safe (append-only with torn-tail-rejecting reader, best-effort rotation
of already-persisted bytes, ...). An empty invariant is not a
suppression.

These rules live in their own registry (``all_dura_rules``) and run
under ``python -m tpusvm.analysis dura`` with their own baseline
(``.tpusvm-dura-baseline.json``). Pure stdlib, no jax/numpy — the
no-jax CI lint job lists and runs it.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional

from tpusvm.analysis.core import Finding, snippet_at
from tpusvm.analysis.dura.model import (
    _JOURNAL_RE,
    DuraModel,
    registered_points,
)
from tpusvm.analysis.registry import Rule

DURA_RULES: Dict[str, Rule] = {}


def dura_register(cls):
    inst = cls()
    if not inst.id:
        raise ValueError(f"dura rule {cls.__name__} has no id")
    if inst.id in DURA_RULES:
        raise ValueError(f"duplicate dura rule id {inst.id}")
    DURA_RULES[inst.id] = inst
    return cls


def all_dura_rules() -> Dict[str, Rule]:
    return dict(sorted(DURA_RULES.items()))


DURA_RULE_SUMMARIES = {
    "JXD301": ("durable write straight onto a committed final path — no "
               "staged-temp + os.replace, so a kill mid-write leaves a "
               "torn file"),
    "JXD302": ("temp file staged in a different directory than its "
               "os.replace target (cross-device rename falls back to "
               "copy+delete: atomicity lost)"),
    "JXD303": ("durable-state commit site not covered by a registered "
               "fault point (faults/injection.py POINTS), or a "
               "faults.point literal naming an unregistered point"),
    "JXD304": ("format-versioned writer whose module reader never gates "
               "the version field (old files half-parse instead of "
               "failing loudly)"),
    "JXD305": ("journal deleted before the artifact it covers is "
               "committed — a kill between the delete and the commit "
               "strands an unrecoverable directory"),
    "JXD306": ("os.replace on a kill-safe path without flush+fsync of "
               "the staged bytes (use tpusvm.utils.durable."
               "fsync_replace): rename can commit before data reaches "
               "disk"),
}


def _finding(rule_id: str, model: DuraModel, node: ast.AST,
             message: str) -> Finding:
    ctx = model.ctx
    return Finding(
        rule=rule_id, path=ctx.path, line=node.lineno,
        col=node.col_offset + 1, message=message,
        snippet=snippet_at(ctx.lines, node.lineno),
    )


class DuraRule(Rule):
    """A rule over one DuraModel (check_model, like the conc rules)."""

    def check_model(self, model: DuraModel) -> List[Finding]:
        raise NotImplementedError


@dura_register
class UnstagedDurableWrite(DuraRule):
    id = "JXD301"
    summary = DURA_RULE_SUMMARIES[id]

    def check_model(self, model: DuraModel) -> List[Finding]:
        out: List[Finding] = []
        for scope in model.scopes:
            for w in scope.writes:
                if w.mode == "a":
                    # append-only protocols are torn-TAIL territory; the
                    # reader's job (read_trace rejects torn records)
                    continue
                if model.write_is_staged(w, scope):
                    continue
                out.append(_finding(
                    self.id, model, w.node,
                    "write lands directly on its final path (no staged "
                    "temp + os.replace in this scope); a kill mid-write "
                    "leaves a torn file where readers expect a committed "
                    "artifact",
                ))
        return out


@dura_register
class CrossDirectoryStage(DuraRule):
    id = "JXD302"
    summary = DURA_RULE_SUMMARIES[id]

    def check_model(self, model: DuraModel) -> List[Finding]:
        out: List[Finding] = []
        for scope in model.scopes:
            for r in scope.replaces:
                if r.src is None or r.dst is None:
                    continue
                src = model.dir_identity(r.src, scope)
                dst = model.dir_identity(r.dst, scope)
                if src is None or dst is None:
                    continue
                if src[0] == "tempfile" and dst[0] != "tempfile":
                    out.append(_finding(
                        self.id, model, r.node,
                        "replace source is staged under tempfile's "
                        "directory but the target lives elsewhere — "
                        "os.replace across filesystems raises EXDEV (or "
                        "degrades to copy+delete): stage the temp next "
                        "to its target",
                    ))
                elif src[0] == dst[0] and src[1] != dst[1]:
                    out.append(_finding(
                        self.id, model, r.node,
                        f"replace source directory ({src[1]}) differs "
                        f"from target directory ({dst[1]}); a "
                        "cross-device rename is not atomic — stage the "
                        "temp in the target's directory",
                    ))
        return out


@dura_register
class UncoveredCommitSite(DuraRule):
    id = "JXD303"
    summary = DURA_RULE_SUMMARIES[id]

    def check_model(self, model: DuraModel) -> List[Finding]:
        out: List[Finding] = []
        points = registered_points()
        if points is not None:
            for call, lit in model.point_calls:
                if lit is not None and lit not in points:
                    out.append(_finding(
                        self.id, model, call,
                        f"faults.point names {lit!r}, which is not in "
                        "the registered POINTS set "
                        "(tpusvm/faults/injection.py) — an active plan "
                        "would reject it at the call site",
                    ))
        if not model.durable:
            return out
        for scope in model.scopes:
            for r in scope.replaces:
                if model.point_covered(r.node):
                    continue
                out.append(_finding(
                    self.id, model, r.node,
                    "durable-state commit (rename) site with no "
                    "faults.point call in its enclosing function — this "
                    "write protocol is invisible to every chaos plan "
                    "and to the derived crash-window matrix "
                    "(dura-matrix); register an injection point in "
                    "faults/injection.py POINTS and call it on this "
                    "path",
                ))
        return out


@dura_register
class UngatedVersionField(DuraRule):
    id = "JXD304"
    summary = DURA_RULE_SUMMARIES[id]

    def check_model(self, model: DuraModel) -> List[Finding]:
        if not model.durable or not model.has_readers:
            return []
        out: List[Finding] = []
        seen = set()
        for key, node in model.version_writes:
            if key in model.read_keys or key in seen:
                continue
            seen.add(key)
            out.append(_finding(
                self.id, model, node,
                f"writer stamps version field {key!r} but no reader in "
                "this module ever gates it (subscript/.get/membership); "
                "files from a different build will half-parse instead "
                "of failing with a version error",
            ))
        return out


@dura_register
class JournalDeletedBeforeCommit(DuraRule):
    id = "JXD305"
    summary = DURA_RULE_SUMMARIES[id]

    def check_model(self, model: DuraModel) -> List[Finding]:
        out: List[Finding] = []
        for scope in model.scopes:
            if not scope.replaces:
                continue
            last_replace = max(r.node.lineno for r in scope.replaces)
            for rm in scope.removes:
                arg = ast.unparse(rm.args[0]) if rm.args else ""
                if not _JOURNAL_RE.search(arg):
                    continue
                if rm.lineno < last_replace:
                    out.append(_finding(
                        self.id, model, rm,
                        "journal removed BEFORE a later rename-commit "
                        "in the same scope — a kill in between leaves "
                        "an uncommitted artifact with its recovery "
                        "journal already gone; commit first, delete "
                        "the journal last",
                    ))
        return out


@dura_register
class RenameWithoutFsync(DuraRule):
    id = "JXD306"
    summary = DURA_RULE_SUMMARIES[id]

    def check_model(self, model: DuraModel) -> List[Finding]:
        if not (model.durable and model.kill_safe):
            return []
        out: List[Finding] = []
        for scope in model.scopes:
            has_fsync = bool(scope.fsyncs)
            for r in scope.replaces:
                if r.fsynced or has_fsync:
                    continue
                out.append(_finding(
                    self.id, model, r.node,
                    "kill-safe protocol commits with a bare os.replace: "
                    "the rename can reach disk before the staged bytes "
                    "do, so a power loss commits a hollow file — spell "
                    "it tpusvm.utils.durable.fsync_replace (or fsync "
                    "the staged fd first)",
                ))
        return out
