"""Per-module write-protocol model for the durability rules.

One DuraModel per file: every durable write (`open(p, "w")`, np.save /
np.savez[_compressed], Path.write_text/write_bytes), every rename-commit
(`os.replace` / `os.rename` / the sanctioned `fsync_replace`), every
journal delete, every `faults.point(...)` call and every format-version
field, grouped by lexical scope. The JXD rules are queries over this
model, the way the JXC rules query ConcModel.

Like the rest of the linter this is a LEXICAL approximation, tuned for a
low false-positive rate on this repo rather than completeness:

  * a write is "staged" when its target shares a path variable with some
    replace-source in the same scope, or when the target spelling
    carries a staging suffix (.tmp/.stage/.part/.new) — a tmp-named file
    that is never renamed is invisible to us;
  * directory identity (JXD302) is resolved through single in-scope
    assignments and os.path.join/`+` shapes; paths we cannot resolve are
    never reported;
  * fault-point coverage (JXD303) is per replace site against the chain
    of lexically enclosing functions — cross-function indirection (the
    point lives in a helper the writer calls) is out of scope and is
    exactly what the derived crash-window matrix (dura-matrix) covers
    dynamically.

Which modules own durable state is a REGISTRY here (DURABLE_MODULES),
extended per-file by the `# tpusvm: durable-protocol[=kill-safe]` pragma
(how the corpus cases opt in). The fault-point universe is AST-parsed
out of tpusvm/faults/injection.py (`POINTS = frozenset({...})`) so the
lint job never imports numpy; tests/test_dura.py pins the parse against
the runtime set.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

#: repo-relative posix path suffix -> claims kill-safety (JXD306 scope).
#: These are the modules whose files a kill may land on mid-write; the
#: True entries additionally promise flush-before-rename durability
#: (journal/commit hot paths whose recovery contract is exactly-once).
DURABLE_MODULES: Dict[str, bool] = {
    "tpusvm/stream/format.py": True,
    "tpusvm/stream/append.py": True,
    "tpusvm/solver/checkpoint.py": True,
    "tpusvm/autopilot/state.py": True,
    "tpusvm/tenants/store.py": True,
    "tpusvm/pod/state.py": True,
    "tpusvm/models/serialization.py": False,
    "tpusvm/serve/cache.py": False,
    "tpusvm/serve/refresh.py": False,
    "tpusvm/serve/watch.py": False,
    "tpusvm/obs/trace.py": False,
    "tpusvm/parallel/cascade.py": False,
}

_DURABLE_PRAGMA_RE = re.compile(
    r"#\s*tpusvm:\s*durable-protocol(=kill-safe)?\b"
)
_STAGED_SPELLING_RE = re.compile(r"\.(tmp|stage|part|new)\b")
_VERSION_KEY_RE = re.compile(r"version", re.IGNORECASE)
_VERSION_VALUE_RE = re.compile(r"VERSION")
_JOURNAL_RE = re.compile(r"journal", re.IGNORECASE)

_WRITE_MODES = frozenset("wxa")
_SAVEZ_CALLS = frozenset(
    {"numpy.save", "numpy.savez", "numpy.savez_compressed"}
)
_REPLACE_CALLS = frozenset({"os.replace", "os.rename"})
_REMOVE_CALLS = frozenset({"os.remove", "os.unlink"})
_JSON_READ_CALLS = frozenset({"json.load", "json.loads", "numpy.load"})


def durable_status(path: str, source: str) -> Tuple[bool, bool]:
    """(is_durable_module, claims_kill_safety) for one file.

    Registry suffix match first; the `# tpusvm: durable-protocol` pragma
    opts any file in (corpus cases), `=kill-safe` also claims JXD306."""
    posix = Path(path).as_posix()
    for suffix, kill_safe in DURABLE_MODULES.items():
        if posix.endswith(suffix):
            return True, kill_safe
    m = _DURABLE_PRAGMA_RE.search(source)
    if m:
        return True, m.group(1) is not None
    return False, False


def registered_points(root: Optional[Path] = None
                      ) -> Optional[FrozenSet[str]]:
    """The fault-point universe, AST-parsed from faults/injection.py.

    Parsed rather than imported so the no-jax lint job never pulls
    numpy. Returns None when the file (or the POINTS assignment) cannot
    be found — rules degrade to skipping the coverage cross-check rather
    than guessing."""
    if root is None:
        root = Path(__file__).resolve().parents[2]
    inj = Path(root) / "faults" / "injection.py"
    try:
        tree = ast.parse(inj.read_text(encoding="utf-8"))
    except (OSError, SyntaxError):
        return None
    for node in tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "POINTS"):
            continue
        v = node.value
        if isinstance(v, ast.Call) and v.args:
            v = v.args[0]
        if isinstance(v, (ast.Set, ast.Tuple, ast.List)) and all(
            isinstance(e, ast.Constant) and isinstance(e.value, str)
            for e in v.elts
        ):
            return frozenset(e.value for e in v.elts)
    return None


def _safe_unparse(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # noqa: BLE001 — unparse of a synthetic node
        return ""


def _own_nodes(scope_node: ast.AST) -> List[ast.AST]:
    """Descendants of a scope, stopping at nested function boundaries
    (each nested def is a scope of its own)."""
    out: List[ast.AST] = []
    stack = list(ast.iter_child_nodes(scope_node))
    while stack:
        node = stack.pop()
        out.append(node)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))
    return out


def _path_tokens(expr: ast.AST) -> Set[str]:
    """Identity tokens of a path expression: bare Names, whole attribute
    chains (`self.out_dir`) and whole call spellings
    (`self._journal_path()`) — but never the module root of a call's
    func chain, so `os.path.join(d, x)` contributes {d, x}, not `os`."""
    toks: Set[str] = set()

    def visit(n: ast.AST) -> None:
        if isinstance(n, ast.Call):
            toks.add(_safe_unparse(n))
            for a in n.args:
                visit(a)
            for kw in n.keywords:
                visit(kw.value)
            return
        if isinstance(n, ast.Attribute):
            toks.add(_safe_unparse(n))
            return
        if isinstance(n, ast.Name):
            toks.add(n.id)
            return
        for c in ast.iter_child_nodes(n):
            visit(c)

    visit(expr)
    toks.discard("")
    return toks


@dataclasses.dataclass
class WriteSite:
    """One durable write call (open-for-write / savez / write_text)."""

    node: ast.Call
    target: ast.AST                 # the path expression being written
    kind: str                       # "open" | "savez" | "write_text"
    mode: str                       # "w" | "x" | "a"


@dataclasses.dataclass
class ReplaceSite:
    """One rename-commit call (os.replace / os.rename / fsync_replace)."""

    node: ast.Call
    src: Optional[ast.AST]
    dst: Optional[ast.AST]
    fsynced: bool                   # spelled as the sanctioned helper


@dataclasses.dataclass
class Scope:
    """One lexical scope (module body or one function def)."""

    node: ast.AST
    name: str
    writes: List[WriteSite] = dataclasses.field(default_factory=list)
    replaces: List[ReplaceSite] = dataclasses.field(default_factory=list)
    removes: List[ast.Call] = dataclasses.field(default_factory=list)
    fsyncs: List[ast.Call] = dataclasses.field(default_factory=list)
    #: single-assignment name -> value expr (ambiguous names excluded)
    assignments: Dict[str, ast.AST] = dataclasses.field(default_factory=dict)


class DuraModel:
    """The write-protocol model of one module (see module docstring)."""

    def __init__(self, ctx):
        self.ctx = ctx
        self.durable, self.kill_safe = durable_status(ctx.path, ctx.source)
        self.scopes: List[Scope] = []
        #: fault-point calls: (call node, literal point name or None)
        self.point_calls: List[Tuple[ast.Call, Optional[str]]] = []
        #: format-version fields written: (key, anchor node)
        self.version_writes: List[Tuple[str, ast.AST]] = []
        #: constant string keys read in gate positions (subscript, .get,
        #: `in`/`not in` membership)
        self.read_keys: Set[str] = set()
        self.has_readers = False
        # function parents chain for fault-point coverage (JXD303)
        self._fn_parents: Dict[int, Optional[ast.AST]] = {}
        self._build()

    # -------------------------------------------------------- construction
    def _build(self) -> None:
        tree = self.ctx.tree
        fn_nodes = [n for n in ast.walk(tree)
                    if isinstance(n, (ast.FunctionDef,
                                      ast.AsyncFunctionDef))]
        parents: Dict[int, ast.AST] = {}
        for n in ast.walk(tree):
            for c in ast.iter_child_nodes(n):
                parents[id(c)] = n
        for fn in fn_nodes:
            p = parents.get(id(fn))
            while p is not None and not isinstance(
                p, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                p = parents.get(id(p))
            self._fn_parents[id(fn)] = p
        self._parents = parents

        self.scopes.append(self._scan_scope(tree, "<module>"))
        for fn in fn_nodes:
            self.scopes.append(self._scan_scope(fn, fn.name))
        self._scan_versions(tree)

    def _scan_scope(self, node: ast.AST, name: str) -> Scope:
        scope = Scope(node=node, name=name)
        assigned_counts: Dict[str, int] = {}
        for n in _own_nodes(node):
            if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                    and isinstance(n.targets[0], ast.Name):
                nm = n.targets[0].id
                assigned_counts[nm] = assigned_counts.get(nm, 0) + 1
                scope.assignments[nm] = n.value
            if not isinstance(n, ast.Call):
                continue
            resolved = self.ctx.resolve_call(n)
            w = self._as_write(n, resolved)
            if w is not None:
                scope.writes.append(w)
            elif resolved in _REPLACE_CALLS and len(n.args) >= 2:
                scope.replaces.append(ReplaceSite(
                    node=n, src=n.args[0], dst=n.args[1], fsynced=False))
            elif resolved and resolved.split(".")[-1] == "fsync_replace":
                scope.replaces.append(ReplaceSite(
                    node=n,
                    src=n.args[0] if n.args else None,
                    dst=n.args[1] if len(n.args) > 1 else None,
                    fsynced=True))
            elif resolved in _REMOVE_CALLS and n.args:
                scope.removes.append(n)
            elif resolved == "os.fsync":
                scope.fsyncs.append(n)
            elif resolved in _JSON_READ_CALLS:
                self.has_readers = True
            if self._is_point_call(resolved):
                lit = None
                if n.args and isinstance(n.args[0], ast.Constant) \
                        and isinstance(n.args[0].value, str):
                    lit = n.args[0].value
                self.point_calls.append((n, lit))
        # ambiguous (multiply-assigned) names cannot be followed
        for nm, count in assigned_counts.items():
            if count > 1:
                scope.assignments.pop(nm, None)
        return scope

    @staticmethod
    def _is_point_call(resolved: Optional[str]) -> bool:
        if not resolved:
            return False
        return bool(re.search(r"(?:^|\.)faults(?:\.injection)?\.point$",
                              resolved))

    def _as_write(self, call: ast.Call,
                  resolved: Optional[str]) -> Optional[WriteSite]:
        if resolved == "open" or (isinstance(call.func, ast.Name)
                                  and call.func.id == "open"):
            mode = "r"
            if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant) \
                    and isinstance(call.args[1].value, str):
                mode = call.args[1].value
            for kw in call.keywords:
                if kw.arg == "mode" and isinstance(kw.value, ast.Constant) \
                        and isinstance(kw.value.value, str):
                    mode = kw.value.value
            if not (_WRITE_MODES & set(mode)) or not call.args:
                return None
            kind = "a" if "a" in mode else ("x" if "x" in mode else "w")
            return WriteSite(node=call, target=call.args[0], kind="open",
                             mode=kind)
        if resolved in _SAVEZ_CALLS and call.args:
            if self._is_buffer(call.args[0]):
                return None
            return WriteSite(node=call, target=call.args[0], kind="savez",
                             mode="w")
        if isinstance(call.func, ast.Attribute) \
                and call.func.attr in ("write_text", "write_bytes"):
            return WriteSite(node=call, target=call.func.value,
                             kind="write_text", mode="w")
        return None

    def _is_buffer(self, target: ast.AST) -> bool:
        """np.savez(buf, ...) onto an in-memory BytesIO is not a durable
        write — the bytes land on disk through a later open()."""
        if isinstance(target, ast.Call):
            r = self.ctx.resolve(target.func)
            return bool(r) and r.split(".")[-1] in ("BytesIO", "StringIO")
        if isinstance(target, ast.Name):
            # follow one assignment in the innermost scope owning it
            for scope in self.scopes:
                v = scope.assignments.get(target.id)
                if isinstance(v, ast.Call):
                    r = self.ctx.resolve(v.func)
                    if r and r.split(".")[-1] in ("BytesIO", "StringIO"):
                        return True
            # also scan pending assignments lexically (scopes list may
            # not include the current scope yet during construction)
            for n in ast.walk(self.ctx.tree):
                if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                        and isinstance(n.targets[0], ast.Name) \
                        and n.targets[0].id == target.id \
                        and isinstance(n.value, ast.Call):
                    r = self.ctx.resolve(n.value.func)
                    if r and r.split(".")[-1] in ("BytesIO", "StringIO"):
                        return True
        return False

    def _scan_versions(self, tree: ast.AST) -> None:
        for n in ast.walk(tree):
            if isinstance(n, ast.Dict):
                for k, v in zip(n.keys, n.values):
                    if not (isinstance(k, ast.Constant)
                            and isinstance(k.value, str)):
                        continue
                    if _VERSION_KEY_RE.search(k.value) or (
                        isinstance(v, (ast.Name, ast.Attribute))
                        and _VERSION_VALUE_RE.search(_safe_unparse(v))
                    ):
                        self.version_writes.append((k.value, k))
            elif isinstance(n, ast.Call):
                resolved = self.ctx.resolve_call(n)
                if resolved in _SAVEZ_CALLS:
                    for kw in n.keywords:
                        if kw.arg and _VERSION_KEY_RE.search(kw.arg):
                            self.version_writes.append((kw.arg, n))
            if isinstance(n, ast.Subscript) \
                    and isinstance(n.slice, ast.Constant) \
                    and isinstance(n.slice.value, str):
                self.read_keys.add(n.slice.value)
            elif isinstance(n, ast.Call) \
                    and isinstance(n.func, ast.Attribute) \
                    and n.func.attr == "get" and n.args \
                    and isinstance(n.args[0], ast.Constant) \
                    and isinstance(n.args[0].value, str):
                self.read_keys.add(n.args[0].value)
            elif isinstance(n, ast.Compare) \
                    and all(isinstance(op, (ast.In, ast.NotIn))
                            for op in n.ops) \
                    and isinstance(n.left, ast.Constant) \
                    and isinstance(n.left.value, str):
                self.read_keys.add(n.left.value)

    # ------------------------------------------------------------- queries
    def resolve_path(self, expr: ast.AST, scope: Scope,
                     depth: int = 0) -> ast.AST:
        """Follow a Name through single in-scope assignments (3 hops)."""
        while isinstance(expr, ast.Name) and depth < 3 \
                and expr.id in scope.assignments:
            expr = scope.assignments[expr.id]
            depth += 1
        return expr

    def write_is_staged(self, w: WriteSite, scope: Scope) -> bool:
        """Is this write covered by the staged-temp + rename protocol?

        Covered when the write target shares an identity token with some
        replace SOURCE in the same scope, or when the (assignment-
        resolved) target spelling carries a staging suffix."""
        wt = _path_tokens(w.target)
        for r in scope.replaces:
            if r.src is not None and (_path_tokens(r.src) & wt):
                return True
        resolved = self.resolve_path(w.target, scope)
        spelled = _safe_unparse(resolved) + " " + _safe_unparse(w.target)
        return bool(_STAGED_SPELLING_RE.search(spelled))

    def dir_identity(self, expr: ast.AST,
                     scope: Scope) -> Optional[Tuple[str, str]]:
        """(kind, identity) of the directory containing `expr`, or None.

        kinds: "tempfile" (resolved through the tempfile module),
        "join" (os.path.join(d, ...) -> identity of d), "sibling"
        (path + suffix / dirname-of-variable -> identity dir(<path>)),
        "const" (literal string). JXD302 only compares identities of the
        SAME kind — mixed derivations are incomparable, not findings."""
        expr = self.resolve_path(expr, scope)
        if isinstance(expr, ast.Call):
            r = self.ctx.resolve_call(expr)
            if r and r.startswith("tempfile."):
                return ("tempfile", r)
            if r in ("os.path.join", "posixpath.join", "ntpath.join") \
                    and expr.args:
                d = self.resolve_path(expr.args[0], scope)
                if isinstance(d, ast.Call):
                    rd = self.ctx.resolve_call(d)
                    if rd and rd.startswith("tempfile."):
                        return ("tempfile", rd)
                if isinstance(d, (ast.Name, ast.Attribute)):
                    return ("join", _safe_unparse(d))
                if isinstance(d, ast.Constant) and isinstance(d.value, str):
                    return ("join", repr(d.value))
                return None
            return None
        if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
            left = expr.left
            while isinstance(left, ast.BinOp) \
                    and isinstance(left.op, ast.Add):
                left = left.left
            inner = self.dir_identity(left, scope)
            if inner is not None:
                # path + ".tmp" is a SIBLING of path: same directory
                return inner
            left = self.resolve_path(left, scope)
            if isinstance(left, (ast.Name, ast.Attribute)):
                return ("sibling", f"dir({_safe_unparse(left)})")
            if isinstance(left, ast.Call):
                return ("sibling", f"dir({_safe_unparse(left)})")
            return None
        if isinstance(expr, (ast.Name, ast.Attribute)):
            return ("sibling", f"dir({_safe_unparse(expr)})")
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            head = expr.value.rsplit("/", 1)[0] if "/" in expr.value else "."
            return ("const", head)
        return None

    def enclosing_functions(self, node: ast.AST) -> List[ast.AST]:
        """Innermost-to-outermost FunctionDef chain containing `node`."""
        chain: List[ast.AST] = []
        p = self._parents.get(id(node))
        while p is not None:
            if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef)):
                chain.append(p)
            p = self._parents.get(id(p))
        return chain

    def point_covered(self, node: ast.AST) -> bool:
        """Does any lexically enclosing function (including its nested
        defs) call faults.point? Module-level sites check the whole
        module."""
        point_ids = {id(c) for c, _ in self.point_calls}
        chain = self.enclosing_functions(node)
        roots = chain if chain else [self.ctx.tree]
        for root in roots:
            for n in ast.walk(root):
                if id(n) in point_ids:
                    return True
        return False
