"""Entry point: `python -m tpusvm.analysis [paths...]`."""

import sys

from tpusvm.analysis.cli import main

if __name__ == "__main__":
    sys.exit(main())
