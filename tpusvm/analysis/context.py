"""Per-module AST analysis context shared by every lint rule.

One ModuleContext is built per file; it resolves import aliases to
canonical dotted names, discovers which functions are JAX-traced (jit /
pmap decorators, `jax.jit(f)` wrapping, lax control-flow combinator
bodies, and functions nested inside any of those), and infers which names
inside each traced function hold tracers — the seed for rules JX001-JX006.

The taint model is deliberately a lexical over/under-approximation tuned
for a low false-positive rate on this repo, not a type checker:

  * parameters of a traced function are tracers unless listed in the
    jit decorator's static_argnames/static_argnums;
  * names assigned from expressions that involve a tracer, or from calls
    into array namespaces (jax.numpy, jax.lax, ...), become tracers;
  * `.shape` / `.ndim` / `.dtype` / `.size` attribute reads, `len()`,
    `isinstance()` and `is`/`is not` comparisons are static under
    tracing and never taint.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set

from tpusvm.analysis.core import is_kernel_path

# call results from these namespaces are traced arrays inside a traced fn
ARRAY_NAMESPACES = (
    "jax.numpy.",
    "jax.lax.",
    "jax.nn.",
    "jax.scipy.",
    "jax.random.",
    "jax.image.",
)

# decorators / wrappers that make a function a tracing entry point
TRACING_WRAPPERS = {
    "jax.jit",
    "jax.pmap",
    "jax.vmap",
    "jax.pjit",
    "jax.experimental.pjit.pjit",
}

# lax combinators whose function-valued arguments are traced; every
# Lambda or locally-defined function passed to one is marked (position
# conventions vary per combinator, so argument slots are not tracked)
LAX_COMBINATORS = {
    "jax.lax.scan",
    "jax.lax.while_loop",
    "jax.lax.fori_loop",
    "jax.lax.cond",
    "jax.lax.switch",
    "jax.lax.map",
    "jax.lax.associative_scan",
    "jax.experimental.shard_map.shard_map",
    "jax.checkpoint",
    "jax.remat",
}

# attribute reads that are STATIC under tracing (never taint)
STATIC_ATTRS = frozenset(
    {"shape", "ndim", "dtype", "size", "itemsize", "nbytes", "weak_type",
     "sharding", "aval", "__name__"}
)

# calls whose results are static/host values regardless of arguments
STATIC_CALLS = frozenset(
    {"len", "isinstance", "hasattr", "callable", "type", "id", "repr",
     "str", "format", "getattr"}
)


@dataclasses.dataclass
class TracedFunction:
    """A function whose body executes under JAX tracing."""

    node: ast.AST                 # FunctionDef | AsyncFunctionDef | Lambda
    name: str
    reason: str                   # human-readable: how tracing was detected
    static_names: Set[str]
    tracer_names: Set[str] = dataclasses.field(default_factory=set)
    own_nodes: List[ast.AST] = dataclasses.field(default_factory=list)
    parent: Optional["TracedFunction"] = None

    @property
    def lineno(self) -> int:
        return getattr(self.node, "lineno", 1)


def _own_nodes(fn_node: ast.AST) -> List[ast.AST]:
    """Descendants of a function, stopping at nested function boundaries.

    Nested functions are traced entries of their own, so their bodies are
    excluded here to keep every node owned by exactly one traced function.
    """
    out: List[ast.AST] = []
    stack = list(ast.iter_child_nodes(fn_node))
    while stack:
        node = stack.pop()
        out.append(node)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))
    return out


def _param_names(fn_node: ast.AST) -> List[str]:
    a = fn_node.args
    params = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        params.append(a.vararg.arg)
    if a.kwarg:
        params.append(a.kwarg.arg)
    return params


class ModuleContext:
    """Everything the rules need to know about one source file."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.tree = ast.parse(source)
        self.lines = source.splitlines()
        self.aliases = self._collect_aliases()
        self.kernel_path = is_kernel_path(path, source)
        # name -> every FunctionDef with that name, in source order; a
        # reference like `lax.while_loop(cond, body, ...)` resolves to the
        # NEAREST PRECEDING definition, so same-named bodies in different
        # functions (e.g. the inner and outer `body` of a two-level
        # solver) each bind to their own combinator call
        self.functions: Dict[str, List[ast.AST]] = {}
        # module-level `NAME = ("a", "b", ...)` string-tuple constants:
        # solvers share one static_argnames tuple between their jit
        # decorator and the compile observatory's wrapper, so the
        # decorator references a Name rather than a literal —
        # _static_names resolves it here
        self.module_str_tuples: Dict[str, Set[str]] = {}
        for n in self.tree.body:
            if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                    and isinstance(n.targets[0], ast.Name) \
                    and isinstance(n.value, (ast.Tuple, ast.List)) \
                    and all(isinstance(e, ast.Constant)
                            and isinstance(e.value, str)
                            for e in n.value.elts):
                self.module_str_tuples[n.targets[0].id] = {
                    e.value for e in n.value.elts
                }
        for n in ast.walk(self.tree):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions.setdefault(n.name, []).append(n)
        for defs in self.functions.values():
            defs.sort(key=lambda d: d.lineno)
        self.traced_functions: List[TracedFunction] = []
        self._discover_traced()
        self._infer_tracers()
        self.traced_node_ids: Set[int] = set()
        for fn in self.traced_functions:
            self.traced_node_ids.add(id(fn.node))
            self.traced_node_ids.update(id(n) for n in fn.own_nodes)

    # ---------------------------------------------------------------- alias
    def _collect_aliases(self) -> Dict[str, str]:
        aliases: Dict[str, str] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        aliases[a.asname] = a.name
                    else:
                        # `import jax.numpy` binds `jax`; attribute chains
                        # resolve naturally from the root name
                        root = a.name.split(".", 1)[0]
                        aliases[root] = root
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    if a.name == "*":
                        continue
                    aliases[a.asname or a.name] = f"{node.module}.{a.name}"
        return aliases

    def resolve(self, node: ast.AST) -> Optional[str]:
        """Canonical dotted name of a Name/Attribute chain, via aliases."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if isinstance(node, ast.Name):
            parts.append(self.aliases.get(node.id, node.id))
            return ".".join(reversed(parts))
        return None

    def resolve_call(self, call: ast.Call) -> Optional[str]:
        return self.resolve(call.func)

    # ------------------------------------------------------------ discovery
    def _jit_decorator_statics(self, fn: ast.AST):
        """(is_traced, reason, static_names) from a function's decorators."""
        for dec in getattr(fn, "decorator_list", []):
            target, call = dec, None
            if isinstance(dec, ast.Call):
                call = dec
                target = dec.func
                resolved = self.resolve(target)
                # functools.partial(jax.jit, static_argnames=...)
                if resolved == "functools.partial" and dec.args:
                    inner = self.resolve(dec.args[0])
                    if inner in TRACING_WRAPPERS:
                        return True, f"@partial({inner}, ...)", \
                            self._static_names(call, fn)
            resolved = self.resolve(target)
            if resolved in TRACING_WRAPPERS:
                reason = f"@{resolved}"
                statics = self._static_names(call, fn) if call else set()
                return True, reason, statics
        return False, "", set()

    def _static_names(self, call: ast.Call, fn: ast.AST) -> Set[str]:
        statics: Set[str] = set()
        params = _param_names(fn)
        for kw in call.keywords:
            if kw.arg == "static_argnames":
                v = kw.value
                if isinstance(v, ast.Constant) and isinstance(v.value, str):
                    statics.add(v.value)
                elif isinstance(v, (ast.Tuple, ast.List)):
                    statics |= {e.value for e in v.elts
                                if isinstance(e, ast.Constant)
                                and isinstance(e.value, str)}
                elif isinstance(v, ast.Name):
                    # static_argnames=_SOLVER_STATIC — a module-level
                    # string-tuple constant shared with other consumers
                    statics |= self.module_str_tuples.get(v.id, set())
            elif kw.arg == "static_argnums":
                v = kw.value
                nums = []
                if isinstance(v, ast.Constant) and isinstance(v.value, int):
                    nums = [v.value]
                elif isinstance(v, (ast.Tuple, ast.List)):
                    nums = [e.value for e in v.elts
                            if isinstance(e, ast.Constant)
                            and isinstance(e.value, int)]
                statics |= {params[i] for i in nums if 0 <= i < len(params)}
        return statics

    def _mark(self, node: ast.AST, reason: str, statics: Set[str],
              marked: Dict[int, TracedFunction]) -> None:
        if id(node) in marked:
            return
        name = getattr(node, "name", "<lambda>")
        marked[id(node)] = TracedFunction(
            node=node, name=name, reason=reason, static_names=set(statics)
        )

    def _discover_traced(self) -> None:
        marked: Dict[int, TracedFunction] = {}

        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                traced, reason, statics = self._jit_decorator_statics(node)
                if traced:
                    self._mark(node, reason, statics, marked)
            elif isinstance(node, ast.Call):
                resolved = self.resolve_call(node)
                if resolved in TRACING_WRAPPERS:
                    # jax.jit(f) / jax.jit(lambda ...: ...)
                    for arg in node.args[:1]:
                        fn = self._as_function(arg, node.lineno)
                        if fn is not None:
                            self._mark(fn, f"{resolved}(...)",
                                       self._call_statics(node, fn), marked)
                elif resolved in LAX_COMBINATORS:
                    for arg in list(node.args) + [k.value
                                                  for k in node.keywords]:
                        fn = self._as_function(arg, node.lineno)
                        if fn is not None:
                            self._mark(fn, f"{resolved} body", set(), marked)

        # nested functions inside a traced function are traced too; walk
        # top-down so parents are marked before children
        roots = list(marked.values())
        for tf in roots:
            for sub in ast.walk(tf.node):
                if sub is tf.node:
                    continue
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                    ast.Lambda)):
                    self._mark(sub, f"nested in traced {tf.name!r}", set(),
                               marked)

        # parent links (lexical nesting among traced functions)
        by_id = marked
        for tf in by_id.values():
            for sub in ast.walk(tf.node):
                if sub is tf.node:
                    continue
                child = by_id.get(id(sub))
                if child is not None and child.parent is None:
                    child.parent = tf

        for tf in by_id.values():
            tf.own_nodes = _own_nodes(tf.node)
        # outer-before-inner so taint inference can seed children from
        # parents
        self.traced_functions = sorted(
            by_id.values(), key=lambda t: (t.lineno, _depth(t))
        )

    def _call_statics(self, call: ast.Call, fn: ast.AST) -> Set[str]:
        try:
            return self._static_names(call, fn)
        except Exception:
            return set()

    def _as_function(self, arg: ast.AST,
                     at_line: int) -> Optional[ast.AST]:
        if isinstance(arg, ast.Lambda):
            return arg
        if isinstance(arg, ast.Name):
            defs = self.functions.get(arg.id, [])
            preceding = [d for d in defs if d.lineno <= at_line]
            if preceding:
                return preceding[-1]
            return defs[0] if defs else None
        return None

    # ---------------------------------------------------------------- taint
    def _infer_tracers(self) -> None:
        for tf in self.traced_functions:
            tracers: Set[str] = set()
            if tf.parent is not None:
                # closed-over tracers from the enclosing traced function
                tracers |= tf.parent.tracer_names
            tracers |= {p for p in _param_names(tf.node)}
            tracers -= tf.static_names
            # fixed point over this function's own assignments
            for _ in range(10):
                before = len(tracers)
                for node in tf.own_nodes:
                    if isinstance(node, ast.Assign):
                        if self.expr_taints(node.value, tracers):
                            for t in node.targets:
                                tracers |= _target_names(t)
                    elif isinstance(node, ast.AugAssign):
                        if self.expr_taints(node.value, tracers):
                            tracers |= _target_names(node.target)
                    elif isinstance(node, ast.AnnAssign) and node.value:
                        if self.expr_taints(node.value, tracers):
                            tracers |= _target_names(node.target)
                    elif isinstance(node, ast.NamedExpr):
                        if self.expr_taints(node.value, tracers):
                            tracers |= _target_names(node.target)
                    elif isinstance(node, ast.For):
                        if self.expr_taints(node.iter, tracers):
                            tracers |= _target_names(node.target)
                if len(tracers) == before:
                    break
            tf.tracer_names = tracers

    def expr_taints(self, node: ast.AST, tracers: Set[str],
                    test_position: bool = False) -> bool:
        """Does evaluating `node` involve a traced value?

        test_position=True applies the extra exemptions that make a
        BRANCH on the value legal under tracing (`is`/`is not`
        comparisons, isinstance, membership tests against literal
        tuples of constants).
        """
        if node is None:
            return False
        if isinstance(node, ast.Name):
            return node.id in tracers
        if isinstance(node, ast.Constant):
            return False
        if isinstance(node, ast.Attribute):
            if node.attr in STATIC_ATTRS:
                return False
            return self.expr_taints(node.value, tracers, test_position)
        if isinstance(node, ast.Subscript):
            # x[i] carries x's taint; a host container indexed by a tracer
            # is a different bug class (concretization) left to runtime
            return self.expr_taints(node.value, tracers, test_position)
        if isinstance(node, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
                # `x is None` on a tracer-or-None parameter is a static
                # trace-time branch, never a traced-value branch
                return False
            if test_position and all(
                isinstance(op, (ast.In, ast.NotIn)) for op in node.ops
            ) and all(_is_const_container(c) for c in node.comparators):
                # `mode in ("a", "b")` — membership against literal
                # constants is (almost always) a static-config check
                return False
            return any(
                self.expr_taints(c, tracers, test_position)
                for c in [node.left] + node.comparators
            )
        if isinstance(node, ast.Call):
            resolved = self.resolve_call(node)
            if resolved in STATIC_CALLS:
                return False
            if resolved and resolved.startswith(ARRAY_NAMESPACES):
                return True
            children = [node.func] + list(node.args) + \
                [kw.value for kw in node.keywords]
            if isinstance(node.func, ast.Name):
                # plain helper call: taints iff its arguments do
                children = list(node.args) + \
                    [kw.value for kw in node.keywords]
            return any(self.expr_taints(c, tracers, test_position)
                       for c in children)
        if isinstance(node, ast.Lambda):
            return False
        # generic structural recursion (BoolOp, BinOp, UnaryOp, IfExp,
        # Tuple, List, Dict, Starred, comprehensions, f-strings, ...)
        return any(
            self.expr_taints(child, tracers, test_position)
            for child in ast.iter_child_nodes(node)
        )

    # ------------------------------------------------------------- queries
    def host_nodes(self) -> List[ast.AST]:
        """Module nodes NOT owned by any traced function."""
        return [n for n in ast.walk(self.tree)
                if id(n) not in self.traced_node_ids]


def _depth(tf: TracedFunction) -> int:
    d, cur = 0, tf.parent
    while cur is not None:
        d, cur = d + 1, cur.parent
    return d


def _target_names(target: ast.AST) -> Set[str]:
    names: Set[str] = set()
    for node in ast.walk(target):
        if isinstance(node, ast.Name):
            names.add(node.id)
    return names


def _is_const_container(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        return all(isinstance(e, ast.Constant) for e in node.elts)
    return False
