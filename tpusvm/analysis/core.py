"""Core data model of the tpusvm static analyzer.

A Finding is one rule violation at one source location; this module also
owns the cross-cutting source-comment conventions (suppressions and the
kernel-path pragma) and file discovery, so rules and the CLI share one
definition of each.

Comment conventions (documented in README "Static analysis"):

  # tpusvm: disable=JX001            suppress on this line (or the line
                                     directly below, when the comment
                                     stands alone)
  # tpusvm: disable=JX001,JX004      several rules
  # tpusvm: disable=all              every rule on this line
  # tpusvm: disable-file=JX002       suppress a rule for the whole file
  # tpusvm: kernel-path              treat this file as a kernel path
                                     (ops/solver) for path-scoped rules
  # tpusvm: guarded-by=<invariant>   concurrency-linter suppression that
                                     DOCUMENTS the guarding invariant
                                     (e.g. "one-way latch; bool store is
                                     GIL-atomic") — suppresses JXC rules
                                     on the line (or the line below when
                                     the comment stands alone); empty
                                     invariant text is rejected
  # tpusvm: durable-by=<invariant>   durability-auditor suppression that
                                     DOCUMENTS the crash-safety invariant
                                     (e.g. "rotation: source survives a
                                     torn rename; reader rejects torn
                                     tails") — suppresses JXD rules the
                                     same way; empty invariant text is
                                     rejected
  # tpusvm: durable-protocol         opt a file into the durable-module
                                     rules (JXD303); `=kill-safe` also
                                     claims kill-safety (JXD306)
"""

from __future__ import annotations

import dataclasses
import hashlib
import re
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Set

# path prefixes (posix, repo-relative) whose files are "kernel paths" for
# the path-scoped rules (JX004 outside traced code, JX007)
KERNEL_PATH_PARTS = ("tpusvm/ops", "tpusvm/solver")

# directories never descended into during discovery: the known-bad lint
# corpus (it exists to FAIL the rules), caches, committed results, and the
# non-Python native tree
DEFAULT_EXCLUDE_DIRS = frozenset(
    {"analysis_corpus", "__pycache__", ".git", "results", "native",
     ".github"}
)

_DISABLE_RE = re.compile(r"#\s*tpusvm:\s*disable=([A-Za-z0-9_,\s]+)")
_GUARDED_BY_RE = re.compile(r"#\s*tpusvm:\s*guarded-by=(.*)$")
_DURABLE_BY_RE = re.compile(r"#\s*tpusvm:\s*durable-by=(.*)$")
_DISABLE_FILE_RE = re.compile(r"#\s*tpusvm:\s*disable-file=([A-Za-z0-9_,\s]+)")
_KERNEL_PRAGMA_RE = re.compile(r"#\s*tpusvm:\s*kernel-path\b")
_COMMENT_ONLY_RE = re.compile(r"^\s*#")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at one source location (1-based line/col)."""

    rule: str
    path: str
    line: int
    col: int
    message: str
    snippet: str = ""
    fingerprint: str = ""

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"


def fingerprint_findings(findings: List[Finding]) -> List[Finding]:
    """Attach stable fingerprints: hash of (rule, path, snippet, occurrence).

    Line numbers are deliberately excluded so a checked-in baseline
    survives unrelated edits above the finding; the occurrence index
    disambiguates identical snippets within one file.
    """
    seen: Dict[str, int] = {}
    out = []
    for f in findings:
        key = f"{f.rule}|{f.path}|{f.snippet.strip()}"
        occ = seen.get(key, 0)
        seen[key] = occ + 1
        digest = hashlib.sha1(f"{key}|{occ}".encode()).hexdigest()[:12]
        out.append(dataclasses.replace(f, fingerprint=digest))
    return out


def snippet_at(lines: List[str], lineno: int) -> str:
    """The stripped source line at 1-based `lineno` ('' when out of range)."""
    if 0 < lineno <= len(lines):
        return lines[lineno - 1].strip()
    return ""


def _parse_rule_list(raw: str) -> Set[str]:
    return {tok.strip().upper() for tok in raw.split(",") if tok.strip()}


def file_suppressions(lines: List[str]) -> Set[str]:
    """Rule ids disabled for the whole file via `# tpusvm: disable-file=`."""
    rules: Set[str] = set()
    for ln in lines:
        m = _DISABLE_FILE_RE.search(ln)
        if m:
            rules |= _parse_rule_list(m.group(1))
    return rules


def line_suppressions(lines: List[str], lineno: int) -> Set[str]:
    """Rule ids disabled for 1-based line `lineno`.

    A trailing comment on the line itself wins; a comment-ONLY line
    directly above also applies (for statements too long to annotate
    inline).
    """
    rules: Set[str] = set()
    for idx in (lineno - 1, lineno - 2):
        if not (0 <= idx < len(lines)):
            continue
        m = _DISABLE_RE.search(lines[idx])
        if m and (idx == lineno - 1 or _COMMENT_ONLY_RE.match(lines[idx])):
            rules |= _parse_rule_list(m.group(1))
    return rules


def is_suppressed(finding: Finding, lines: List[str],
                  file_rules: Optional[Set[str]] = None) -> bool:
    if file_rules is None:
        file_rules = file_suppressions(lines)
    active = file_rules | line_suppressions(lines, finding.line)
    return finding.rule in active or "ALL" in active


def guarded_by_annotation(lines: List[str], lineno: int) -> Optional[str]:
    """The `# tpusvm: guarded-by=<invariant>` text covering 1-based line
    `lineno`, or None. Placement rules mirror line_suppressions: a
    trailing comment on the line itself, or a comment-only line directly
    above. The invariant text is mandatory — an empty annotation returns
    None, so the finding it meant to suppress stays active (the
    concurrency linter's suppressions must NAME the invariant they rely
    on)."""
    for idx in (lineno - 1, lineno - 2):
        if not (0 <= idx < len(lines)):
            continue
        m = _GUARDED_BY_RE.search(lines[idx])
        if m and (idx == lineno - 1 or _COMMENT_ONLY_RE.match(lines[idx])):
            text = m.group(1).strip()
            if text:
                return text
    return None


def durable_by_annotation(lines: List[str], lineno: int) -> Optional[str]:
    """The `# tpusvm: durable-by=<invariant>` text covering 1-based line
    `lineno`, or None. Same placement and non-empty-text contract as
    guarded_by_annotation: the durability auditor's suppressions must
    NAME the crash-safety invariant they rely on."""
    for idx in (lineno - 1, lineno - 2):
        if not (0 <= idx < len(lines)):
            continue
        m = _DURABLE_BY_RE.search(lines[idx])
        if m and (idx == lineno - 1 or _COMMENT_ONLY_RE.match(lines[idx])):
            text = m.group(1).strip()
            if text:
                return text
    return None


def has_kernel_pragma(source: str) -> bool:
    return bool(_KERNEL_PRAGMA_RE.search(source))


def is_kernel_path(path: str, source: str = "") -> bool:
    posix = Path(path).as_posix()
    if any(part in posix for part in KERNEL_PATH_PARTS):
        return True
    return bool(source) and has_kernel_pragma(source)


def iter_python_files(paths: Iterable[str]) -> List[Path]:
    """Expand files/directories into a sorted, deduped list of .py files."""
    found: Set[Path] = set()
    for raw in paths:
        p = Path(raw)
        if p.is_dir():
            for f in p.rglob("*.py"):
                if not any(part in DEFAULT_EXCLUDE_DIRS for part in f.parts):
                    found.add(f)
        elif p.suffix == ".py":
            # explicit file arguments bypass the exclude list (that is how
            # the corpus self-tests lint their known-bad snippets)
            found.add(p)
    return sorted(found)
