"""CLI for the tpusvm linter: `python -m tpusvm.analysis [paths...]`.

Exit codes: 0 = clean (modulo baseline), 1 = findings, 2 = usage error.
The linter itself imports no JAX — it is pure stdlib `ast` over source
text — so the CI lint job runs without accelerator deps installed.

`python -m tpusvm.analysis ir-audit [...]` dispatches to the jaxpr-level
semantic auditor (tpusvm.analysis.ir — rules JXIR101-106), which DOES
need jax and runs in the CI test job on JAX_PLATFORMS=cpu.

`python -m tpusvm.analysis conc [...]` dispatches to the lock-discipline
linter (tpusvm.analysis.conc — rules JXC201-206, stdlib-only like this
one); `conc-stress [...]` runs its seeded schedule-perturbation race
harness against the real threaded objects (test-job, needs numpy/jax).

`python -m tpusvm.analysis dura [...]` dispatches to the crash-safety &
atomicity auditor (tpusvm.analysis.dura — rules JXD301-306, stdlib-only);
`dura-matrix [...]` runs the derived crash-window matrix: kill windows
enumerated from the static write-protocol model, executed through the
recovery scenarios (test-job, needs numpy/jax).
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from tpusvm.analysis.baseline import (
    DEFAULT_BASELINE_NAME,
    load_baseline,
    write_baseline,
)
from tpusvm.analysis.core import _parse_rule_list
from tpusvm.analysis.lint import lint_paths
from tpusvm.analysis.registry import all_rules
from tpusvm.analysis.report import render_json, render_text

DEFAULT_PATHS = ("tpusvm", "benchmarks", "scripts", "bench.py")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tpusvm.analysis",
        description=("JAX tracing-safety & TPU-hazard linter for the "
                     "tpusvm tree (rules JX001-JX008)"),
    )
    p.add_argument("paths", nargs="*", default=list(DEFAULT_PATHS),
                   help="files/directories to lint "
                        f"(default: {' '.join(DEFAULT_PATHS)})")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--select", default="",
                   help="comma-separated rule ids to run (default: all)")
    p.add_argument("--ignore", default="",
                   help="comma-separated rule ids to skip")
    p.add_argument("--baseline", default=DEFAULT_BASELINE_NAME,
                   help="baseline file of grandfathered findings "
                        f"(default: {DEFAULT_BASELINE_NAME}; missing "
                        "file = empty baseline)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline file entirely")
    p.add_argument("--write-baseline", action="store_true",
                   help="write all current findings to the baseline "
                        "file and exit 0")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule registry and exit")
    return p


def main(argv=None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "ir-audit":
        # the jaxpr-level semantic auditor (rules JXIR101-106) — a
        # separate CLI because it NEEDS jax, while this linter must
        # stay importable/runnable without accelerator deps
        from tpusvm.analysis.ir.cli import main as ir_main

        return ir_main(argv[1:])
    if argv and argv[0] == "conc":
        # the lock-discipline linter (rules JXC201-206) — separate
        # subcommand with its own baseline (.tpusvm-conc-baseline.json);
        # pure stdlib like this linter, so it also runs in the no-jax
        # lint job
        from tpusvm.analysis.conc.cli import main as conc_main

        return conc_main(argv[1:])
    if argv and argv[0] == "conc-stress":
        # the dynamic arm: seeded schedule-perturbation suites over the
        # real threaded objects (imports serve/stream/obs/faults, which
        # pull numpy + jax — test-job territory, like ir-audit)
        from tpusvm.analysis.conc.cli import stress_main

        return stress_main(argv[1:])
    if argv and argv[0] == "dura":
        # the crash-safety & atomicity auditor (rules JXD301-306) —
        # separate subcommand with its own baseline
        # (.tpusvm-dura-baseline.json); pure stdlib, lint-job safe
        from tpusvm.analysis.dura.cli import main as dura_main

        return dura_main(argv[1:])
    if argv and argv[0] == "dura-matrix":
        # the dynamic arm: the machine-derived crash-window matrix —
        # control runs + generated kill plans over the real durable
        # writers (imports stream/solver/serve, so numpy + jax:
        # test-job territory, like conc-stress)
        from tpusvm.analysis.dura.cli import matrix_main

        return matrix_main(argv[1:])

    args = build_parser().parse_args(argv)

    if args.list_rules:
        for rid, rule in all_rules().items():
            print(f"{rid}  {rule.summary}")
        # the IR rules live in tpusvm.analysis.ir (run via the
        # `ir-audit` subcommand); listing them here needs no jax
        from tpusvm.analysis.ir.rules import IR_RULE_SUMMARIES

        for rid, summary in sorted(IR_RULE_SUMMARIES.items()):
            print(f"{rid}  {summary}  [ir-audit]")
        # likewise the lock-discipline rules (the `conc` subcommand)
        from tpusvm.analysis.conc.rules import CONC_RULE_SUMMARIES

        for rid, summary in sorted(CONC_RULE_SUMMARIES.items()):
            print(f"{rid}  {summary}  [conc]")
        # and the durability rules (the `dura` subcommand)
        from tpusvm.analysis.dura.rules import DURA_RULE_SUMMARIES

        for rid, summary in sorted(DURA_RULE_SUMMARIES.items()):
            print(f"{rid}  {summary}  [dura]")
        return 0

    select = _parse_rule_list(args.select) or None
    ignore = _parse_rule_list(args.ignore) or None
    baseline = None
    if not args.no_baseline and not args.write_baseline:
        try:
            baseline = load_baseline(args.baseline) or None
        except ValueError as e:
            print(f"tpusvm-lint: {e}", file=sys.stderr)
            return 2

    missing = [p for p in args.paths if not Path(p).exists()]
    if missing:
        print(f"tpusvm-lint: no such path(s): {', '.join(missing)}",
              file=sys.stderr)
        return 2

    try:
        result = lint_paths(args.paths, select=select, ignore=ignore,
                            baseline=baseline)
    except ValueError as e:  # unknown rule ids in --select/--ignore
        print(f"tpusvm-lint: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        write_baseline(args.baseline, result.findings)
        print(f"tpusvm-lint: wrote {len(result.findings)} finding(s) to "
              f"{args.baseline}")
        return 0

    print(render_json(result) if args.format == "json"
          else render_text(result))
    return result.exit_code
