"""JX008 — pallas_* solver flags that the resolved config would ignore.

blocked_smo_solve's pallas_* kwargs configure the Pallas inner engine;
before round 6 an active flag combined with a non-pallas engine was
SILENTLY ignored, so an A/B run could record `eta_exclude=true` while
measuring the plain XLA engine (ADVICE r5). The solver now raises at
trace time; this rule catches the same class STATICALLY at call sites
where the conflict is visible as literals — before any hardware is
burned on a mislabeled run.

The flag-compatibility table is tpusvm.config.PALLAS_FLAG_RULES — one
source of truth shared with the solver's runtime validation, so a new
pallas_* flag added there is linted here for free.
"""

from __future__ import annotations

import ast

from tpusvm.analysis.core import Finding, snippet_at
from tpusvm.analysis.registry import Rule, register
from tpusvm.config import PALLAS_FLAG_RULES, pallas_flag_errors

_TARGET = "blocked_smo_solve"


def _const(call: ast.Call, kwarg: str):
    """(present, constant_value_or_None) for a literal keyword argument."""
    for kw in call.keywords:
        if kw.arg == kwarg:
            if isinstance(kw.value, ast.Constant):
                return True, kw.value.value
            return True, None
    return False, None


@register
class PallasFlagCompat(Rule):
    id = "JX008"
    summary = ("active pallas_* flag at a call site whose literal "
               "inner/wss config cannot honour it (flag-compatibility "
               "table: tpusvm.config.PALLAS_FLAG_RULES)")

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            resolved = ctx.resolve_call(node)
            if not resolved or not (
                resolved == _TARGET or resolved.endswith("." + _TARGET)
            ):
                continue
            flags = {}
            for name in PALLAS_FLAG_RULES:
                present, value = _const(node, name)
                # only literal values can be judged statically; a flag
                # fed from a variable is the runtime validation's job
                if present and value is not None:
                    flags[name] = value
            if not flags:
                continue
            has_star_kwargs = any(kw.arg is None for kw in node.keywords)
            _, inner = _const(node, "inner")
            wss_present, wss = _const(node, "wss")
            if not isinstance(wss, int):
                # an omitted wss is the statically-known default (1) —
                # unless a **kwargs expansion could be supplying it
                wss = 1 if not wss_present and not has_star_kwargs else None
            # inner unspecified/non-literal means 'auto' MAY resolve to
            # pallas — no static verdict; only literal conflicts fire
            for err in pallas_flag_errors(
                inner if isinstance(inner, str) else None, wss, flags,
            ):
                yield Finding(
                    rule=self.id, path=ctx.path, line=node.lineno,
                    col=node.col_offset + 1,
                    message=(f"{err} — blocked_smo_solve raises on this "
                             "combination at trace time"),
                    snippet=snippet_at(ctx.lines, node.lineno),
                )
