"""JX010 — raw contraction outside the kernel modules (the AST half of
the IR auditor's JXIR101).

Every matmul-shaped operation in this repo must route through
tpusvm/ops/ or tpusvm/kernels/ (matmul_p / coef_matvec / the dispatch
layer), where tpusvm.config.resolve_matmul_precision attaches an
explicit precision to the emitted dot_general. A bare `K @ coef`,
`jnp.dot`, `jnp.einsum` or `lax.dot_general` elsewhere carries jax's
DEFAULT precision — raw single-pass bf16 on TPU MXUs, ~1e-2 absolute
error on unit-scale Gram entries, enough to break SV-set parity with
the f64 oracle. JXIR101 catches the hazard in the traced jaxpr at audit
time; this rule catches it in review, before the trace exists.

Scope: `jnp.*`/`lax.*` contraction CALLS are flagged anywhere in a
non-exempt file (they are unambiguously JAX); the `@` OPERATOR is
flagged only inside traced functions, where operands are tracers —
host-side NumPy linear algebra (the f64 oracle, dataset synthesis,
bench assertions) legitimately uses `@` and is none of this rule's
business.
"""

from __future__ import annotations

import ast
from pathlib import Path

from tpusvm.analysis.core import Finding, snippet_at
from tpusvm.analysis.registry import Rule, register

# the modules allowed to emit contractions: they own the precision
# routing. NOT core.KERNEL_PATH_PARTS — tpusvm/solver is a kernel path
# for dtype/debug rules but must still route its matmuls through ops.
_CONTRACTION_HOME_PARTS = ("tpusvm/ops", "tpusvm/kernels")

_CONTRACTION_CALLS = {
    "jax.numpy.dot",
    "jax.numpy.matmul",
    "jax.numpy.einsum",
    "jax.numpy.vdot",
    "jax.numpy.inner",
    "jax.numpy.tensordot",
    "jax.lax.dot",
    "jax.lax.dot_general",
    "jax.lax.batch_matmul",
}

_ADVICE = ("route it through tpusvm.kernels dispatch or "
           "tpusvm.ops.rbf.matmul_p/coef_matvec so the resolved "
           "precision rung reaches the emitted dot_general (jax's "
           "default = raw single-pass bf16 on TPU MXUs)")


def _is_exempt(path: str) -> bool:
    posix = Path(path).as_posix()
    return any(part in posix for part in _CONTRACTION_HOME_PARTS)


@register
class RawContraction(Rule):
    id = "JX010"
    summary = ("raw @ / jnp.dot / jnp.einsum / lax.dot_general outside "
               "tpusvm/ops and tpusvm/kernels (contraction precision "
               "never resolved — raw bf16 on TPU)")

    def check(self, ctx):
        if _is_exempt(ctx.path):
            return
        # matmul CALLS: unambiguous jax namespaces, flagged module-wide
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                resolved = ctx.resolve_call(node)
                if resolved in _CONTRACTION_CALLS:
                    yield Finding(
                        rule=self.id, path=ctx.path, line=node.lineno,
                        col=node.col_offset + 1,
                        message=(f"`{resolved}` outside the contraction "
                                 f"home modules — {_ADVICE}"),
                        snippet=snippet_at(ctx.lines, node.lineno),
                    )
        # the @ operator: only where operands are traced arrays
        for tf in ctx.traced_functions:
            for node in tf.own_nodes:
                if (isinstance(node, ast.BinOp)
                        and isinstance(node.op, ast.MatMult)):
                    yield Finding(
                        rule=self.id, path=ctx.path, line=node.lineno,
                        col=node.col_offset + 1,
                        message=(f"raw `@` matmul inside traced "
                                 f"{tf.name!r} ({tf.reason}) — "
                                 f"{_ADVICE}"),
                        snippet=snippet_at(ctx.lines, node.lineno),
                    )
