"""Rule modules for the tpusvm linter; importing this package registers
every rule with tpusvm.analysis.registry."""

from tpusvm.analysis.rules import (  # noqa: F401
    jx001_tracer_branch,
    jx002_host_sync,
    jx003_dynamic_shape,
    jx004_dtype_drift,
    jx005_closure_capture,
    jx006_global_config,
    jx007_debug_leftover,
    jx008_pallas_flags,
    jx009_loop_callback,
    jx010_raw_contraction,
)
