"""JX003 — data-dependent output shapes under tracing.

XLA requires static shapes: boolean-mask indexing (`x[mask]`), one-arg
`jnp.where(cond)`, `jnp.nonzero` & friends all produce arrays whose SIZE
depends on runtime data, which fails to trace (or forces a host fallback).
The TPU-native replacements are the three-arg `jnp.where(cond, a, b)`,
masked reductions, or the `size=`/fill_value forms of nonzero/unique —
this repo's fixed-capacity SV buffers (tpusvm/parallel/svbuffer.py) exist
precisely because of this constraint.
"""

from __future__ import annotations

import ast

from tpusvm.analysis.core import Finding, snippet_at
from tpusvm.analysis.registry import Rule, register

# one-arg jnp.where is dynamic; with `size=` the *_nonzero family is fine
_DYNAMIC_CALLS = {
    "jax.numpy.nonzero",
    "jax.numpy.flatnonzero",
    "jax.numpy.argwhere",
    "jax.numpy.unique",
    "jax.numpy.compress",
    "jax.numpy.extract",
}


@register
class DynamicShape(Rule):
    id = "JX003"
    summary = ("data-dependent output shape under jit: boolean-mask "
               "indexing, one-arg jnp.where, nonzero/unique without "
               "size=")

    def check(self, ctx):
        for tf in ctx.traced_functions:
            bool_names = self._bool_mask_names(ctx, tf)
            for node in tf.own_nodes:
                if isinstance(node, ast.Call):
                    yield from self._check_call(ctx, tf, node)
                elif isinstance(node, ast.Subscript):
                    yield from self._check_subscript(ctx, tf, node,
                                                    bool_names)

    def _bool_mask_names(self, ctx, tf):
        """Names assigned from comparison expressions (boolean masks)."""
        names = set()
        for node in tf.own_nodes:
            if isinstance(node, ast.Assign) and isinstance(node.value,
                                                           ast.Compare):
                if ctx.expr_taints(node.value, tf.tracer_names):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            names.add(t.id)
        return names

    def _check_call(self, ctx, tf, node):
        resolved = ctx.resolve_call(node)
        kwargs = {kw.arg for kw in node.keywords}
        if (resolved == "jax.numpy.where" and len(node.args) == 1
                and not kwargs & {"x", "y"}):
            yield Finding(
                rule=self.id, path=ctx.path, line=node.lineno,
                col=node.col_offset + 1,
                message=("one-arg jnp.where(cond) returns "
                         "data-dependent-size index arrays and fails "
                         "under jit; use the three-arg form or "
                         "jnp.nonzero(cond, size=...)"),
                snippet=snippet_at(ctx.lines, node.lineno),
            )
        elif resolved in _DYNAMIC_CALLS and "size" not in kwargs:
            short = resolved.replace("jax.numpy.", "jnp.")
            yield Finding(
                rule=self.id, path=ctx.path, line=node.lineno,
                col=node.col_offset + 1,
                message=(f"{short} without size= has a data-dependent "
                         "output shape and fails under jit; pass size= "
                         "(+ fill_value) for a static shape"),
                snippet=snippet_at(ctx.lines, node.lineno),
            )

    def _check_subscript(self, ctx, tf, node, bool_names):
        sl = node.slice
        is_mask = isinstance(sl, ast.Compare) and not all(
            isinstance(op, (ast.Is, ast.IsNot)) for op in sl.ops
        )
        if not is_mask and isinstance(sl, ast.Name):
            is_mask = sl.id in bool_names
        if not is_mask and isinstance(sl, ast.UnaryOp) \
                and isinstance(sl.op, ast.Invert):
            inner = sl.operand
            is_mask = isinstance(inner, ast.Compare) or (
                isinstance(inner, ast.Name) and inner.id in bool_names)
        if is_mask and ctx.expr_taints(node.value, tf.tracer_names):
            yield Finding(
                rule=self.id, path=ctx.path, line=node.lineno,
                col=node.col_offset + 1,
                message=("boolean-mask indexing has a data-dependent "
                         "result shape and fails under jit; use "
                         "jnp.where(mask, x, fill) or masked reductions"),
                snippet=snippet_at(ctx.lines, node.lineno),
            )
