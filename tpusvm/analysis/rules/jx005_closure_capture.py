"""JX005 — jitted function closes over a module-level ndarray.

An ndarray captured by closure is embedded in the jaxpr as a CONSTANT:
it is re-hashed on every dispatch, baked into the executable
(constant-folding bloat at kernel-table sizes), and a rebind of the
module global silently does NOT invalidate the compiled function — three
different bugs from one innocuous-looking capture. Arrays belong in the
function's arguments (donate/device_put as needed).

Only DIRECT tracing entry points (decorated/wrapped jitted functions) are
checked: nested traced functions closing over their parent's tracers is
how lax control flow is written.
"""

from __future__ import annotations

import ast

from tpusvm.analysis.core import Finding, snippet_at
from tpusvm.analysis.registry import Rule, register

_ARRAY_PRODUCERS = ("numpy.", "jax.numpy.", "jax.random.")


@register
class ClosureCapture(Rule):
    id = "JX005"
    summary = ("jitted function closes over a module-level ndarray "
               "(baked into the jaxpr as a constant; pass it as an "
               "argument instead)")

    def check(self, ctx):
        module_arrays = self._module_array_bindings(ctx)
        if not module_arrays:
            return
        for tf in ctx.traced_functions:
            if tf.parent is not None:
                continue  # nested traced fns legitimately capture tracers
            local = self._local_bindings(tf)
            reported = set()
            for node in tf.own_nodes:
                if not (isinstance(node, ast.Name)
                        and isinstance(node.ctx, ast.Load)):
                    continue
                name = node.id
                if (name in local or name in ctx.aliases
                        or name not in module_arrays
                        or name in reported):
                    continue
                reported.add(name)
                yield Finding(
                    rule=self.id, path=ctx.path, line=node.lineno,
                    col=node.col_offset + 1,
                    message=(
                        f"traced function {tf.name!r} ({tf.reason}) "
                        f"closes over module-level ndarray {name!r} "
                        f"(built at line {module_arrays[name]}); the "
                        "array is inlined as a compile-time constant — "
                        "pass it as an argument"
                    ),
                    snippet=snippet_at(ctx.lines, node.lineno),
                )

    def _module_array_bindings(self, ctx):
        """Module-level `NAME = <array-producing call>` bindings."""
        out = {}
        for stmt in ctx.tree.body:
            if not isinstance(stmt, ast.Assign):
                continue
            value = stmt.value
            if not isinstance(value, ast.Call):
                continue
            resolved = ctx.resolve_call(value)
            if resolved and (resolved.startswith(_ARRAY_PRODUCERS)
                             or resolved in ("numpy.load",
                                             "numpy.loadtxt",
                                             "numpy.genfromtxt")):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        out[t.id] = stmt.lineno
        return out

    def _local_bindings(self, tf):
        """Names bound inside the function (params + assignments)."""
        args = tf.node.args
        names = {p.arg for p in
                 args.posonlyargs + args.args + args.kwonlyargs}
        if args.vararg:
            names.add(args.vararg.arg)
        if args.kwarg:
            names.add(args.kwarg.arg)
        for node in tf.own_nodes:
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign,
                                 ast.NamedExpr, ast.For)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    for sub in ast.walk(t):
                        if isinstance(sub, ast.Name):
                            names.add(sub.id)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                names.add(node.name)
            elif isinstance(node, ast.comprehension):
                for sub in ast.walk(node.target):
                    if isinstance(sub, ast.Name):
                        names.add(sub.id)
            elif isinstance(node, ast.withitem) and node.optional_vars:
                for sub in ast.walk(node.optional_vars):
                    if isinstance(sub, ast.Name):
                        names.add(sub.id)
            elif isinstance(node, ast.ExceptHandler) and node.name:
                names.add(node.name)
            elif isinstance(node, (ast.Import, ast.ImportFrom)):
                for a in node.names:
                    names.add((a.asname or a.name).split(".", 1)[0])
        return names
