"""JX006 — mutated module-global read inside a traced function.

A module global that some function rebinds via `global NAME` is a
trace-time constant everywhere it is read under jit: the traced function
captures the value from the FIRST trace, and later mutations silently do
nothing (or worse, leak into some retraces and not others, depending on
cache keys). This is the static twin of the `benchmarks/midscale_parity`
CFG bug (ADVICE r5): config must flow through arguments or static
argnames, not through mutable module state.
"""

from __future__ import annotations

import ast

from tpusvm.analysis.core import Finding, snippet_at
from tpusvm.analysis.registry import Rule, register


@register
class MutatedGlobalConfig(Rule):
    id = "JX006"
    summary = ("module global rebound via `global` is read inside a "
               "traced function (captured once at trace time; thread it "
               "through arguments)")

    def check(self, ctx):
        mutated = self._mutated_globals(ctx)
        if not mutated:
            return
        for tf in ctx.traced_functions:
            reported = set()
            for node in tf.own_nodes:
                if (isinstance(node, ast.Name)
                        and isinstance(node.ctx, ast.Load)
                        and node.id in mutated
                        and node.id not in tf.tracer_names
                        and node.id not in reported):
                    reported.add(node.id)
                    yield Finding(
                        rule=self.id, path=ctx.path, line=node.lineno,
                        col=node.col_offset + 1,
                        message=(
                            f"traced function {tf.name!r} reads module "
                            f"global {node.id!r}, which is rebound via "
                            f"`global` in {mutated[node.id]!r}; the value "
                            "is frozen at first trace and later "
                            "mutations are silently ignored — pass it as "
                            "an argument or static argname"
                        ),
                        snippet=snippet_at(ctx.lines, node.lineno),
                    )

    def _mutated_globals(self, ctx):
        """Names declared `global` AND assigned inside some function."""
        mutated = {}
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            declared = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.Global):
                    declared.update(node.names)
            if not declared:
                continue
            for node in ast.walk(fn):
                targets = []
                if isinstance(node, ast.Assign):
                    targets = node.targets
                elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                    targets = [node.target]
                for t in targets:
                    if isinstance(t, ast.Name) and t.id in declared:
                        mutated.setdefault(t.id, fn.name)
        return mutated
