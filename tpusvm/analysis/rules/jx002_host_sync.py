"""JX002 — implicit host-device synchronisation.

Two variants of the same hazard:

  * inside a traced function: `np.asarray`/`np.array` of a tracer,
    `.item()` / `.tolist()` / `.block_until_ready()` on a tracer, or the
    `float()`/`int()`/`bool()` builtins applied to one — these either
    raise ConcretizationTypeError under jit or, in op-by-op code that
    LOOKS jitted, silently serialize the device pipeline;
  * in host code inside a `for`/`while` loop: `.item()` /
    `.block_until_ready()` calls, each of which stalls the host on the
    device — the classic accidental per-iteration sync that turns an
    async dispatch loop into a round-trip-bound one.

Deliberate synchronisation points (timing barriers in benchmark
harnesses) carry a `# tpusvm: disable=JX002` annotation — the comment IS
the documentation that the sync is intentional.
"""

from __future__ import annotations

import ast

from tpusvm.analysis.core import Finding, snippet_at
from tpusvm.analysis.registry import Rule, register

_HOST_MATERIALIZERS = {"numpy.asarray", "numpy.array", "numpy.copy"}
_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
_CONCRETIZING_BUILTINS = {"float", "int", "bool", "complex"}
_HOT_LOOP_METHODS = {"item", "block_until_ready"}


@register
class HostSync(Rule):
    id = "JX002"
    summary = ("implicit host-device sync: np.asarray/.item()/float() on "
               "a tracer, or per-iteration .item()/.block_until_ready() "
               "in a host hot loop")

    def check(self, ctx):
        yield from self._traced(ctx)
        yield from self._host_loops(ctx)

    def _traced(self, ctx):
        for tf in ctx.traced_functions:
            for node in tf.own_nodes:
                if not isinstance(node, ast.Call):
                    continue
                resolved = ctx.resolve_call(node)
                hit = None
                if resolved in _HOST_MATERIALIZERS and any(
                    ctx.expr_taints(a, tf.tracer_names) for a in node.args
                ):
                    hit = (f"{resolved.split('.')[-1]}() materialises a "
                           "traced value on the host")
                elif (isinstance(node.func, ast.Attribute)
                        and node.func.attr in _SYNC_METHODS
                        and ctx.expr_taints(node.func.value,
                                            tf.tracer_names)):
                    hit = (f".{node.func.attr}() forces a host round-trip "
                           "on a traced value")
                elif (isinstance(node.func, ast.Name)
                        and node.func.id in _CONCRETIZING_BUILTINS
                        and node.func.id not in ctx.aliases
                        and node.args
                        and ctx.expr_taints(node.args[0], tf.tracer_names)):
                    hit = (f"{node.func.id}() concretises a traced value")
                if hit:
                    yield Finding(
                        rule=self.id, path=ctx.path, line=node.lineno,
                        col=node.col_offset + 1,
                        message=(f"{hit} inside traced function "
                                 f"{tf.name!r} ({tf.reason})"),
                        snippet=snippet_at(ctx.lines, node.lineno),
                    )

    def _host_loops(self, ctx):
        # lexical loop ancestry over host-only nodes
        loops = [n for n in ctx.host_nodes()
                 if isinstance(n, (ast.For, ast.While))]
        seen = set()
        for loop in loops:
            for node in ast.walk(loop):
                if id(node) in ctx.traced_node_ids or id(node) in seen:
                    continue
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in _HOT_LOOP_METHODS):
                    seen.add(id(node))
                    yield Finding(
                        rule=self.id, path=ctx.path, line=node.lineno,
                        col=node.col_offset + 1,
                        message=(
                            f".{node.func.attr}() inside a host loop "
                            "synchronises with the device every "
                            "iteration; hoist it out of the loop or "
                            "batch the transfers"
                        ),
                        snippet=snippet_at(ctx.lines, node.lineno),
                    )
