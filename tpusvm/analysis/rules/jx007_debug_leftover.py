"""JX007 — leftover debugging hooks on kernel paths.

`jax.debug.print` / `jax.debug.callback` insert host callbacks into the
compiled program (a device->host round trip per call — catastrophic
inside the solver's while_loop hot path), and `breakpoint()` /
`pdb.set_trace()` hang non-interactive runs outright. Scope: kernel-path
files (tpusvm/ops/, tpusvm/solver/, or the `# tpusvm: kernel-path`
pragma), where these only ever appear as forgotten debugging.
"""

from __future__ import annotations

import ast

from tpusvm.analysis.core import Finding, snippet_at
from tpusvm.analysis.registry import Rule, register

_DEBUG_CALLS = {
    "jax.debug.print",
    "jax.debug.breakpoint",
    "jax.debug.callback",
    "pdb.set_trace",
    "ipdb.set_trace",
}


@register
class DebugLeftover(Rule):
    id = "JX007"
    summary = ("leftover jax.debug.print/breakpoint()/pdb on a kernel "
               "path (host callback in the hot loop)")

    def check(self, ctx):
        if not ctx.kernel_path:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            resolved = ctx.resolve_call(node)
            is_breakpoint = (isinstance(node.func, ast.Name)
                             and node.func.id == "breakpoint"
                             and node.func.id not in ctx.aliases)
            if resolved in _DEBUG_CALLS or is_breakpoint:
                what = "breakpoint()" if is_breakpoint else resolved
                yield Finding(
                    rule=self.id, path=ctx.path, line=node.lineno,
                    col=node.col_offset + 1,
                    message=(
                        f"leftover debug hook {what} on a kernel path; "
                        "it inserts a host round-trip (or hangs "
                        "non-interactive runs) — remove before shipping"
                    ),
                    snippet=snippet_at(ctx.lines, node.lineno),
                )
