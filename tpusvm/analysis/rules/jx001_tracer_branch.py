"""JX001 — Python control flow branching on a traced value.

Inside a jit/pmap/lax-combinator body, a Python `if`/`while` whose test
involves a tracer either raises ConcretizationTypeError or — worse, when
the value happens to be concrete at trace time (a weak-typed constant, a
shape-dependent expression that silently became data-dependent after a
refactor) — bakes ONE branch into the compiled program and recompiles on
every distinct value. The TPU-native fix is lax.cond / lax.select /
jnp.where.
"""

from __future__ import annotations

import ast

from tpusvm.analysis.core import Finding, snippet_at
from tpusvm.analysis.registry import Rule, register


@register
class TracerBranch(Rule):
    id = "JX001"
    summary = ("Python if/while on a traced value inside a jit/scan body "
               "(use lax.cond/lax.select/jnp.where)")

    def check(self, ctx):
        for tf in ctx.traced_functions:
            for node in tf.own_nodes:
                if not isinstance(node, (ast.If, ast.While)):
                    continue
                if ctx.expr_taints(node.test, tf.tracer_names,
                                   test_position=True):
                    kind = "if" if isinstance(node, ast.If) else "while"
                    yield Finding(
                        rule=self.id,
                        path=ctx.path,
                        line=node.lineno,
                        col=node.col_offset + 1,
                        message=(
                            f"Python `{kind}` branches on a traced value "
                            f"inside {tf.name!r} ({tf.reason}); under "
                            "tracing this either raises or freezes one "
                            "branch into the compiled program — use "
                            "lax.cond/lax.select/jnp.where"
                        ),
                        snippet=snippet_at(ctx.lines, node.lineno),
                    )
