"""JX004 — dtype drift from implicit-dtype constructors and bare float
literals.

This codebase runs mixed precision on purpose (f32 features + f64
accumulators, `resolve_accum_dtype`), and flips `jax_enable_x64`
process-globally on first use — so ANY array constructor without an
explicit dtype produces a different dtype depending on WHEN it runs
relative to that flip, and a bare Python float literal materialised as an
array is f32 before the flip and f64 after. The resulting drift is the
exact failure class the round-1 STALLED livelock came from (updates below
f32 resolution). Scope: everywhere inside traced functions, and the whole
file on kernel paths (tpusvm/ops/, tpusvm/solver/, or files carrying the
`# tpusvm: kernel-path` pragma).
"""

from __future__ import annotations

import ast

from tpusvm.analysis.core import Finding, snippet_at
from tpusvm.analysis.registry import Rule, register

# constructor -> 0-based positional index where dtype may be passed
_SHAPE_CONSTRUCTORS = {
    "zeros": 1, "ones": 1, "empty": 1, "full": 2,
    "arange": 3, "linspace": 5, "eye": 3, "identity": 1,
}
_CONTENT_CONSTRUCTORS = {"array": 1, "asarray": 1}
_NAMESPACES = ("jax.numpy.", "numpy.")


def _contains_float_literal(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, float):
            return True
    return False


@register
class DtypeDrift(Rule):
    id = "JX004"
    summary = ("array constructor without explicit dtype= (or a bare "
               "float literal materialised as an array) in a traced "
               "function or kernel path")

    def check(self, ctx):
        if ctx.kernel_path:
            nodes = [(n, "kernel path") for n in ast.walk(ctx.tree)
                     if isinstance(n, ast.Call)]
        else:
            nodes = [(n, f"traced function {tf.name!r}")
                     for tf in ctx.traced_functions
                     for n in tf.own_nodes if isinstance(n, ast.Call)]
        seen = set()
        for node, where in nodes:
            if id(node) in seen:
                continue
            seen.add(id(node))
            finding = self._check_call(ctx, node, where)
            if finding is not None:
                yield finding

    def _check_call(self, ctx, node, where):
        resolved = ctx.resolve_call(node)
        if not resolved or not resolved.startswith(_NAMESPACES):
            return None
        name = resolved.split(".")[-1]
        has_dtype_kw = any(kw.arg == "dtype" for kw in node.keywords)
        if name in _SHAPE_CONSTRUCTORS:
            dtype_pos = _SHAPE_CONSTRUCTORS[name]
            if has_dtype_kw or len(node.args) > dtype_pos:
                return None
            return Finding(
                rule=self.id, path=ctx.path, line=node.lineno,
                col=node.col_offset + 1,
                message=(
                    f"{name}() without an explicit dtype= in {where}: "
                    "the produced dtype depends on the process-global "
                    "jax_enable_x64 flip (resolve_accum_dtype) — pin it"
                ),
                snippet=snippet_at(ctx.lines, node.lineno),
            )
        if name in _CONTENT_CONSTRUCTORS and node.args:
            if has_dtype_kw or len(node.args) > _CONTENT_CONSTRUCTORS[name]:
                return None
            if _contains_float_literal(node.args[0]):
                return Finding(
                    rule=self.id, path=ctx.path, line=node.lineno,
                    col=node.col_offset + 1,
                    message=(
                        f"{name}() over a bare float literal in {where}: "
                        "the literal is f32 before the jax_enable_x64 "
                        "flip and f64 after — pass dtype= explicitly"
                    ),
                    snippet=snippet_at(ctx.lines, node.lineno),
                )
        return None
