"""JX009 — host escape inside a solver inner loop (telemetry must be
carry-resident).

The convergence-telemetry design rule, enforced: inside the body of a
lax LOOP combinator (`lax.while_loop` / `lax.scan` / `lax.fori_loop` /
`lax.map` / `lax.associative_scan`, and anything lexically nested in
one), any host-callback insertion — `jax.debug.print`,
`jax.debug.callback`, `jax.experimental.io_callback`,
`jax.pure_callback`, `jax.experimental.host_callback.call` — or host
materialisation of a tracer (`np.asarray`/`np.array`) schedules a
device->host round trip PER ITERATION of the compiled hot loop. That is
exactly how "just print the gap" observability destroys a solver whose
entire design is zero host syncs until termination. The sanctioned
pattern is the one `blocked_smo_solve(telemetry=T)` uses: write into a
ring carried through the loop state and materialise once at the end,
with the rest of the result.

Scope is deliberately narrower than its siblings so each fires on its
own hazard: JX007 covers debug hooks anywhere in kernel-path FILES;
JX002 covers materialisation in any traced function and syncs in host
loops; JX009 is specifically the per-iteration callback inside a
compiled loop body, in any file. (Overlaps on the same line are
possible in real code — that is two true findings, not a conflict; the
single-hazard corpus snippets keep them separable.)

Legitimate uses of `io_callback` OUTSIDE loop bodies (e.g. a one-shot
checkpoint hook) are not flagged.
"""

from __future__ import annotations

import ast

from tpusvm.analysis.core import Finding, snippet_at
from tpusvm.analysis.registry import Rule, register

_CALLBACK_CALLS = {
    "jax.debug.print",
    "jax.debug.callback",
    "jax.debug.breakpoint",
    "jax.experimental.io_callback",
    "jax.experimental.host_callback.call",
    "jax.experimental.host_callback.id_tap",
    "jax.pure_callback",
}

# loop combinators whose bodies re-execute per iteration (the cond/switch
# combinators are single-shot and excluded: a callback there is JX007's
# business when it matters)
_LOOP_REASONS = (
    "jax.lax.while_loop body",
    "jax.lax.scan body",
    "jax.lax.fori_loop body",
    "jax.lax.map body",
    "jax.lax.associative_scan body",
)

_HOST_MATERIALIZERS = {"numpy.asarray", "numpy.array", "numpy.copy"}


def _in_loop_body(tf) -> bool:
    """True when tf is a loop-combinator body or nested inside one."""
    cur = tf
    while cur is not None:
        if cur.reason in _LOOP_REASONS:
            return True
        cur = cur.parent
    return False


@register
class LoopHostCallback(Rule):
    id = "JX009"
    summary = ("host callback / materialisation inside a lax loop body "
               "(a device->host round trip per iteration; telemetry "
               "must be carry-resident)")

    def check(self, ctx):
        for tf in ctx.traced_functions:
            if not _in_loop_body(tf):
                continue
            for node in tf.own_nodes:
                if not isinstance(node, ast.Call):
                    continue
                resolved = ctx.resolve_call(node)
                hit = None
                if resolved in _CALLBACK_CALLS:
                    hit = (f"{resolved} inserts a host callback into the "
                           "compiled loop")
                elif resolved in _HOST_MATERIALIZERS and any(
                    ctx.expr_taints(a, tf.tracer_names) for a in node.args
                ):
                    hit = (f"{resolved.split('.')[-1]}() materialises loop "
                           "state on the host")
                if hit:
                    yield Finding(
                        rule=self.id, path=ctx.path, line=node.lineno,
                        col=node.col_offset + 1,
                        message=(
                            f"{hit} — one device->host round trip PER "
                            f"ITERATION of {tf.name!r} ({tf.reason}); "
                            "carry the values through the loop state and "
                            "materialise once at the end "
                            "(blocked_smo_solve's telemetry ring is the "
                            "house pattern)"
                        ),
                        snippet=snippet_at(ctx.lines, node.lineno),
                    )
