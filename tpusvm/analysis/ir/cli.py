"""`python -m tpusvm.analysis ir-audit` — the IR auditor's CLI.

Unlike the AST linter (pure stdlib, no accelerator deps), the IR audit
traces real jaxprs and therefore needs jax; CI runs it in the test job
under JAX_PLATFORMS=cpu. Exit codes match the linter: 0 = clean (modulo
baseline), 1 = findings, 2 = usage error.

`--smoke` is the CI gate: full audit + structural assertions (at least
`--min-entries` entry points actually traced, every JXIR rule
registered) + the committed-baseline diff — the committed baseline is
EMPTY, so any finding fails the build.
"""

from __future__ import annotations

import argparse
import os
import sys

from tpusvm.analysis.baseline import load_baseline, write_baseline
from tpusvm.analysis.core import _parse_rule_list
from tpusvm.analysis.ir.audit import (
    DEFAULT_IR_BASELINE_NAME,
    render_audit_json,
    run_ir_audit,
)
from tpusvm.analysis.ir.rules import IR_RULE_SUMMARIES


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tpusvm.analysis ir-audit",
        description=("jaxpr-level semantic auditor for the repo's jit "
                     "entry points (rules JXIR101-JXIR106)"),
    )
    p.add_argument("--format", choices=("text", "json"), default="text",
                   help="stdout report format (json = the audit "
                        "artifact schema)")
    p.add_argument("--json-out", default="",
                   help="also write the audit artifact to this path "
                        "(benchmarks/results/ir_audit_cpu.json is the "
                        "committed instance)")
    p.add_argument("--select", default="",
                   help="comma-separated JXIR rule ids to run")
    p.add_argument("--ignore", default="",
                   help="comma-separated JXIR rule ids to skip")
    p.add_argument("--entry", action="append", default=[],
                   help="audit only this entry point (repeatable)")
    p.add_argument("--baseline", default=DEFAULT_IR_BASELINE_NAME,
                   help="baseline file of grandfathered findings "
                        f"(default: {DEFAULT_IR_BASELINE_NAME}; missing "
                        "file = empty baseline)")
    p.add_argument("--no-baseline", action="store_true")
    p.add_argument("--write-baseline", action="store_true",
                   help="write all current findings to the baseline "
                        "file and exit 0")
    p.add_argument("--list-rules", action="store_true")
    p.add_argument("--list-entries", action="store_true")
    p.add_argument("--smoke", action="store_true",
                   help="CI gate: assert >= --min-entries traced, all "
                        "rules registered, and no non-baselined finding")
    p.add_argument("--min-entries", type=int, default=8,
                   help="--smoke: minimum entry points that must "
                        "actually trace (default 8)")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    if args.list_rules:
        for rid, summary in sorted(IR_RULE_SUMMARIES.items()):
            print(f"{rid}  {summary}")
        return 0
    if args.list_entries:
        from tpusvm.analysis.ir.entrypoints import default_entrypoints

        for e in default_entrypoints():
            sweep = f" sweep={sorted(e.sweep)}" if e.sweep else ""
            print(f"{e.name}  [{e.precision}]{sweep}  {e.description}")
        return 0

    select = _parse_rule_list(args.select) or None
    ignore = _parse_rule_list(args.ignore) or None
    baseline = None
    if not args.no_baseline and not args.write_baseline:
        try:
            baseline = load_baseline(args.baseline) or None
        except ValueError as e:
            print(f"tpusvm-ir-audit: {e}", file=sys.stderr)
            return 2

    try:
        result = run_ir_audit(select=select, ignore=ignore,
                              baseline=baseline,
                              entry_filter=set(args.entry) or None)
    except ValueError as e:
        print(f"tpusvm-ir-audit: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        write_baseline(args.baseline, result.findings)
        print(f"tpusvm-ir-audit: wrote {len(result.findings)} finding(s) "
              f"to {args.baseline}")
        return 0

    if args.json_out:
        tmp = args.json_out + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            fh.write(render_audit_json(result))
        os.replace(tmp, args.json_out)

    if args.format == "json":
        print(render_audit_json(result), end="")
    else:
        from tpusvm.analysis.report import render_text

        print(render_text(result))
        skipped = [e for e in result.entries if not e.traced]
        traced = result.traced_count
        print(f"tpusvm-ir-audit: traced {traced}/{len(result.entries)} "
              "entry point(s)"
              + (f"; skipped: "
                 + "; ".join(f"{e.name} ({e.skip_reason})"
                             for e in skipped) if skipped else ""))

    if args.smoke:
        problems = []
        if result.traced_count < args.min_entries:
            problems.append(
                f"only {result.traced_count} entry point(s) traced "
                f"(smoke floor: {args.min_entries})")
        missing = set(IR_RULE_SUMMARIES) - {
            rid for rid in IR_RULE_SUMMARIES}  # registry self-check
        if missing:  # pragma: no cover — structural invariant
            problems.append(f"rules missing from registry: {missing}")
        if result.findings:
            problems.append(
                f"{len(result.findings)} non-baselined finding(s) — the "
                "committed baseline is empty by design; fix the hazard "
                "or (for a deliberate exception) regenerate the "
                "baseline with --write-baseline and justify it in "
                "review")
        if problems:
            for p in problems:
                print(f"tpusvm-ir-audit --smoke FAILED: {p}",
                      file=sys.stderr)
            return 1
        print(f"tpusvm-ir-audit --smoke ok: {result.traced_count} entry "
              f"points traced, {len(IR_RULE_SUMMARIES)} rules, "
              f"{len(result.baselined)} baselined finding(s)")
        return 0

    return result.exit_code
