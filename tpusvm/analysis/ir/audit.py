"""IR audit driver: trace every registered entry point, run the JXIR
rules, apply the fingerprinted baseline, and render results.

The result object mirrors analysis.lint.LintResult (findings /
suppressed / baselined / files_scanned) so the existing text and JSON
reporters render IR findings unchanged; `render_audit_json` additionally
emits the committed machine-readable artifact
(benchmarks/results/ir_audit_cpu.json): schema-versioned, byte-
deterministic (sorted keys, no timestamps — two runs must produce
identical bytes, tests/test_ir_audit.py::test_audit_is_deterministic).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional, Set, Tuple

from tpusvm.analysis.core import Finding, fingerprint_findings
from tpusvm.analysis.ir.rules import (
    IR_RULE_SUMMARIES,
    TraceAudit,
    select_ir_rules,
)
from tpusvm.analysis.ir.tracing import SkipTrace, eqn_stats, trace_entry

AUDIT_SCHEMA_VERSION = 1
DEFAULT_IR_BASELINE_NAME = ".tpusvm-ir-baseline.json"


@dataclasses.dataclass
class EntryReport:
    """Per-entry-point trace outcome for the audit artifact."""

    name: str
    description: str
    precision: str
    traced: bool
    skip_reason: Optional[str] = None
    swept: Tuple[str, ...] = ()
    stats: Optional[dict] = None


@dataclasses.dataclass
class IRAuditResult:
    findings: List[Finding]
    suppressed: List[Finding]          # always [] — no source to annotate
    baselined: List[Finding]
    entries: List[EntryReport]

    @property
    def files_scanned(self) -> int:    # reporter compatibility: one
        return self.traced_count       # "file" per traced entry point

    @property
    def traced_count(self) -> int:
        return sum(1 for e in self.entries if e.traced)

    @property
    def exit_code(self) -> int:
        return 1 if self.findings else 0


def audit_entry(entry, rules) -> Tuple[List[Finding], EntryReport]:
    """Trace one entry (twice when it declares a sweep) and run rules."""
    report = EntryReport(name=entry.name, description=entry.description,
                         precision=entry.precision, traced=False,
                         swept=tuple(sorted(entry.sweep)))
    try:
        first = {k: v[0] for k, v in entry.sweep.items()}
        fn, args, kwargs = entry.build(**first)
        jaxpr = trace_entry(fn, args, kwargs)
        alt_str = None
        if entry.sweep:
            second = {k: v[1] for k, v in entry.sweep.items()}
            fn2, args2, kwargs2 = entry.build(**second)
            alt_str = str(trace_entry(fn2, args2, kwargs2))
    except SkipTrace as e:
        report.skip_reason = str(e)
        return [], report
    report.traced = True
    report.stats = eqn_stats(jaxpr)
    audit = TraceAudit(entry=entry, jaxpr=jaxpr, jaxpr_str=str(jaxpr),
                       jaxpr_alt_str=alt_str)
    findings: List[Finding] = []
    for rule in rules:
        findings.extend(rule.check(audit))
    return findings, report


def run_ir_audit(entries=None, select: Optional[Set[str]] = None,
                 ignore: Optional[Set[str]] = None,
                 baseline: Optional[Set[Tuple[str, str, str]]] = None,
                 entry_filter: Optional[Set[str]] = None) -> IRAuditResult:
    """Audit `entries` (default: the full registry) under the rules.

    `baseline` is the same (rule, path, fingerprint) key set the AST
    linter grandfathers with (analysis/baseline.py); matching findings
    are reported separately and do not fail the gate.
    """
    if entries is None:
        from tpusvm.analysis.ir.entrypoints import default_entrypoints

        entries = default_entrypoints()
    if entry_filter:
        known = {e.name for e in entries}
        unknown = set(entry_filter) - known
        if unknown:
            raise ValueError(f"unknown entry point(s): {sorted(unknown)}; "
                             f"known: {sorted(known)}")
        entries = [e for e in entries if e.name in entry_filter]
    rules = select_ir_rules(select, ignore)

    all_findings: List[Finding] = []
    reports: List[EntryReport] = []
    for entry in entries:
        findings, report = audit_entry(entry, rules)
        all_findings.extend(findings)
        reports.append(report)

    all_findings.sort(key=lambda f: (f.path, f.rule, f.snippet))
    all_findings = fingerprint_findings(all_findings)
    active, baselined = [], []
    for f in all_findings:
        key = (f.rule, f.path, f.fingerprint)
        if baseline and key in baseline:
            baselined.append(f)
        else:
            active.append(f)
    return IRAuditResult(findings=active, suppressed=[],
                         baselined=baselined, entries=reports)


def render_audit_json(result: IRAuditResult) -> str:
    """The committed machine-readable audit artifact (schema v1)."""
    from collections import Counter

    counts = Counter(f.rule for f in result.findings)
    doc: Dict = {
        "version": AUDIT_SCHEMA_VERSION,
        "tool": "tpusvm.analysis.ir",
        "rules": dict(sorted(IR_RULE_SUMMARIES.items())),
        "entry_points": [
            {
                "name": e.name,
                "description": e.description,
                "precision": e.precision,
                "traced": e.traced,
                "skip_reason": e.skip_reason,
                "swept_scalars": list(e.swept),
                "stats": e.stats,
            }
            for e in result.entries
        ],
        "traced_entry_points": result.traced_count,
        "findings": [f.to_dict() for f in result.findings],
        "counts": dict(sorted(counts.items())),
        "baselined": len(result.baselined),
    }
    return json.dumps(doc, indent=2, sort_keys=False) + "\n"
