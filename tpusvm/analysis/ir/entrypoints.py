"""Canonical abstract signatures for every audited jit entry point.

The compile observatory's registry (tpusvm.obs.prof.JIT_ENTRY_POINTS —
populated as a side effect of `profiled_jit`, so it lists exactly the jit
objects the repo ships) supplies the functions; this module supplies the
shapes. One `IREntryPoint` per audited configuration pairs a builder —
which returns (fn, args, kwargs) with arrays as `jax.ShapeDtypeStruct`
and sweep hyperparameters as concrete Python floats — with the resolved
precision rung its trace must obey (JXIR101) and the scalars whose
values must NOT leak into the trace (JXIR106's dual-trace check).

Canonical shapes follow the repo's power-of-two bucket discipline
(serve's compile-cache buckets, the shrink driver's compaction buckets):
every dimension is a multiple of the widest TPU tile in play
(config.TPU_TILE_SHAPES — (16, 128) for the bf16 rung), so the JXIR104
tile-alignment report is clean by construction on the shipped shapes and
any misalignment a future change introduces is a real regression.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Callable, Dict, Tuple

import jax
import jax.numpy as jnp

from tpusvm.analysis.ir.tracing import SkipTrace

# canonical dimensions (all multiples of the (16, 128) bf16 tile)
N = 1024      # training rows
D = 128       # features
Q = 256       # blocked working-set size
M = 512       # prediction batch rows
N_SV = 512    # support-vector rows (prediction/serving operand)
N_CLS = 16    # OVR classes
BUCKET = 128  # serve compile-cache bucket (power of two, tile-aligned)

F32 = jnp.float32


def _s(shape, dtype=F32):
    return jax.ShapeDtypeStruct(shape, dtype)


@dataclasses.dataclass(frozen=True)
class IREntryPoint:
    """One audited trace configuration.

    build(**scalars) -> (fn, args, kwargs). `sweep` maps each scalar
    kwarg of build to a (first, second) value pair: the auditor traces
    once with the first values (the jaxpr every rule walks) and once
    with the second, and JXIR106 requires the two jaxprs to be
    IDENTICAL — a difference means a weak scalar's concrete value leaked
    into the trace, i.e. jit recompiles per hyperparameter value. An
    empty sweep declares every scalar static by design (the serving
    contract: one executable per model).
    """

    name: str
    build: Callable[..., Tuple[Callable, tuple, dict]]
    sweep: Dict[str, Tuple[float, float]] = dataclasses.field(
        default_factory=dict)
    precision: str = "float32"   # resolved matmul rung (JXIR101)
    allow_f64: bool = False      # entry legitimately carries f64 avals
    description: str = ""


def _registered(name: str):
    """The raw jit object + statics behind an observatory name."""
    from tpusvm.obs import prof

    entry = prof.JIT_ENTRY_POINTS.get(name)
    if entry is None:  # pragma: no cover — registry drift is a bug
        raise SkipTrace(f"{name!r} not in obs.prof.JIT_ENTRY_POINTS "
                        f"(known: {sorted(prof.JIT_ENTRY_POINTS)})")
    return entry


# ------------------------------------------------------------- solvers
def _blocked_builder(sweep_statics: dict, with_pause: bool = False):
    import tpusvm.solver.blocked  # noqa: F401 — registers the entry

    def build(C=10.0, gamma=0.5):
        jitted, _ = _registered("solver.blocked_smo_solve")
        fn = functools.partial(jitted, q=Q, telemetry=0, **sweep_statics)
        args = (_s((N, D)), _s((N,)))
        kwargs = dict(C=C, gamma=gamma)
        if with_pause:
            kwargs["pause_at"] = _s((), jnp.int32)
        return fn, args, kwargs

    return build


def _fleet_build():
    import tpusvm.fleet.solve  # noqa: F401 — registers the entry

    jitted, _ = _registered("solver.fleet_smo_solve")
    # a bucket-of-4 fleet at the canonical solver shape; the per-problem
    # hyperparameters are ARRAYS by the fleet's launch-economics
    # contract, so their values cannot leak into the trace by
    # construction — no sweep needed (the dual-trace check would compare
    # identical jaxprs trivially). Canonical face is all-f32 like the
    # blocked entry (production f64 accum runs are out of audit scope,
    # exactly as for the solo solver's accum_dtype=float64 calls)
    B = 4
    fn = functools.partial(jitted, q=Q, telemetry=0)
    args = (_s((N, D)), _s((B, N)))
    kwargs = dict(Cs=_s((B,)), gammas=_s((B,)))
    return fn, args, kwargs


def _smo_build(C=10.0, gamma=0.5):
    import tpusvm.solver.smo  # noqa: F401

    jitted, _ = _registered("solver.smo_solve")
    return jitted, (_s((N, D)), _s((N,))), dict(C=C, gamma=gamma)


# ---------------------------------------------------------- prediction
def _decision_build():
    import tpusvm.solver.predict  # noqa: F401

    jitted, _ = _registered("predict.decision_function")
    fn = functools.partial(jitted, gamma=0.5, block=M, kernel="rbf")
    return fn, (_s((M, D)), _s((N_SV, D)), _s((N_SV,)), _s(())), {}


def _decision_flat_build():
    import tpusvm.solver.predict  # noqa: F401

    jitted, _ = _registered("predict.decision_function_flat")
    fn = functools.partial(jitted, gamma=0.5, kernel="rbf")
    return fn, (_s((M, D)), _s((N_SV, D)), _s((N_SV,)), _s(())), {}


def _ovr_build():
    import tpusvm.models.ovr  # noqa: F401

    jitted, _ = _registered("predict.ovr_scores")
    fn = functools.partial(jitted, kernel="rbf", degree=3)
    # gamma/coef0 arrive as 0-d device arrays in production (the serving
    # worker materialises them per model), hence abstract here
    return fn, (_s((M, D)), _s((N_SV, D)), _s((N_CLS, N_SV)),
                _s((N_CLS,)), _s(()), _s(())), {}


# -------------------------------------------------------------- serving
def _serve_bucket_binary_build():
    import tpusvm.solver.predict  # noqa: F401

    jitted, _ = _registered("predict.decision_function")
    # mirrors serve.buckets.CompileCache._lower for kind="binary"/"svr":
    # the scan block is capped at the bucket, kernel params are static
    # model config — the exact program the bucket cache AOT-compiles
    fn = functools.partial(jitted, gamma=0.5, block=BUCKET, kernel="rbf",
                           degree=3, coef0=0.0)
    return fn, (_s((BUCKET, D)), _s((N_SV, D)), _s((N_SV,)), _s(())), {}


def _serve_bucket_ovr_build():
    import tpusvm.models.ovr  # noqa: F401

    jitted, _ = _registered("predict.ovr_scores")
    fn = functools.partial(jitted, kernel="rbf", degree=3)
    return fn, (_s((BUCKET, D)), _s((N_SV, D)), _s((N_CLS, N_SV)),
                _s((N_CLS,)), _s(()), _s(())), {}


# -------------------------------------------- kernel-dispatch contractions
def _kernels_build(family: str):
    def build(gamma=0.5, coef0=1.0):
        from tpusvm import kernels

        if family == "rbf":
            def fn(X, XB, coef, g):
                return kernels.cross_matvec("rbf", X, XB, coef, gamma=g)
            return fn, (_s((N, D)), _s((Q, D)), _s((Q,)), gamma), {}
        if family == "linear":
            def fn(X, XB, coef):
                return kernels.cross_matvec("linear", X, XB, coef,
                                            gamma=0.0)
            return fn, (_s((N, D)), _s((Q, D)), _s((Q,))), {}

        def fn(X, XB, coef, g, c0):
            return kernels.cross_matvec("poly", X, XB, coef, gamma=g,
                                        coef0=c0, degree=3)
        return fn, (_s((N, D)), _s((Q, D)), _s((Q,)), gamma, coef0), {}

    return build


# ------------------------------------------- approximate-kernel entry points
def _approx_rff_transform_build():
    import tpusvm.approx.features  # noqa: F401 — registers the entries

    jitted, _ = _registered("approx.rff_transform")
    # canonical map: d=D(128) raw features -> 2*128=256 mapped (both
    # tile-aligned — config.validate_map_dim enforces the lane rule on
    # every real rff_dim up front)
    return jitted, (_s((N, D)), _s((D, 128))), {}


def _approx_nystrom_transform_build():
    import tpusvm.approx.features  # noqa: F401

    jitted, _ = _registered("approx.nystrom_transform")
    # k=128 landmarks; gamma arrives as a 0-d device array (FeatureMap
    # pins np.float32(gamma)), so its value cannot bake into the trace
    return jitted, (_s((N, D)), _s((128, D)), _s((128, 128)),
                    _s(())), {}


def _approx_decision_build():
    import tpusvm.approx.features  # noqa: F401

    jitted, _ = _registered("predict.approx_decision")
    # the fused map+decision program serve's bucket cache lowers for
    # binary/svr approx models (rff face): raw bucket rows + the map
    # operand tuple + MAPPED support rows
    fn = functools.partial(jitted, family="rff", block=M)
    return fn, (_s((M, D)), (_s((D, 128)),), _s((N_SV, 256)),
                _s((N_SV,)), _s(())), {}


def _approx_ovr_scores_build():
    import tpusvm.approx.features  # noqa: F401

    jitted, _ = _registered("predict.approx_ovr_scores")
    # the ovr face, on the nystrom branch so both map families' predict
    # jaxprs are walked (rff is covered by predict.approx_decision)
    fn = functools.partial(jitted, family="nystrom")
    return fn, (_s((M, D)), (_s((128, D)), _s((128, 128)), _s(())),
                _s((N_SV, 128)), _s((N_CLS, N_SV)), _s((N_CLS,))), {}


# ------------------------------------------------------ cascade round fn
def _cascade_round_build():
    if not hasattr(jax, "shard_map"):
        raise SkipTrace("jax.shard_map unavailable in this jax "
                        f"({jax.__version__}); the cascade round "
                        "executable cannot be built")
    try:
        from tpusvm.config import SVMConfig
        from tpusvm.parallel.cascade import _build_round_fn
        from tpusvm.parallel.mesh import make_mesh
        from tpusvm.parallel.svbuffer import SVBuffer

        train_cap, sv_cap = 256, 128
        mesh = make_mesh(1)
        fn = _build_round_fn(mesh, "tree", 1, train_cap, None, sv_cap,
                             SVMConfig(), None, "blocked", {})

        def buf(cap):
            return SVBuffer(X=_s((cap, D)), Y=_s((cap,), jnp.int32),
                            alpha=_s((cap,)), ids=_s((cap,), jnp.int32),
                            valid=_s((cap,), jnp.bool_))

        return fn, (buf(train_cap), buf(sv_cap)), {}
    except SkipTrace:
        raise
    except Exception as e:  # pragma: no cover — topology-dependent
        raise SkipTrace(f"cascade round executable not traceable here: "
                        f"{type(e).__name__}: {e}")


# ------------------------------------------------------------- registry
def default_entrypoints():
    """The audited entry points, in stable registry order."""
    sweep_cg = {"C": (10.0, 3.0), "gamma": (0.5, 0.125)}
    return [
        IREntryPoint(
            name="solver.blocked_smo_solve",
            build=_blocked_builder({}),
            sweep=dict(sweep_cg),
            description="blocked SMO, rbf, f32 trust anchor",
        ),
        IREntryPoint(
            name="solver.blocked_smo_solve[bf16_f32]",
            build=_blocked_builder({"matmul_precision": "bf16_f32",
                                    "shrink_stable": 3}),
            sweep=dict(sweep_cg),
            precision="bf16_f32",
            description="blocked SMO on the bf16_f32 ladder rung "
                        "(rounded operands, f32 accumulation)",
        ),
        IREntryPoint(
            name="solver.blocked_smo_solve[linear]",
            build=_blocked_builder({"kernel": "linear"}),
            sweep={"C": (10.0, 3.0)},
            description="blocked SMO, linear primal fast path",
        ),
        IREntryPoint(
            name="solver.blocked_smo_solve[krow_cache]",
            build=_blocked_builder({"krow_cache": Q}),
            sweep=dict(sweep_cg),
            description="blocked SMO with the K-row LRU cache paths",
        ),
        IREntryPoint(
            name="solver.shrink_segment",
            build=_blocked_builder({"shrink_stable": 3,
                                    "return_state": True},
                                   with_pause=True),
            sweep=dict(sweep_cg),
            description="one shrinking-driver segment (stability "
                        "counters + pause/return_state surface)",
        ),
        IREntryPoint(
            name="solver.blocked_smo_solve[fused]",
            build=_blocked_builder({"fused_fupdate": True}),
            sweep=dict(sweep_cg),
            description="blocked SMO with the fused Pallas f-update "
                        "kernel (the pallas_call body is walked too)",
        ),
        IREntryPoint(
            name="solver.fleet_smo_solve",
            build=_fleet_build,
            description="batched many-model fleet launch (vmapped "
                        "blocked core; per-problem C/gamma arrive as "
                        "arrays, so no scalar can bake into the trace)",
        ),
        IREntryPoint(
            name="solver.smo_solve",
            build=_smo_build,
            sweep=dict(sweep_cg),
            description="flat single-pair SMO solver",
        ),
        IREntryPoint(
            name="predict.decision_function",
            build=_decision_build,
            description="blocked batched scorer (kernel params static "
                        "by the serving contract — no sweep)",
        ),
        IREntryPoint(
            name="predict.decision_function_flat",
            build=_decision_flat_build,
            description="flat mesh-sharded scorer",
        ),
        IREntryPoint(
            name="predict.ovr_scores",
            build=_ovr_build,
            description="one-vs-rest class-score gemm",
        ),
        IREntryPoint(
            name="serve.bucket[binary]",
            build=_serve_bucket_binary_build,
            description="serve compile-cache bucket executable, "
                        "binary/svr kind",
        ),
        IREntryPoint(
            name="serve.bucket[ovr]",
            build=_serve_bucket_ovr_build,
            description="serve compile-cache bucket executable, ovr kind",
        ),
        IREntryPoint(
            name="kernels.cross_matvec[rbf]",
            build=_kernels_build("rbf"),
            sweep={"gamma": (0.5, 0.125)},
            description="kernel-dispatch blocked f-update contraction, "
                        "rbf family",
        ),
        IREntryPoint(
            name="kernels.cross_matvec[linear]",
            build=_kernels_build("linear"),
            description="kernel-dispatch contraction, linear primal",
        ),
        IREntryPoint(
            name="kernels.cross_matvec[poly]",
            build=_kernels_build("poly"),
            sweep={"gamma": (0.5, 0.125), "coef0": (1.0, 0.25)},
            description="kernel-dispatch contraction, poly family",
        ),
        IREntryPoint(
            name="approx.rff_transform",
            build=_approx_rff_transform_build,
            description="random-Fourier feature map Phi(X) (cos/sin "
                        "halves of the seeded omega matmul)",
        ),
        IREntryPoint(
            name="approx.nystrom_transform",
            build=_approx_nystrom_transform_build,
            description="Nystrom landmark map K(X, M) @ K_mm^{-1/2} "
                        "(gamma a 0-d array operand — no scalar leak "
                        "possible by construction)",
        ),
        IREntryPoint(
            name="predict.approx_decision",
            build=_approx_decision_build,
            description="fused map+decision scorer (the approx serve "
                        "bucket executable, rff face)",
        ),
        IREntryPoint(
            name="predict.approx_ovr_scores",
            build=_approx_ovr_scores_build,
            description="fused map+ovr-gemm scorer (approx ovr bucket "
                        "executable, nystrom face)",
        ),
        IREntryPoint(
            name="cascade.round_fn",
            build=_cascade_round_build,
            description="distributed cascade round executable "
                        "(shard_map; skipped where jax lacks it)",
        ),
    ]


def entrypoint_names():
    return [e.name for e in default_entrypoints()]
