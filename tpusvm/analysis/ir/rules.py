"""The JXIR rule set: semantic checks over traced jaxprs.

Where the AST rules (JX001-JX010) police what the source text says, these
rules machine-check what the compiler was actually asked to solve. Each
rule is a function over one `TraceAudit` (an entry point plus its traced
jaxpr(s)) yielding `Finding`s whose `path` is the pseudo-path
``jaxpr://<entry-name>`` — line/col carry no meaning at IR level (always
1:1), and fingerprints hash the rule + entry + a stable equation
descriptor, so the shared baseline mechanism (analysis/baseline.py) works
unchanged.

  JXIR101  unrouted contraction precision: every dot_general must carry
           an explicit precision consistent with the entry's resolved
           matmul rung; jax's None/DEFAULT (raw single-pass bf16 on TPU
           MXUs) is the footgun config.resolve_matmul_precision exists
           to close, now checked at the IR where it bites. The bf16_f32
           rung's signature is ROUNDED bf16 operands + f32 accumulation
           (preferred_element_type), which is only legal on bf16-rung
           entries.
  JXIR102  dtype provenance: no float64/complex aval anywhere in the
           graph (unless the entry declares allow_f64 — the x64
           accumulator mode), and no weak-typed ARRAY aval (a
           Python-scalar-derived array whose dtype was decided by
           promotion accident; as a carry or output it also forces
           jax's weak-type fixpoint re-trace). Weak 0-d scalars are the
           healthy jit hyperparameter pattern and exempt.
  JXIR103  loop-carry stability: while/scan carries must have
           structurally identical in/out avals (shape, dtype, weak
           type) and no weak-typed carry at all — the shrink
           compaction and checkpoint-resume paths hand carries across
           segment boundaries and depend on this exactly.
  JXIR104  TPU tile alignment: dot_general operands whose trailing two
           dims are not multiples of the dtype's min tile
           (config.TPU_TILE_SHAPES) are padded by the compiler; the
           finding reports the estimated padding-waste %. Canonical
           shapes follow the serve/shrink power-of-two buckets, so any
           finding is a real mis-sized operand.
  JXIR105  host callback reachable from a loop body at IR level — the
           semantic closure of JX009's syntactic check (a callback
           smuggled through a helper the AST walker cannot see still
           shows up as a debug_callback/io_callback equation inside
           the while/scan body jaxpr).
  JXIR106  recompile hazard: the entry is traced twice with different
           values for its swept weak scalars; any difference between
           the two jaxprs means a hyperparameter's VALUE leaked into
           the trace (a closure capture or host-side arithmetic), i.e.
           every sweep point pays a fresh compile.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Iterable, List, Optional

from tpusvm.analysis.core import Finding
from tpusvm.analysis.ir.tracing import (
    aval_of,
    in_loop_body,
    iter_eqns,
)

#: rule id -> one-line summary; importable without jax (the analysis CLI
#: lists IR rules next to the AST ones in its no-accelerator lint job)
IR_RULE_SUMMARIES = {
    "JXIR101": ("dot_general without an explicit precision consistent "
                "with the entry's resolved matmul rung (jax's default = "
                "raw single-pass bf16 on TPU MXUs)"),
    "JXIR102": ("float64 or weak-typed array aval in a traced graph "
                "(dtype provenance: Python-scalar promotion that "
                "recompiles or drifts)"),
    "JXIR103": ("while/scan carry in/out avals differ or carry is "
                "weak-typed (carry instability breaks shrink compaction "
                "and checkpoint-resume re-entry)"),
    "JXIR104": ("dot_general operand not aligned to the TPU min tile "
                "for its dtype — compiler pads, wasting HBM/MXU cycles"),
    "JXIR105": ("host callback reachable from a while/scan body at IR "
                "level (a device->host round trip per iteration)"),
    "JXIR106": ("entry-point trace varies with the concrete value of a "
                "weak scalar argument (recompile per hyperparameter "
                "value)"),
}

_CALLBACK_PRIMS = {
    "debug_callback", "io_callback", "pure_callback", "callback",
    "outside_call", "infeed", "outfeed", "host_callback_call",
}


@dataclasses.dataclass
class TraceAudit:
    """One entry point's traced artifacts, as handed to every rule."""

    entry: object                       # IREntryPoint
    jaxpr: object                       # ClosedJaxpr (sweep-first values)
    jaxpr_alt_str: Optional[str] = None  # str(jaxpr) at second values
    jaxpr_str: Optional[str] = None      # str(jaxpr) at first values

    @property
    def path(self) -> str:
        return f"jaxpr://{self.entry.name}"


def _finding(audit: TraceAudit, rule: str, message: str,
             snippet: str) -> Finding:
    return Finding(rule=rule, path=audit.path, line=1, col=1,
                   message=message, snippet=snippet)


def _eqn_snippet(eqn, path) -> str:
    """Stable, human-readable equation descriptor for fingerprints: the
    primitive, its operand shapes/dtypes, and where it sits."""
    ops = ",".join(
        f"{aval_of(v).dtype}{list(aval_of(v).shape)}" for v in eqn.invars
    )
    where = "/".join(path) or "top"
    return f"{eqn.primitive.name}({ops}) @ {where}"


# ----------------------------------------------------------------- JXIR101
def check_jxir101(audit: TraceAudit) -> Iterable[Finding]:
    import jax

    Precision = jax.lax.Precision
    rung = audit.entry.precision
    bf16_rung = rung in ("bf16_f32", "bf16_f32c")
    for eqn, path in iter_eqns(audit.jaxpr):
        if eqn.primitive.name != "dot_general":
            continue
        dtypes = [str(aval_of(v).dtype) for v in eqn.invars]
        bf16_ops = all(dt == "bfloat16" for dt in dtypes)
        prec = eqn.params.get("precision")
        pref = eqn.params.get("preferred_element_type")
        snippet = _eqn_snippet(eqn, path)
        if bf16_ops:
            if not bf16_rung:
                yield _finding(
                    audit, "JXIR101",
                    f"bfloat16-operand contraction in a {rung!r}-rung "
                    "entry: operands were rounded to bf16 outside the "
                    "bf16_f32 ladder rungs", snippet)
            elif str(pref) != "float32":
                yield _finding(
                    audit, "JXIR101",
                    "bf16 operands without f32 accumulation "
                    f"(preferred_element_type={pref}): the bf16_f32 rung "
                    "requires exact f32 adds via "
                    "preferred_element_type=float32 (ops.rbf.matmul_p)",
                    snippet)
            continue
        vals = prec if isinstance(prec, (tuple, list)) else (prec,)
        if prec is None or any(p is None or p == Precision.DEFAULT
                               for p in vals):
            yield _finding(
                audit, "JXIR101",
                f"dot_general with precision={prec!r}: jax's default "
                "requests RAW single-pass bf16 on TPU MXUs (~1e-2 Gram "
                "error, breaks SV-set parity); route the contraction "
                "through ops.rbf.matmul_p / ops.rbf.coef_matvec so the "
                f"resolved {rung!r} rung reaches the IR", snippet)


# ----------------------------------------------------------------- JXIR102
def check_jxir102(audit: TraceAudit) -> Iterable[Finding]:
    if audit.entry.allow_f64:
        return
    seen = set()
    jaxpr = audit.jaxpr.jaxpr

    def hazards(var, where):
        aval = aval_of(var)
        dt = str(getattr(aval, "dtype", ""))
        weak = bool(getattr(aval, "weak_type", False))
        ndim = len(getattr(aval, "shape", ()))
        if dt in ("float64", "complex128") and not (weak and ndim == 0):
            return (f"float64 aval {dt}{list(aval.shape)} {where} — the "
                    "canonical f32 signature promoted to double "
                    "somewhere (a Python float in an x64 context, or an "
                    "explicit f64 cast outside the accumulator mode)")
        if weak and ndim >= 1:
            return (f"weak-typed array aval {dt}{list(aval.shape)} "
                    f"{where} — a Python-scalar-derived array whose "
                    "dtype follows promotion accidents; give it an "
                    "explicit dtype at construction")
        return None

    for var in jaxpr.invars:
        msg = hazards(var, "at an entry input")
        if msg and ("invar", id(var)) not in seen:
            seen.add(("invar", id(var)))
            yield _finding(audit, "JXIR102", msg, "entry invars")
    for var in jaxpr.constvars:
        msg = hazards(var, "in a closed-over constant")
        if msg:
            yield _finding(audit, "JXIR102", msg, "entry constvars")
    for eqn, path in iter_eqns(audit.jaxpr):
        for var in eqn.outvars:
            msg = hazards(var, f"from `{eqn.primitive.name}`")
            if msg:
                yield _finding(audit, "JXIR102", msg,
                               _eqn_snippet(eqn, path))


# ----------------------------------------------------------------- JXIR103
def _carry_pairs(eqn):
    """(in_aval, out_aval) pairs of a loop carry, or [] for non-loops."""
    name = eqn.primitive.name
    if name == "while":
        body = eqn.params["body_jaxpr"].jaxpr
        nc = eqn.params.get("body_nconsts", 0)
        return list(zip(body.invars[nc:], body.outvars))
    if name == "scan":
        body = eqn.params["jaxpr"].jaxpr
        nc = eqn.params.get("num_consts", 0)
        ncar = eqn.params.get("num_carry", 0)
        return list(zip(body.invars[nc:nc + ncar], body.outvars[:ncar]))
    return []


def check_jxir103(audit: TraceAudit) -> Iterable[Finding]:
    for eqn, path in iter_eqns(audit.jaxpr):
        pairs = _carry_pairs(eqn)
        for slot, (vin, vout) in enumerate(pairs):
            a, b = aval_of(vin), aval_of(vout)
            a_sig = (tuple(a.shape), str(a.dtype), bool(a.weak_type))
            b_sig = (tuple(b.shape), str(b.dtype), bool(b.weak_type))
            snippet = (f"{eqn.primitive.name} carry[{slot}] @ "
                       f"{'/'.join(path) or 'top'}")
            if a_sig != b_sig:
                yield _finding(
                    audit, "JXIR103",
                    f"loop carry slot {slot} changes aval across one "
                    f"iteration: in {a_sig} vs out {b_sig} — resume/"
                    "compaction re-entry would rebuild a different "
                    "program", snippet)
            elif a.weak_type:
                yield _finding(
                    audit, "JXIR103",
                    f"weak-typed loop carry slot {slot} "
                    f"({a.dtype}{list(a.shape)}): jax re-traces the body "
                    "for the weak-type fixpoint and the carry dtype is "
                    "promotion-determined; initialise the carry with an "
                    "explicit dtype (jnp.int32(0), jnp.zeros(..., "
                    "dtype=...))", snippet)


# ----------------------------------------------------------------- JXIR104
def check_jxir104(audit: TraceAudit) -> Iterable[Finding]:
    """Tile alignment of dot_general CONTRACTING dims.

    Scope decision: only contracted dimensions are checked. They are the
    dims the repo's sizing disciplines control (q, the scan block, the
    serve buckets, shrink's compaction capacities, sv buffers), their
    padding cost is multiplicative (paid once per OUTPUT tile, every
    iteration of the contraction loop), and a drift off the tile grid
    there is always a fixable regression. Small NON-contracting dims are
    problem shape, not sizing bugs — the OVR class count, the flat
    solver's two selected K-rows — and flagging them would force a
    baseline entry for every legitimately small model axis.
    """
    from tpusvm.config import tpu_tile_for

    for eqn, path in iter_eqns(audit.jaxpr):
        if eqn.primitive.name != "dot_general":
            continue
        (lhs_c, rhs_c), _batch = eqn.params["dimension_numbers"]
        for opi, (var, contract) in enumerate(
                zip(eqn.invars, (lhs_c, rhs_c))):
            aval = aval_of(var)
            shape = tuple(getattr(aval, "shape", ()))
            if len(shape) < 2:
                continue  # vectors/scalars are not MXU-tiled operands
            tile = tpu_tile_for(str(aval.dtype))
            for cd in contract:
                # position decides the constraint: last dim sits on the
                # 128-lane axis, second-to-last on the sublane axis;
                # leading dims are untiled
                axis_from_end = len(shape) - 1 - cd
                if axis_from_end > 1:
                    continue
                size = shape[cd]
                req = tile[1] if axis_from_end == 0 else tile[0]
                padded = -(-size // req) * req
                if padded == size:
                    continue
                waste = 100.0 * (1.0 - size / padded)
                yield _finding(
                    audit, "JXIR104",
                    f"dot_general operand {opi} "
                    f"{aval.dtype}{list(shape)}: contracting dim {cd} "
                    f"(size {size}) is not a multiple of its TPU tile "
                    f"extent {req} — the compiler pads it to {padded}, "
                    f"an estimated {waste:.1f}% padding waste on every "
                    "output tile; size it on the power-of-two bucket "
                    "grid (serve buckets / shrink compaction "
                    "discipline)",
                    f"operand{opi}:{_eqn_snippet(eqn, path)}")


# ----------------------------------------------------------------- JXIR105
def check_jxir105(audit: TraceAudit) -> Iterable[Finding]:
    for eqn, path in iter_eqns(audit.jaxpr):
        if eqn.primitive.name in _CALLBACK_PRIMS and in_loop_body(path):
            yield _finding(
                audit, "JXIR105",
                f"`{eqn.primitive.name}` reachable from a loop body "
                f"({'/'.join(path)}): one device->host round trip per "
                "iteration of the compiled loop — JX009's hazard, here "
                "proven at IR level through whatever helpers hid it from "
                "the AST; carry telemetry through the loop state instead",
                _eqn_snippet(eqn, path))


# ----------------------------------------------------------------- JXIR106
def check_jxir106(audit: TraceAudit) -> Iterable[Finding]:
    if not audit.entry.sweep or audit.jaxpr_alt_str is None:
        return
    a, b = audit.jaxpr_str, audit.jaxpr_alt_str
    if a == b:
        return
    # first differing line, for the message only (fingerprint stays on
    # the stable entry-level snippet)
    diff_line = ""
    for la, lb in zip(a.splitlines(), b.splitlines()):
        if la != lb:
            diff_line = la.strip()
            break
    names = ", ".join(sorted(audit.entry.sweep))
    yield _finding(
        audit, "JXIR106",
        f"re-tracing with different values of weak scalar(s) [{names}] "
        "produced a DIFFERENT jaxpr (first divergence: "
        f"`{diff_line[:120]}`): a hyperparameter's concrete value is "
        "baked into the trace — every sweep point recompiles; pass the "
        "scalar as a traced argument, not a closure constant",
        "sweep-divergence")


@dataclasses.dataclass(frozen=True)
class IRRule:
    id: str
    summary: str
    check: Callable[[TraceAudit], Iterable[Finding]]


def all_ir_rules() -> Dict[str, IRRule]:
    checks = {
        "JXIR101": check_jxir101,
        "JXIR102": check_jxir102,
        "JXIR103": check_jxir103,
        "JXIR104": check_jxir104,
        "JXIR105": check_jxir105,
        "JXIR106": check_jxir106,
    }
    assert set(checks) == set(IR_RULE_SUMMARIES)
    return {rid: IRRule(rid, IR_RULE_SUMMARIES[rid], fn)
            for rid, fn in sorted(checks.items())}


def select_ir_rules(select=None, ignore=None) -> List[IRRule]:
    rules = all_ir_rules()
    unknown = (set(select or ()) | set(ignore or ())) - set(rules)
    if unknown:
        raise ValueError(f"unknown IR rule id(s): {sorted(unknown)}; "
                         f"known: {sorted(rules)}")
    return [r for rid, r in rules.items()
            if (not select or rid in select)
            and (not ignore or rid not in ignore)]
