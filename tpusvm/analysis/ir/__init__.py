"""tpusvm.analysis.ir — jaxpr-level semantic auditor (rules JXIR1xx).

The AST linter checks the *source text*; this subpackage checks the
*solved problem*: it traces the repo's real jit entry points against a
canonical registry of abstract signatures (entrypoints.py, fed by the
compile observatory's JIT_ENTRY_POINTS registry), walks the closed
jaxprs — while/scan/cond sub-jaxprs and pallas bodies included — and
machine-checks precision routing (JXIR101), dtype/weak-type provenance
(JXIR102), loop-carry stability (JXIR103), TPU tile alignment
(JXIR104), loop-body host callbacks (JXIR105), and weak-scalar
recompile hazards (JXIR106).

Run it with `python -m tpusvm.analysis ir-audit` (needs jax; CI runs it
on JAX_PLATFORMS=cpu). Findings share the AST linter's Finding type,
reporters, and fingerprinted-baseline mechanism; the committed baseline
(.tpusvm-ir-baseline.json) is EMPTY and the committed audit artifact
lives at benchmarks/results/ir_audit_cpu.json.

This __init__ stays import-light (no jax): the lint CI job imports
`tpusvm.analysis.ir.rules.IR_RULE_SUMMARIES` to list the JXIR rules
without accelerator deps; everything that traces lives behind function
calls in audit/entrypoints/tracing.
"""

from tpusvm.analysis.ir.rules import IR_RULE_SUMMARIES  # noqa: F401

__all__ = ["IR_RULE_SUMMARIES"]
