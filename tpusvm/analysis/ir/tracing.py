"""Jaxpr acquisition and traversal for the IR auditor.

The AST linter (JX001-JX010) checks what the source *says*; this module
feeds the JXIR rules what the compiler actually *solves*: the closed
jaxpr of each registered entry point, traced from canonical abstract
signatures (tpusvm.analysis.ir.entrypoints). Tracing goes through the
very jit objects the repo ships — `jax.make_jaxpr` applied to the jit
wrapper yields a top-level `pjit` equation whose params carry the real
inner jaxpr, with static_argnames resolved exactly as a production call
would resolve them — so the audited graph IS the compiled graph, not a
re-derivation of it.

`iter_eqns` walks a closed jaxpr recursively: any equation parameter
holding a Jaxpr/ClosedJaxpr (pjit bodies, `while` cond/body, `scan`
bodies, `cond`/`switch` branches, custom_jvp/vjp call jaxprs, and
pallas_call kernel bodies where the primitive exposes them) is descended
into, with a human-readable path like
``pjit.jaxpr/while.body_jaxpr/cond.branches`` attached to every yielded
equation so findings can say *where inside the program* a hazard sits.
"""

from __future__ import annotations

from typing import Any, Iterator, List, Tuple

# NOTE: jax is imported lazily inside the functions that need it —
# tpusvm.analysis.ir.rules re-exports rule SUMMARIES through this package
# into the no-accelerator lint CI job, which must import without jax.


class SkipTrace(Exception):
    """Raised by an entry-point builder when the entry cannot be traced
    in this environment (missing jax feature, missing device topology).
    The audit records the entry as skipped-with-reason instead of
    failing; the ≥-min-entries smoke gate keeps "skipped" honest."""


def trace_entry(fn, args: tuple, kwargs: dict):
    """Closed jaxpr of `fn(*args, **kwargs)`.

    Arrays are passed as jax.ShapeDtypeStruct (pure abstract — nothing
    is allocated); sweep scalars arrive as concrete Python floats, which
    `make_jaxpr` abstractifies to weak-typed 0-d avals — the same avals
    jit's cache keys on, so weak-type behaviour is audited faithfully.
    """
    import jax

    return jax.make_jaxpr(fn)(*args, **kwargs)


def _subjaxprs(value: Any) -> List:
    """Jaxpr/ClosedJaxpr instances inside one eqn param value."""
    import jax

    out = []
    vals = value if isinstance(value, (list, tuple)) else [value]
    for v in vals:
        if isinstance(v, (jax.core.Jaxpr, jax.core.ClosedJaxpr)):
            out.append(v)
    return out


def iter_eqns(closed_jaxpr) -> Iterator[Tuple[Any, Tuple[str, ...]]]:
    """Yield (eqn, path) over a closed jaxpr and every nested sub-jaxpr.

    `path` is a tuple of "primitive.param" hops from the top level down
    to the sub-jaxpr owning the equation; () means top level.
    """
    jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)

    def walk(jx, path):
        for eqn in jx.eqns:
            yield eqn, path
            for pname, pval in eqn.params.items():
                for sub in _subjaxprs(pval):
                    inner = getattr(sub, "jaxpr", sub)
                    hop = f"{eqn.primitive.name}.{pname}"
                    yield from walk(inner, path + (hop,))

    yield from walk(jaxpr, ())


def in_loop_body(path: Tuple[str, ...]) -> bool:
    """True when `path` descends through a loop body (re-executed per
    iteration): a `while` cond/body or a `scan` body. `cond`/`switch`
    branches execute once per call and do not count."""
    return any(hop.startswith(("while.", "scan.")) for hop in path)


def eqn_stats(closed_jaxpr) -> dict:
    """Structural counts for the audit artifact (sorted, deterministic)."""
    n_eqns = n_dots = n_while = n_scan = n_pallas = 0
    for eqn, _ in iter_eqns(closed_jaxpr):
        n_eqns += 1
        name = eqn.primitive.name
        if name == "dot_general":
            n_dots += 1
        elif name == "while":
            n_while += 1
        elif name == "scan":
            n_scan += 1
        elif name.startswith("pallas"):
            n_pallas += 1
    return {"eqns": n_eqns, "dot_generals": n_dots, "while_loops": n_while,
            "scans": n_scan, "pallas_calls": n_pallas}


def aval_of(var):
    """Aval of a jaxpr Var or Literal (both carry .aval in this jax)."""
    return var.aval
