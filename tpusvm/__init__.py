"""tpusvm — TPU-native parallel SVM training (JAX / XLA / Pallas / shard_map).

A from-scratch framework with the capabilities of the reference project
"Parallelizing Support Vector Machine Training with GPU and MPI"
(guaijiacc/…): binary RBF-kernel SVM training via SMO with Keerthi
first-order working-set selection, a serial correctness oracle, a fully
on-device single-chip solver, distributed Cascade SVM (classical tree and
modified star merges) over a jax.sharding.Mesh, and one-vs-rest multi-class
training. See SURVEY.md for the capability map.
"""

from tpusvm.config import CascadeConfig, SVMConfig, preset
from tpusvm.status import Status

__version__ = "0.23.0"

__all__ = ["SVMConfig", "CascadeConfig", "preset", "Status", "__version__"]
