"""Device mesh helpers.

The reference's process geometry is `mpirun -np P` over cluster nodes
(code/mpi_svm3.sh); here it is a 1-D jax.sharding.Mesh over TPU chips whose
axis carries the cascade's SV-exchange traffic on ICI. On a host without P
real chips, tests use XLA's host-platform device simulation
(tests/conftest.py) and the same code runs on virtual CPU devices.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

CASCADE_AXIS = "cascade"


def make_mesh(
    n_shards: Optional[int] = None,
    devices: Optional[Sequence[jax.Device]] = None,
    axis: str = CASCADE_AXIS,
) -> Mesh:
    """1-D mesh over the first n_shards devices (default: all)."""
    if devices is None:
        devices = jax.devices()
    if n_shards is not None:
        if n_shards > len(devices):
            raise ValueError(
                f"requested {n_shards} shards but only {len(devices)} devices"
            )
        devices = devices[:n_shards]
    return Mesh(np.asarray(devices), (axis,))


def shard_leading(mesh: Mesh, tree, axis: str = CASCADE_AXIS):
    """device_put each array with its leading dim sharded over the mesh axis."""
    def put(x):
        spec = P(axis, *([None] * (x.ndim - 1)))
        return jax.device_put(x, NamedSharding(mesh, spec))
    return jax.tree.map(put, tree)


def replicate(mesh: Mesh, tree):
    """device_put each array fully replicated over the mesh."""
    def put(x):
        return jax.device_put(x, NamedSharding(mesh, P()))
    return jax.tree.map(put, tree)
