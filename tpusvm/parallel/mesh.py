"""Device mesh helpers.

The reference's process geometry is `mpirun -np P` over cluster nodes
(code/mpi_svm3.sh); here it is a 1-D jax.sharding.Mesh over TPU chips whose
axis carries the cascade's SV-exchange traffic on ICI. On a host without P
real chips, tests use XLA's host-platform device simulation
(tests/conftest.py) and the same code runs on virtual CPU devices.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

CASCADE_AXIS = "cascade"


def require_1d_mesh(mesh: Mesh, what: str) -> None:
    """Raise unless mesh has exactly one axis. Callers that pad/shard by
    mesh.devices.size along axis 0 rely on the two agreeing, which only a
    1-D mesh guarantees."""
    if len(mesh.axis_names) != 1:
        raise ValueError(
            f"{what} requires a 1-D mesh; got axes {mesh.axis_names} "
            f"with shape {dict(mesh.shape)}"
        )


def make_mesh(
    n_shards: Optional[int] = None,
    devices: Optional[Sequence[jax.Device]] = None,
    axis: str = CASCADE_AXIS,
) -> Mesh:
    """1-D mesh over the first n_shards devices (default: all)."""
    if devices is None:
        devices = jax.devices()
    if n_shards is not None:
        if n_shards > len(devices):
            raise ValueError(
                f"requested {n_shards} shards but only {len(devices)} devices"
            )
        devices = devices[:n_shards]
    return Mesh(np.asarray(devices), (axis,))


def shard_leading(mesh: Mesh, tree, axis: str = CASCADE_AXIS):
    """device_put each array with its leading dim sharded over the mesh axis."""
    def put(x):
        spec = P(axis, *([None] * (x.ndim - 1)))
        return jax.device_put(x, NamedSharding(mesh, spec))
    return jax.tree.map(put, tree)


def replicate(mesh: Mesh, tree):
    """device_put each array fully replicated over the mesh."""
    def put(x):
        return jax.device_put(x, NamedSharding(mesh, P()))
    return jax.tree.map(put, tree)


def shard_rows_padded(mesh: Optional[Mesh], X):
    """Zero-pad X's leading axis to a device multiple, device_put it
    row-sharded over the mesh's (single) axis. Returns (X_sharded, n) with
    n the original row count — slice outputs back to [:n]. For
    row-independent computations (e.g. the prediction matmul) the zero
    padding rows produce garbage-but-isolated outputs that the slice
    drops; NamedSharding itself requires an even split, hence the pad.
    mesh=None returns (X, n) unchanged, so callers with an optional mesh
    need no branch."""
    import jax.numpy as jnp

    n = X.shape[0]
    if mesh is None:
        return X, n
    require_1d_mesh(mesh, "shard_rows_padded")
    pad = (-n) % mesh.devices.size
    if pad:
        X = jnp.concatenate(
            [X, jnp.zeros((pad,) + X.shape[1:], X.dtype)]
        )
    return shard_leading(mesh, X, axis=mesh.axis_names[0]), n
