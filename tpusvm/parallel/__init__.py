from tpusvm.parallel.cascade import CascadeResult, cascade_fit
from tpusvm.parallel.mesh import CASCADE_AXIS, make_mesh, replicate, shard_leading
from tpusvm.parallel.svbuffer import (
    SVBuffer,
    compact,
    dedup_first,
    empty,
    extract_svs,
    from_arrays,
    merge_dedup,
)

__all__ = [
    "CascadeResult",
    "cascade_fit",
    "CASCADE_AXIS",
    "make_mesh",
    "replicate",
    "shard_leading",
    "SVBuffer",
    "compact",
    "dedup_first",
    "empty",
    "extract_svs",
    "from_arrays",
    "merge_dedup",
]
