"""Fixed-capacity padded support-vector buffers + masked dedup/compaction.

The reference's cascade passes dynamically-sized SV sets between ranks as
(count, X, Y, alpha, ID) message groups (mpi_svm_main3.cpp:692-716) and
dedups them with an unordered_set of global IDs (mpi_svm_main3.cpp:628-655).
XLA requires static shapes, so SV sets become capacity-padded buffers with a
validity mask (SURVEY.md §2.4, §7.3 "Dynamic shapes"), and the hash-set dedup
becomes a lexicographic sort by (id, position): the first occurrence of each
id survives, which reproduces the reference's sequential insert-if-new
semantics exactly (earlier positions win).

All functions here are pure jnp and run unchanged inside shard_map.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax


class SVBuffer(NamedTuple):
    """A padded SV set. Rows with valid=False are padding.

    X:     (cap, d)   features
    Y:     (cap,)     labels in {+1,-1}; 0 in padding
    alpha: (cap,)     dual variables; 0 in padding
    ids:   (cap,) int32 global sample IDs; -1 in padding
    valid: (cap,) bool
    """

    X: jax.Array
    Y: jax.Array
    alpha: jax.Array
    ids: jax.Array
    valid: jax.Array

    @property
    def capacity(self) -> int:
        return self.Y.shape[0]

    def count(self) -> jax.Array:
        return jnp.sum(self.valid).astype(jnp.int32)


def empty(cap: int, d: int, dtype=jnp.float32) -> SVBuffer:
    return SVBuffer(
        X=jnp.zeros((cap, d), dtype),
        Y=jnp.zeros((cap,), jnp.int32),
        alpha=jnp.zeros((cap,), dtype),
        ids=jnp.full((cap,), -1, jnp.int32),
        valid=jnp.zeros((cap,), bool),
    )


def from_arrays(X, Y, alpha, ids, valid) -> SVBuffer:
    return SVBuffer(
        X=X,
        Y=Y.astype(jnp.int32),
        alpha=alpha.astype(X.dtype),
        ids=ids.astype(jnp.int32),
        valid=valid.astype(bool),
    )


def compact(buf: SVBuffer, cap_out: int) -> Tuple[SVBuffer, jax.Array]:
    """Pack valid rows to the front (stable order) into a cap_out buffer.

    Returns (packed buffer, valid count). Rows beyond cap_out are dropped —
    callers must check count <= cap_out for overflow.
    """
    cap_in, d = buf.X.shape
    count = buf.count()
    # destination slot for each row; invalid / overflowing rows -> cap_out (drop)
    pos = jnp.cumsum(buf.valid.astype(jnp.int32)) - 1
    dest = jnp.where(buf.valid, pos, cap_out)
    out = empty(cap_out, d, buf.X.dtype)
    out = SVBuffer(
        X=out.X.at[dest].set(buf.X, mode="drop"),
        Y=out.Y.at[dest].set(buf.Y, mode="drop"),
        alpha=out.alpha.at[dest].set(buf.alpha, mode="drop"),
        ids=out.ids.at[dest].set(buf.ids, mode="drop"),
        valid=out.valid.at[dest].set(buf.valid, mode="drop"),
    )
    return out, count


def dedup_first(buf: SVBuffer) -> SVBuffer:
    """Invalidate duplicate ids, keeping the FIRST valid occurrence.

    Sort-based replacement for the reference's unordered_set insert-if-new
    loop (mpi_svm_main3.cpp:644-655): lexicographic sort by (id, position),
    mark rows whose id equals the previous sorted row's id as duplicates,
    scatter the keep-mask back to original positions. O(cap log cap), static
    shapes, no host round trip.
    """
    cap = buf.ids.shape[0]
    big = jnp.int32(2**31 - 1)
    key = jnp.where(buf.valid, buf.ids, big)  # invalid rows sort to the end
    pos = jnp.arange(cap, dtype=jnp.int32)
    sorted_key, sorted_pos = lax.sort((key, pos), num_keys=2)
    first = jnp.concatenate(
        [jnp.ones((1,), bool), sorted_key[1:] != sorted_key[:-1]]
    )
    keep_sorted = first & (sorted_key != big)
    keep = jnp.zeros((cap,), bool).at[sorted_pos].set(keep_sorted)
    return buf._replace(valid=buf.valid & keep)


def merge_dedup(
    primary: SVBuffer, secondary: SVBuffer, cap_out: int,
) -> Tuple[SVBuffer, jax.Array]:
    """Union of two SV sets with the cascade's exact alpha semantics.

    Primary rows keep their alpha (warm start); secondary rows get alpha = 0
    and are dropped when their id already appears in primary (or earlier in
    secondary). This is precisely the reference's union builder:
      - tree:  primary = received SVs (warm), secondary = own set, alpha=0
               (mpi_svm_main3.cpp:628-655)
      - star:  primary = rank0's own SVs (warm), secondary = workers' SVs,
               alpha reset to 0 (mpi_svm_main2.cpp:596-604)
      - round start: primary = broadcast global SVs (warm), secondary = local
               partition (mpi_svm_main2.cpp:481-502)

    Returns (merged buffer of capacity cap_out, pre-truncation valid count).
    count > cap_out means overflow: rows were dropped and the caller should
    raise/grow capacity.
    """
    cat = SVBuffer(
        X=jnp.concatenate([primary.X, secondary.X]),
        Y=jnp.concatenate([primary.Y, secondary.Y]),
        alpha=jnp.concatenate([primary.alpha, jnp.zeros_like(secondary.alpha)]),
        ids=jnp.concatenate([primary.ids, secondary.ids]),
        valid=jnp.concatenate([primary.valid, secondary.valid]),
    )
    return compact(dedup_first(cat), cap_out)


def extract_svs(
    train: SVBuffer, alpha: jax.Array, sv_tol: float, cap_out: int,
) -> Tuple[SVBuffer, jax.Array]:
    """Keep rows with alpha > sv_tol (get_SV_indices, main3.cpp:297-304).

    Returns (SV buffer of capacity cap_out, pre-truncation SV count).
    """
    is_sv = train.valid & (alpha > sv_tol)
    buf = SVBuffer(
        X=train.X, Y=train.Y, alpha=alpha.astype(train.X.dtype),
        ids=train.ids, valid=is_sv,
    )
    return compact(buf, cap_out)
