"""Distributed Cascade SVM over a TPU mesh (tree and star topologies).

TPU-native redesign of the reference's two MPI cascade programs:

  - classical binary-reduction tree (mpi_svm_main3.cpp:565-828): per round,
    every rank trains on (received SVs [warm alpha] u own set [alpha=0]),
    then at step s ranks == s (mod 2s) send their SV set to rank-s and go
    idle; after log2(P)+1 steps rank 0 holds the merged model.
  - modified two-layer star (mpi_svm_main2.cpp:439-769): per round, every
    rank trains on (global SVs [warm] u own partition [alpha=0]) in
    parallel, then rank 0 merges all SV sets (own alphas kept, received
    reset to 0) and retrains the merged set.

The MPI machinery maps to XLA collectives over the mesh axis (SURVEY.md
§2.4):
  - initial scatter (tags 10-13)        -> NamedSharding'd partition arrays
  - per-round global-SV Bcast (C20)     -> replicated in_specs (free: the
                                           round function receives the
                                           buffer replicated)
  - tree SV exchange (tags 20-24)       -> lax.ppermute of padded SVBuffers
  - star gather to rank 0               -> lax.all_gather; the merged solve
                                           is executed replicated on every
                                           device (same wall-clock as the
                                           reference's workers idling while
                                           rank 0 solves, no idle silicon)
  - convergence-flag Bcast (C24)        -> host-side Python round loop
                                           (6-7 rounds in practice, one
                                           device->host transfer per round)

Idle ranks in the tree rounds get their training buffer fully invalidated
(valid &= active), so their on-device solver exits after one iteration
instead of chewing on garbage — SPMD lockstep without wasted wall-clock.

Everything is SPMD with static shapes; per-rank SV sets are capacity-padded
SVBuffers (tpusvm.parallel.svbuffer). Dedup-by-ID and the warm-start alpha
rules match the reference exactly (see merge_dedup docstring).

Host fallback (no shard_map): on a jax build without `jax.shard_map`
the same rounds run as a plain Python loop over ranks
(_tree_round_host/_star_round_host) — identical merges, identical
solves, identical diagnostics shapes — so the cascade trains on stock
CPU jax and the pod tier (tpusvm.pod) has an in-process control to be
bit-compared against. Idle tree ranks are skipped outright (the SPMD
path masks their outputs away, so skipping them is value-identical).
"""

from __future__ import annotations

import contextlib
import functools
import time
import warnings
from typing import Any, Dict, List, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from tpusvm import faults
from tpusvm.config import CascadeConfig, SVMConfig, resolve_accum_dtype
from tpusvm.data.partition import partition as make_partition
from tpusvm.obs import prof
from tpusvm.parallel.mesh import CASCADE_AXIS, make_mesh
from tpusvm.parallel.svbuffer import SVBuffer, empty, extract_svs, merge_dedup
from tpusvm.solver.blocked import blocked_smo_solve
from tpusvm.solver.smo import smo_solve
from tpusvm.status import Status


class CascadeResult(NamedTuple):
    """Final global model (rank 0's converged SV set) + run history."""

    sv_X: np.ndarray
    sv_Y: np.ndarray
    sv_alpha: np.ndarray
    sv_ids: np.ndarray
    b: float
    rounds: int
    converged: bool
    history: List[Dict[str, Any]]


_CKPT_VERSION = 1


def save_round_state(path: str, global_sv: SVBuffer, prev_ids, rnd: int,
                     b: float, n_shards: Optional[int] = None,
                     topology: Optional[str] = None) -> None:
    """Persist the cascade's inter-round state (SURVEY.md §5.4: the
    broadcast global-SV set IS the reference's in-memory checkpoint; this
    writes it out). Atomic via temp-file rename so a crash mid-write never
    corrupts the previous checkpoint.

    n_shards/topology, when given, are stored so a resume under a
    DIFFERENT partition or merge topology is refused with a config error
    instead of silently walking a different cascade (the SV-buffer shapes
    alone cannot tell 4 shards from 8)."""
    import os

    extra = {}
    if n_shards is not None:
        extra["n_shards"] = n_shards
    if topology is not None:
        extra["topology"] = topology
    faults.point("cascade.checkpoint", path=path, round=rnd)
    tmp = path + ".tmp"
    np.savez_compressed(
        tmp,
        ckpt_version=_CKPT_VERSION,
        round=rnd,
        b=b,
        prev_ids=np.asarray(sorted(prev_ids), np.int32),
        sv_X=np.asarray(global_sv.X),
        sv_Y=np.asarray(global_sv.Y),
        sv_alpha=np.asarray(global_sv.alpha),
        sv_ids=np.asarray(global_sv.ids),
        sv_valid=np.asarray(global_sv.valid),
        **extra,
    )
    # np.savez appends .npz to the temp name
    os.replace(tmp + ".npz", path)


def check_round_state_config(path: str, n_shards: int,
                             topology: str) -> None:
    """Refuse a checkpoint written under a different cascade config.

    Older checkpoints (no stored config) pass — the shape checks still
    apply; checkpoints that DO carry config must match exactly."""
    with np.load(path, allow_pickle=False) as z:
        if "n_shards" in z.files and int(z["n_shards"]) != n_shards:
            raise ValueError(
                f"cascade checkpoint config mismatch: it was written for "
                f"n_shards={int(z['n_shards'])}, this run partitions into "
                f"{n_shards}; resume with the original shard count or "
                "start fresh without --resume"
            )
        if "topology" in z.files and str(z["topology"]) != topology:
            raise ValueError(
                f"cascade checkpoint config mismatch: it was written for "
                f"topology={str(z['topology'])!r}, this run uses "
                f"{topology!r}; resume with the original topology or "
                "start fresh without --resume"
            )


def load_round_state(path: str, dtype=jnp.float32):
    """Returns (global_sv: SVBuffer, prev_ids: set, next_round: int, b)."""
    with np.load(path, allow_pickle=False) as z:
        if int(z["ckpt_version"]) != _CKPT_VERSION:
            raise ValueError(
                f"unsupported cascade checkpoint version {int(z['ckpt_version'])}"
            )
        buf = SVBuffer(
            X=jnp.asarray(z["sv_X"], dtype),
            Y=jnp.asarray(z["sv_Y"]),
            # keep the stored dual dtype: in mixed-precision runs alpha is
            # float64 between rounds, and truncating it would make the
            # resumed trajectory diverge from an uninterrupted run
            alpha=jnp.asarray(z["sv_alpha"]),
            ids=jnp.asarray(z["sv_ids"]),
            valid=jnp.asarray(z["sv_valid"]),
        )
        return (
            buf,
            set(z["prev_ids"].tolist()),
            int(z["round"]) + 1,
            float(z["b"]),
        )


def _resume_fingerprint(status, start_round: int, prev_ids,
                        b: float) -> np.ndarray:
    """Compact per-process summary of the loaded checkpoint state:
    [status, next round, CRC of the sorted SV-ID set, b bits lo, b bits hi]
    with status 0 = file missing, 1 = loaded, 2 = load failed.
    Identical checkpoints produce identical fingerprints; any divergence
    (missing file on one host, different round, different SV set) differs
    in at least one field. uint32 fields so the cross-process gather is
    exact whether or not jax x64 is enabled."""
    import zlib

    ids = np.asarray(sorted(prev_ids), np.int64)
    b_bits = int(np.float64(b).view(np.uint64))
    return np.array(
        [
            int(status),
            start_round,
            zlib.crc32(ids.tobytes()),
            b_bits & 0xFFFFFFFF,
            b_bits >> 32,
        ],
        np.uint32,
    )


def _check_resume_fingerprints(all_fps: np.ndarray) -> None:
    """Raise unless every process loaded the same checkpoint state.

    all_fps: (process_count, 5) stack of _resume_fingerprint rows. The
    cascade round loop is SPMD: every process must launch the same number
    of round_fn collectives with the same global_sv input, so a resume
    where process 0 starts at round N while another process (whose host
    lacks the checkpoint file) starts fresh at round 1 is a distributed
    deadlock, not a recoverable skew. Checkpoint/resume on a multi-host
    cluster therefore REQUIRES checkpoint_path on a shared filesystem (or
    an identical copy staged to every host before restart).

    A local load FAILURE (stale shapes, corrupt file) is folded into the
    fingerprint as status=2 rather than raised before the gather — raising
    early on one process would leave the others blocked inside
    process_allgather forever, the very hang this check exists to
    prevent."""
    status = all_fps[:, 0]
    if (status == 2).any():
        bad = np.nonzero(status == 2)[0].tolist()
        raise RuntimeError(
            "cascade resume: checkpoint failed to load on processes "
            f"{bad} (stale shapes or corrupt file); see that process's "
            "chained error. All processes must be restarted with a valid, "
            "identical checkpoint."
        )
    if (all_fps == all_fps[0]).all():
        return
    loaded = status.astype(bool)
    if loaded.any() and not loaded.all():
        missing = np.nonzero(~loaded)[0].tolist()
        raise RuntimeError(
            "cascade resume: checkpoint file present on some processes but "
            f"missing on processes {missing}. Multi-host resume requires "
            "checkpoint_path on a shared filesystem (process 0 writes it); "
            "stage the file to every host or fix the path."
        )
    raise RuntimeError(
        "cascade resume: processes loaded DIVERGENT checkpoint state "
        "(per-process [status, round, id_crc32, b_lo, b_hi] = "
        f"{all_fps.tolist()}). "
        "All processes must read the same checkpoint file — use a shared "
        "filesystem or stage identical copies before restarting."
    )


def _verify_resume_agreement(status, start_round: int, prev_ids,
                             b: float, load_err=None) -> None:
    """Cross-process agreement check for resume=True (no-op single-process).

    Gathers every process's checkpoint fingerprint and raises before any
    round collective is launched if they disagree — turning the silent
    distributed deadlock/garbage of a partial resume into an immediate,
    explained error. load_err: the local load failure (if any), chained
    onto the raised error so the failing process reports its real cause."""
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils

    fp = _resume_fingerprint(status, start_round, prev_ids, b)
    all_fps = np.asarray(multihost_utils.process_allgather(fp))
    try:
        _check_resume_fingerprints(all_fps)
    except RuntimeError as e:
        if load_err is not None:
            raise e from load_err
        raise


def _squeeze(tree):
    return jax.tree.map(lambda x: x[0], tree)


def _solve(train: SVBuffer, cfg: SVMConfig, accum_dtype=None,
           solver: str = "pair", solver_opts: Optional[dict] = None):
    solve = blocked_smo_solve if solver == "blocked" else smo_solve
    return solve(
        train.X,
        train.Y,
        valid=train.valid,
        alpha0=train.alpha,
        C=cfg.C,
        gamma=cfg.gamma,
        eps=cfg.eps,
        tau=cfg.tau,
        max_iter=cfg.max_iter,
        kernel=cfg.kernel,
        degree=cfg.degree,
        coef0=cfg.coef0,
        warm_start=True,
        accum_dtype=accum_dtype,
        **(solver_opts or {}),
    )


def _tree_round_device(
    part_buf, global_sv, *, n_shards, train_cap, sv_cap, cfg, accum_dtype,
    solver, solver_opts,
):
    """One classical-cascade round, per device (mpi_svm_main3.cpp:565-718)."""
    part_buf = _squeeze(part_buf)
    rank = lax.axis_index(CASCADE_AXIS)
    recv = global_sv  # round-start broadcast: global SVs with warm alpha
    own = part_buf    # local set starts as the partition (:616-619)
    b = jnp.zeros((), part_buf.X.dtype)

    merged_counts, sv_counts, iters, statuses = [], [], [], []
    step = 1
    while step <= n_shards:
        active = (rank % step) == 0
        train, mcount = merge_dedup(recv, own, train_cap)
        train = train._replace(valid=train.valid & active)
        res = _solve(train, cfg, accum_dtype, solver, solver_opts)
        own, svcount = extract_svs(train, res.alpha, cfg.sv_tol, sv_cap)
        b = jnp.where(active, res.b, b)
        merged_counts.append(jnp.where(active, mcount, 0))
        sv_counts.append(jnp.where(active, svcount, 0))
        iters.append(jnp.where(active, res.n_iter, 0))
        statuses.append(jnp.where(active, res.status, -1))
        if step < n_shards:
            perm = [
                (r, r - step)
                for r in range(n_shards)
                if r % (2 * step) == step
            ]
            recv = jax.tree.map(
                lambda x: lax.ppermute(x, CASCADE_AXIS, perm), own
            )
        step *= 2

    diag = {
        "merged_count": jnp.stack(merged_counts),
        "sv_count": jnp.stack(sv_counts),
        "iters": jnp.stack(iters),
        "status": jnp.stack(statuses),
    }
    return _replicate_outputs(own, b, diag)


def _replicate_outputs(model, b, diag):
    """Broadcast rank 0's model/b and gather per-rank diagnostics so every
    device (hence every PROCESS) holds the full round result. This is what
    makes the cascade multi-host capable: with row-sharded outputs, a host
    can only fetch its own shards (np.asarray on a cross-process array
    raises), but the host-side round loop — convergence test, overflow
    checks, checkpointing — needs the global model and all shards'
    diagnostics on every process to take the same branch in SPMD lockstep
    (the reference broadcasts its converged flag for the same reason,
    mpi_svm_main3.cpp:822-827). The extra collectives are sv_cap-sized —
    noise next to the per-round solves."""
    model0 = jax.tree.map(
        lambda x: lax.all_gather(x, CASCADE_AXIS)[0], model
    )
    b0 = lax.all_gather(b, CASCADE_AXIS)[0]
    diag = {k: lax.all_gather(v, CASCADE_AXIS) for k, v in diag.items()}
    return model0, b0, diag


def _star_round_device(
    part_buf, global_sv, *, n_shards, train_cap, merged_cap, sv_cap, cfg,
    accum_dtype, solver, solver_opts,
):
    """One modified-cascade round, per device (mpi_svm_main2.cpp:439-769)."""
    part_buf = _squeeze(part_buf)
    # Layer 1: every rank trains (global SVs [warm] u partition [alpha=0])
    train, mcount = merge_dedup(global_sv, part_buf, train_cap)
    res = _solve(train, cfg, accum_dtype, solver, solver_opts)
    sv, svcount = extract_svs(train, res.alpha, cfg.sv_tol, sv_cap)

    # Layer 2: gather all SV sets; merge with rank0-keeps-alpha semantics
    # (own SVs warm, received alphas reset to 0, mpi_svm_main2.cpp:596-604).
    # The merged solve runs replicated on every device — identical result,
    # same wall-clock as the reference's rank 0 solving while workers idle.
    g = jax.tree.map(lambda x: lax.all_gather(x, CASCADE_AXIS), sv)
    primary = jax.tree.map(lambda x: x[0], g)
    secondary = jax.tree.map(lambda x: x[1:].reshape((-1,) + x.shape[2:]), g)
    merged, merged_count = merge_dedup(primary, secondary, merged_cap)
    res2 = _solve(merged, cfg, accum_dtype, solver, solver_opts)
    new_global, gcount = extract_svs(merged, res2.alpha, cfg.sv_tol, sv_cap)

    diag = {
        "merged_count": jnp.stack([mcount, merged_count]),
        "sv_count": jnp.stack([svcount, gcount]),
        "iters": jnp.stack([res.n_iter, res2.n_iter]),
        "status": jnp.stack([res.status, res2.status]),
    }
    # new_global/b are already identical on every rank (the merged solve
    # runs replicated); the helper's broadcast is then a no-op in value and
    # the diag gather is what multi-host needs
    return _replicate_outputs(new_global, res2.b, diag)


def _leaf_buf(part_bufs: SVBuffer, r: int) -> SVBuffer:
    """Rank r's slice of the stacked (n_shards, ...) partition buffers."""
    return SVBuffer(*(x[r] for x in part_bufs))


def star_merge(svs, merged_cap: int):
    """The star's layer-2 union: rank 0's buffer is primary (alpha kept),
    ranks 1..P-1 are concatenated — FULL padded buffers, in rank order —
    as secondary (alpha zeroed). The concatenation keeps padding rows in
    place because dedup_first's (id, position) sort makes positions
    semantic: this is byte-for-byte the flattened all_gather[1:] the
    device round feeds merge_dedup, and the pod coordinator reuses it so
    both engines walk the same merge.

    Returns (merged buffer of capacity merged_cap, pre-truncation count).
    """
    primary = svs[0]
    if len(svs) > 1:
        secondary = SVBuffer(*(
            jnp.concatenate([getattr(s, f) for s in svs[1:]])
            for f in SVBuffer._fields
        ))
    else:
        secondary = empty(0, primary.X.shape[1], primary.X.dtype)
    return merge_dedup(primary, secondary, merged_cap)


def _tree_round_host(
    part_bufs, global_sv, *, n_shards, train_cap, sv_cap, cfg, accum_dtype,
    solver, solver_opts,
):
    """One classical-cascade round as a host loop over ranks.

    Value-identical to _tree_round_device: same merges, same solves (the
    per-leaf jit executable is shared across ranks — identical shapes),
    same diag layout ((n_shards, n_steps), idle entries 0 / status -1).
    Idle ranks are skipped — the SPMD path invalidates their buffers and
    masks their outputs, so nothing they compute is ever read."""
    n_steps = n_shards.bit_length()
    own = {r: _leaf_buf(part_bufs, r) for r in range(n_shards)}
    recv = {r: global_sv for r in range(n_shards)}
    mc = np.zeros((n_shards, n_steps), np.int64)
    sc = np.zeros((n_shards, n_steps), np.int64)
    it = np.zeros((n_shards, n_steps), np.int64)
    st = np.full((n_shards, n_steps), -1, np.int64)
    b = None
    step, si = 1, 0
    while step <= n_shards:
        for r in range(0, n_shards, step):  # active ranks: r % step == 0
            train, mcount = merge_dedup(recv[r], own[r], train_cap)
            res = _solve(train, cfg, accum_dtype, solver, solver_opts)
            own[r], svcount = extract_svs(train, res.alpha, cfg.sv_tol,
                                          sv_cap)
            mc[r, si] = int(mcount)
            sc[r, si] = int(svcount)
            it[r, si] = int(res.n_iter)
            st[r, si] = int(res.status)
            if r == 0:
                b = res.b
        if step < n_shards:
            for r in range(step, n_shards, 2 * step):  # senders
                recv[r - step] = own[r]
        step *= 2
        si += 1
    diag = {"merged_count": mc, "sv_count": sc, "iters": it, "status": st}
    return own[0], b, diag


def _star_round_host(
    part_bufs, global_sv, *, n_shards, train_cap, merged_cap, sv_cap, cfg,
    accum_dtype, solver, solver_opts,
):
    """One modified-cascade round as a host loop over ranks.

    Value-identical to _star_round_device; diag layout (n_shards, 2) with
    the layer-2 merged solve's numbers replicated down column 1, exactly
    as the all_gather of the replicated solve produces them."""
    svs, layer1 = [], []
    for r in range(n_shards):
        train, mcount = merge_dedup(global_sv, _leaf_buf(part_bufs, r),
                                    train_cap)
        res = _solve(train, cfg, accum_dtype, solver, solver_opts)
        sv, svcount = extract_svs(train, res.alpha, cfg.sv_tol, sv_cap)
        svs.append(sv)
        layer1.append((int(mcount), int(svcount), int(res.n_iter),
                       int(res.status)))
    merged, merged_count = star_merge(svs, merged_cap)
    res2 = _solve(merged, cfg, accum_dtype, solver, solver_opts)
    new_global, gcount = extract_svs(merged, res2.alpha, cfg.sv_tol, sv_cap)
    diag = {
        "merged_count": np.array(
            [[m, int(merged_count)] for m, _, _, _ in layer1], np.int64),
        "sv_count": np.array(
            [[s, int(gcount)] for _, s, _, _ in layer1], np.int64),
        "iters": np.array(
            [[i, int(res2.n_iter)] for _, _, i, _ in layer1], np.int64),
        "status": np.array(
            [[s, int(res2.status)] for _, _, _, s in layer1], np.int64),
    }
    return new_global, res2.b, diag


def _build_round_fn(
    mesh, topology, n_shards, train_cap, merged_cap, sv_cap, cfg, accum_dtype,
    solver, solver_opts,
):
    common = dict(
        n_shards=n_shards,
        train_cap=train_cap,
        sv_cap=sv_cap,
        cfg=cfg,
        accum_dtype=accum_dtype,
        solver=solver,
        solver_opts=solver_opts,
    )
    if mesh is None:
        # host fallback: no shard_map on this jax build — the same round
        # as a Python loop over ranks (see module docstring)
        if topology == "tree":
            return functools.partial(_tree_round_host, **common)
        return functools.partial(_star_round_host, merged_cap=merged_cap,
                                 **common)
    if topology == "tree":
        device_fn = functools.partial(
            _tree_round_device,
            n_shards=n_shards,
            train_cap=train_cap,
            sv_cap=sv_cap,
            cfg=cfg,
            accum_dtype=accum_dtype,
            solver=solver,
            solver_opts=solver_opts,
        )
    else:
        device_fn = functools.partial(
            _star_round_device,
            n_shards=n_shards,
            train_cap=train_cap,
            merged_cap=merged_cap,
            sv_cap=sv_cap,
            cfg=cfg,
            accum_dtype=accum_dtype,
            solver=solver,
            solver_opts=solver_opts,
        )
    part_specs = SVBuffer(*([P(CASCADE_AXIS)] * 5))
    repl_specs = SVBuffer(*([P()] * 5))
    # outputs are replicated by _replicate_outputs (multi-host capability:
    # every process can fetch them without touching remote shards); diag
    # values carry the per-shard axis inside their leading dim
    out_specs = (
        SVBuffer(*([P()] * 5)),
        P(),
        {k: P() for k in ("merged_count", "sv_count", "iters", "status")},
    )
    # check_vma=False: the solver's scan/while_loop carries start from
    # constant zeros (unvarying), which the varying-manual-axes checker would
    # reject on every carry; correctness is unaffected (no cross-device
    # communication happens inside the solver).
    fn = jax.shard_map(
        device_fn,
        mesh=mesh,
        in_specs=(part_specs, repl_specs),
        out_specs=out_specs,
        check_vma=False,
    )
    return jax.jit(fn)


def cascade_fit(
    X: np.ndarray,
    Y: np.ndarray,
    svm_config: SVMConfig = SVMConfig(),
    cascade_config: CascadeConfig = CascadeConfig(),
    mesh=None,
    dtype=jnp.float32,
    accum_dtype="auto",
    verbose: bool = False,
    checkpoint_path: Optional[str] = None,
    resume: bool = False,
    solver: str = "pair",
    solver_opts: Optional[dict] = None,
    stratified: bool = False,
    partition=None,
    tracer=None,
) -> CascadeResult:
    """Train a binary SVM with the distributed cascade.

    X must already be scaled (the reference scales with global min/max before
    scattering, mpi_svm_main3.cpp:529-539 — use data.MinMaxScaler on the full
    array first). accum_dtype: see smo_solve; the default "auto" resolves to
    f64 accumulators (enabling jax x64) — the mixed-precision mode matching
    the all-double reference; pass None for same-as-features accumulators.

    partition: a prebuilt data.partition.Partition (already scaled) used
    INSTEAD of partitioning X/Y here — the out-of-core entry point:
    stream.partition_from_dataset fills one by streaming manifest shards
    (with the manifest-fitted scaler), so the cascade never sees a
    monolithic array. X/Y/stratified are ignored (pass None); everything
    downstream — dedup-by-ID merges, convergence, checkpoints — keys on
    the partition's global IDs either way. Its leaf count must equal
    cascade_config.n_shards.

    checkpoint_path: if set, the inter-round state (global SV buffer +
    previous-round ID set) is written there after every round;
    resume=True restarts from that file if it exists (the warm-start
    semantics make rounds naturally resumable — same X/Y/config must be
    passed again; only round state is persisted).

    solver: per-shard solver — "pair" (default; the reference-faithful
    one-pair-per-iteration solver each MPI rank runs) or "blocked" (the
    TPU-first working-set solver, solver/blocked.py) — the on-chip
    accelerated-solver-per-mesh-member hybrid the reference's report lists
    as future work (SURVEY.md §2.3 last row). Both converge to the same
    stopping criterion, so the cascade's SV-set fixed point is unchanged.
    solver_opts: extra static solver knobs (blocked: q, max_outer,
    max_inner, matmul_precision — bf16_f32 rungs require a refine
    budget here, since leaves run under shard_map where the shrinking
    driver's un-shrink revalidation cannot; krow_cache works per leaf).
    The host-driven shrink_every/shrink_min/... driver knobs
    (solver/shrink.py) are rejected with a specific error: compaction
    is a host-side segmenting loop, which a shard_map'd leaf solve
    cannot run — single-chip shrinking of a cascade's leaf problems is
    a future PR.

    stratified: deal each class round-robin over the shards instead of
    the reference's contiguous scatter (data.partition) — label-sorted
    input then cannot hand a leaf a single-class shard (whose solve dies
    NO_WORKING_SET). Global IDs are original row indices either way, so
    the dedup-by-ID merges and the ID-set convergence test are unchanged.

    tracer: an obs.trace.Tracer; each round then lands as a
    `cascade.round` span + event carrying the global SV count, b, and
    the per-leaf/per-step merge sizes, SV counts and iteration counts —
    the per-round diagnostics the reference printed as rank-0 text,
    machine-readable in the run's one trace file.
    """
    if solver not in ("pair", "blocked"):
        raise ValueError(f"unknown solver {solver!r}")
    driver_keys = sorted(set(solver_opts or ()) & {
        "shrink_every", "shrink_min", "shrink_gap_factor",
        "max_unshrinks"})
    if driver_keys:
        # fail specifically, not as a TypeError from a shard_map'd solve
        raise ValueError(
            f"solver_opts {driver_keys} belong to the host-side "
            "shrinking driver (tpusvm.solver.shrink), which cannot run "
            "inside the cascade's shard_map leaves; use --mode single "
            "for shrinking, or drop the knobs (shrink_stable alone is "
            "a valid leaf-solver static: stability tracking only)"
        )
    accum_dtype = resolve_accum_dtype(accum_dtype)
    cc = cascade_config
    n_shards = cc.n_shards
    if mesh is None and hasattr(jax, "shard_map"):
        # mesh=None on a shard_map-less jax build selects the host-loop
        # round functions instead of raising from make_mesh/shard_map —
        # same merges and solves, rank loop on the host (module docstring)
        mesh = make_mesh(n_shards)
    sv_cap = cc.sv_capacity

    if partition is not None:
        if partition.X.shape[0] != n_shards:
            raise ValueError(
                f"prebuilt partition has {partition.X.shape[0]} leaves, "
                f"cascade_config.n_shards is {n_shards}"
            )
        part = partition
    else:
        part = make_partition(np.asarray(X), np.asarray(Y), n_shards,
                              stratified=stratified)
    chunk = part.X.shape[1]
    d = part.X.shape[2]
    train_cap = chunk + sv_cap
    # star layer-2 retrain buffer: the worker-SV union is deduped/compacted
    # before the solve, so its capacity only needs to hold the union's valid
    # rows — a tight cap keeps the replicated rank-0-equivalent solve from
    # paying for n_shards*sv_cap of padding (solver cost scales with the
    # padded size); overflow is checked per round below
    merged_cap = cc.resolved_star_merge_capacity()

    part_bufs = SVBuffer(
        X=jnp.asarray(part.X, dtype),
        Y=jnp.asarray(part.Y),
        alpha=jnp.zeros((n_shards, chunk), dtype),
        ids=jnp.asarray(part.ids),
        valid=jnp.asarray(part.valid),
    )
    global_sv = empty(sv_cap, d, dtype)

    prev_ids: set = set()  # reference: global_ID_sv starts empty
    history: List[Dict[str, Any]] = []
    converged = False
    rounds = 0
    b = 0.0
    start_round = 1

    # resume BEFORE building/compiling the round function: a refused
    # checkpoint (wrong shapes, wrong partition/topology) fails in
    # milliseconds instead of after the shard_map compile
    if resume and checkpoint_path is not None:
        import os

        ckpt_status = 1 if os.path.exists(checkpoint_path) else 0
        load_err = None
        if ckpt_status:
            # a load failure must NOT raise before the agreement gather
            # below: peers would block in process_allgather forever —
            # fold it into the fingerprint (status=2) and raise after
            try:
                check_round_state_config(checkpoint_path, n_shards,
                                         cc.topology)
                global_sv, prev_ids, start_round, b = load_round_state(
                    checkpoint_path, dtype
                )
                if global_sv.capacity != sv_cap or global_sv.X.shape[1] != d:
                    raise ValueError(
                        "cascade checkpoint shapes do not match this run: "
                        f"capacity {global_sv.capacity} vs {sv_cap}, "
                        f"d {global_sv.X.shape[1]} vs {d}"
                    )
            except Exception as e:  # noqa: BLE001 — re-raised below
                ckpt_status, load_err = 2, e
        # multi-host: fail fast (before any round collective) if the
        # processes did not all load the same state — ADVICE r3 medium;
        # see _check_resume_fingerprints for the shared-fs requirement
        _verify_resume_agreement(ckpt_status, start_round, prev_ids, b,
                                 load_err)
        if load_err is not None:
            raise load_err
        if ckpt_status == 1:
            if verbose:
                print(f"resuming cascade from round {start_round} "
                      f"({len(prev_ids)} SVs in checkpoint)")
            rounds = start_round - 1
            if start_round > svm_config.max_rounds:
                warnings.warn(
                    f"cascade checkpoint is already at round {rounds} >= "
                    f"max_rounds={svm_config.max_rounds}; returning the "
                    "checkpointed model without training (raise max_rounds "
                    "to continue)",
                    RuntimeWarning,
                    stacklevel=2,
                )

    round_fn = _build_round_fn(
        mesh, cc.topology, n_shards, train_cap, merged_cap, sv_cap,
        svm_config, accum_dtype, solver, dict(solver_opts or {}),
    )

    # fallback result if the loop body never runs (resumed past max_rounds)
    new_global = jax.tree.map(np.asarray, global_sv)

    full_merged_cap = n_shards * sv_cap  # star layer-2 concatenation bound

    round_retry = faults.Retry(faults.DEFAULT_IO_POLICY, op="cascade.round")
    for rnd in range(start_round, svm_config.max_rounds + 1):
        # chaos hook: transient rules here are retried with backoff (the
        # round has not started — nothing to roll back); a kill rule
        # simulates dying between rounds, and resume must then reproduce
        # the uninterrupted trajectory from the checkpoint
        round_retry(faults.point, "cascade.round", round=rnd)
        t0 = time.perf_counter()
        round_span = (tracer.span("cascade.round", round=rnd)
                      if tracer else contextlib.nullcontext())
        with round_span:
            while True:
                # the round executable is the cascade's one jit entry:
                # profiled_call records its (one-off) lower/compile cost
                # and FLOPs when the compile observatory is on, and is
                # the plain call otherwise. The host fallback has no
                # single jit entry — its per-leaf solves are themselves
                # profiled jit points — so it is called directly.
                if mesh is None:
                    out_global, b_all, diag = round_fn(part_bufs, global_sv)
                else:
                    out_global, b_all, diag = prof.profiled_call(
                        "cascade.round_fn", round_fn, part_bufs, global_sv
                    )
                diag = {k: np.asarray(v) for k, v in diag.items()}
                if (
                    cc.topology == "star"
                    and merged_cap < full_merged_cap
                    and diag["merged_count"][:, 1].max() > merged_cap
                ):
                    # The deduped worker-SV union overflowed the tight
                    # layer-2 retrain buffer, so this round's merged solve
                    # saw a truncated union — its result is invalid. The
                    # concatenation bound n_shards*sv_cap always fits (the
                    # union draws at most sv_cap valid rows per shard), so
                    # transparently rebuild at that capacity, re-run the
                    # round (the inter-round state is untouched until the
                    # check passes), and keep the widened round_fn for the
                    # remaining rounds — the union grows with the global SV
                    # set, so a tight retry would just re-overflow. At full
                    # width the bound makes overflow impossible, hence no
                    # raise here.
                    warnings.warn(
                        f"cascade round {rnd}: worker-SV union of "
                        f"{diag['merged_count'][:, 1].max()} rows "
                        f"overflowed the star merge buffer ({merged_cap}); "
                        f"retrying the round with the full concatenation "
                        f"capacity {full_merged_cap} (set "
                        "star_merge_capacity to avoid the recompile)",
                        RuntimeWarning,
                        stacklevel=2,
                    )
                    merged_cap = full_merged_cap
                    round_fn = _build_round_fn(
                        mesh, cc.topology, n_shards, train_cap, merged_cap,
                        sv_cap, svm_config, accum_dtype, solver,
                        dict(solver_opts or {}),
                    )
                    continue
                break
            new_global = jax.tree.map(np.asarray, out_global)
            b = float(np.asarray(b_all))
        dt = time.perf_counter() - t0
        rounds = rnd

        # overflow detection: pre-truncation counts vs capacities
        if cc.topology == "tree":
            if diag["merged_count"].max() > train_cap:
                raise RuntimeError(
                    f"cascade train buffer overflow: {diag['merged_count'].max()}"
                    f" > capacity {train_cap}; increase sv_capacity"
                )
        else:
            if diag["merged_count"][:, 0].max() > train_cap:
                raise RuntimeError(
                    f"cascade train buffer overflow: "
                    f"{diag['merged_count'][:, 0].max()} > capacity {train_cap}"
                )
        if diag["sv_count"].max() > sv_cap:
            raise RuntimeError(
                f"SV buffer overflow: {diag['sv_count'].max()} SVs > capacity "
                f"{sv_cap}; increase sv_capacity"
            )

        ids_arr = np.asarray(new_global.ids)[np.asarray(new_global.valid)]
        ids_now = set(ids_arr.tolist())
        entry = {
            "round": rnd,
            "sv_count": len(ids_now),
            "sv_ids": np.sort(ids_arr),
            "b": b,
            "time_s": dt,
            "iters": diag["iters"],
            "status": diag["status"],
        }
        history.append(entry)
        if tracer is not None:
            # per-round / per-leaf telemetry: the diag arrays carry one
            # row per merge step (tree) or layer (star) per shard
            tracer.event(
                "cascade.round",
                round=rnd,
                sv_count=len(ids_now),
                b=b,
                time_s=dt,
                topology=cc.topology,
                merged_count=diag["merged_count"].tolist(),
                leaf_sv_count=diag["sv_count"].tolist(),
                iters=diag["iters"].tolist(),
                status=diag["status"].tolist(),
            )
        bad = diag["status"][diag["status"] >= int(Status.INFEASIBLE_UV)]
        if bad.size:
            warnings.warn(
                f"cascade round {rnd}: solver bail-outs on some shards "
                f"(statuses {sorted(set(Status(int(s)).name for s in bad))}); "
                "the merged model may be partially optimised",
                RuntimeWarning,
                stacklevel=2,
            )
        if verbose:
            print(
                f"=== Round {rnd} === SV count = {len(ids_now)}, "
                f"b = {b:.15f}, {dt:.3f}s"
            )

        if not ids_now:
            # Every shard failed to find a working set (e.g. label-sorted
            # input making each partition single-class). The reference would
            # silently "converge" on the empty set with an uninitialised b;
            # fail loudly instead of returning a NaN model.
            raise RuntimeError(
                "cascade produced an empty global support-vector set — all "
                "per-shard solves found no working set (is the data sorted "
                "by label, making partitions single-class?); statuses: "
                f"{diag['status'].tolist()}"
            )

        # ID-set convergence test (mpi_svm_main3.cpp:720-744)
        if ids_now == prev_ids:
            converged = True
        prev_ids = ids_now

        if checkpoint_path is not None and jax.process_index() == 0:
            # every process computes identical (replicated) round state;
            # only process 0 persists it — the reference's rank-0-only IO
            # pattern (SURVEY.md §5.5), and it avoids a same-file rename
            # race on a shared filesystem
            save_round_state(checkpoint_path, new_global, prev_ids, rnd, b,
                             n_shards=n_shards, topology=cc.topology)

        if converged:
            break
        global_sv = SVBuffer(
            X=jnp.asarray(new_global.X),
            Y=jnp.asarray(new_global.Y),
            alpha=jnp.asarray(new_global.alpha),
            ids=jnp.asarray(new_global.ids),
            valid=jnp.asarray(new_global.valid),
        )

    mask = np.asarray(new_global.valid)
    return CascadeResult(
        sv_X=np.asarray(new_global.X)[mask],
        sv_Y=np.asarray(new_global.Y)[mask],
        sv_alpha=np.asarray(new_global.alpha)[mask],
        sv_ids=np.asarray(new_global.ids)[mask],
        b=b,
        rounds=rounds,
        converged=converged,
        history=history,
    )
