"""Configuration for TPU-native SVM training.

All defaults reproduce the reference implementation's hardcoded constants
(reference: main3.cpp:95 gamma, :163 C, :109/:165 eps, :196-198 tau/max_iter,
:297 sv_tol; mpi_svm_main3.cpp:542-544 max_rounds) so a zero-flag run is a
parity run. The reference has no config system at all (constants are edited
in-source, SURVEY.md §5.6); this dataclass + the CLI in `tpusvm.cli` is the
TPU-native replacement.
"""

from __future__ import annotations

import dataclasses
from typing import Optional


# Supported kernel families (tpusvm.kernels). Lives here — not in the
# kernels package — so config/serialization can validate names without
# importing the JAX-backed dispatch module.
#
# "sigmoid" (tanh(gamma x.z + coef0)) closes the last named EXACT-kernel
# gap; "rff"/"nystrom" are the APPROXIMATE families (tpusvm.approx): a
# seeded explicit feature map sends the rbf kernel into a space where
# every kernel computation is the linear family's primal-friendly
# matmul — the solvers/predict/serve paths receive PRE-MAPPED features
# and dispatch routes the approx names through the linear fast path.
KERNEL_FAMILIES = ("rbf", "linear", "poly", "sigmoid", "rff", "nystrom")

# the families whose "features" are an explicit approximate-kernel map
# Phi(x) rather than raw data rows (tpusvm.approx.features); model/serve
# layers apply the map, solver/kernel layers see linear geometry
APPROX_FAMILIES = ("rff", "nystrom")


def is_approx_family(family: str) -> bool:
    return family in APPROX_FAMILIES


# the lane dimension every TPU tile shares (TPU_TILE_SHAPES below): the
# trailing dim of any MXU/VMEM operand pads up to a multiple of this
_TPU_LANE = 128


def validate_map_dim(D: int, what: str = "rff_dim") -> int:
    """Validate an approximate-map feature dimension for TPU tiling.

    The mapped feature matrix (n, D) is the solver's streamed MXU operand
    — its trailing dim D lands on the lane axis, so a D that is not a
    multiple of the 128-lane tile is padded up by the compiler, silently
    burning HBM bandwidth and MXU cycles on zeros on EVERY f-update
    contraction (the JXIR104 padding-waste rationale, applied up front:
    the map dimension is chosen by config, so misalignment is a config
    bug, not a data property). RFF additionally needs an even D (cos/sin
    halves of D/2 frequency draws) — implied by the lane rule.
    """
    if D < _TPU_LANE or D % _TPU_LANE != 0:
        raise ValueError(
            f"{what}={D} is not TPU-tile-aligned: the mapped feature "
            f"matrix (n, {what}) streams through the MXU with {what} on "
            f"the 128-lane axis, so {what} must be a positive multiple "
            f"of {_TPU_LANE} (TPU_TILE_SHAPES; the JXIR104 rule) — e.g. "
            f"{max(_TPU_LANE, (D // _TPU_LANE + 1) * _TPU_LANE)}"
        )
    return D


# ---------------------------------------------------------------- precision
# The explicit resolved token for jax's precision="default" (raw
# single-pass bf16 MXU matmuls). The jax name is a footgun: callers wrote
# precision="default" believing they were asking for "the default
# precision" and silently got ~1e-2-error bf16 Gram entries (enough to
# break SV-set parity with the f64 oracle — ops/rbf.py DEFAULT_PRECISION).
# Raw bf16 must now be REQUESTED by this unmistakable name; the string
# "default" raises everywhere (resolve_matmul_precision). The blocked
# solver keeps accepting matmul_precision="default" on its own surface
# for backward compatibility — it translates to this token only after
# validating the refine pairing that makes raw bf16 safe.
RAW_BF16 = "raw_bf16"

#: resolved contraction-precision tokens, the solver speed ladder:
#:   "float32"   full-f32-equivalent multi-pass MXU matmuls (trust anchor)
#:   "highest"   jax Precision.HIGHEST (same tier, explicit)
#:   "bf16_f32"  bf16 operands, f32 accumulation (preferred_element_type):
#:               single-pass MXU throughput with exact f32 adds — operand
#:               rounding (~0.4% relative) is the only loss. Backend-
#:               independent semantics (the operands are ROUNDED, not a
#:               TPU precision hint), so CPU runs exercise the same math.
#:   "bf16_f32c" ditto plus one compensated residual pass
#:               (X - bf16(X)) @ bf16(B): recovers most of the left
#:               operand's rounding error for ~2x the matmul cost —
#:               still under the ~3x of full-f32 emulation.
#:   RAW_BF16    raw single-pass bf16 (jax precision="default"); cannot
#:               be reached by accident — see resolve_matmul_precision.
MATMUL_PRECISIONS = ("float32", "highest", "bf16_f32", "bf16_f32c",
                     RAW_BF16)


def resolve_matmul_precision(precision):
    """The single resolver every solver/ops contraction routes through.

    Maps the user-facing knob to a MATMUL_PRECISIONS token:
      None -> "float32" (the library default, full-f32 trust anchor);
      "float32"/"highest"/"bf16_f32"/"bf16_f32c"/RAW_BF16 -> themselves;
      "default" -> ValueError ALWAYS, naming the knob: jax's name for raw
        bf16 reads like "no preference" and used to silently flip the
        dominant contraction to ~1e-2-error arithmetic. The ONLY spelling
        that reaches raw bf16 is the unmistakable RAW_BF16 token — the
        blocked solver emits it after validating its refine/shrink drift
        guard, and a human typing "raw_bf16" has read this docstring.

    This is the runtime check the JX-lint hazard class relies on: raw
    single-pass bf16 is impossible to enable by accident because no
    accidental spelling resolves to it.
    """
    if precision is None:
        return "float32"
    if precision == "default":
        raise ValueError(
            "precision='default' is jax's name for RAW SINGLE-PASS bf16 "
            "MXU matmuls (~1e-2 absolute error on unit-scale Gram "
            "entries), not 'the default precision'. Request it "
            "explicitly as tpusvm.config.RAW_BF16, use the solver knob "
            "matmul_precision='default' (which validates the refine "
            "pairing first), or pick a ladder rung: 'float32' (trust "
            "anchor), 'bf16_f32' (bf16 operands, f32 accumulation), "
            "'bf16_f32c' (compensated)."
        )
    if precision not in MATMUL_PRECISIONS:
        raise ValueError(
            f"unknown matmul precision {precision!r}; supported: "
            f"{list(MATMUL_PRECISIONS)} (None = 'float32')"
        )
    return precision


@dataclasses.dataclass(frozen=True)
class SVMConfig:
    """Hyperparameters and numerical tolerances of the SMO solver.

    Attributes:
      C: box constraint (reference main3.cpp:342 — C=10 for MNIST, 1 for banknote).
      gamma: RBF width, K(a,b)=exp(-gamma*||a-b||^2) (main3.cpp:95 — 0.00125 for
        MNIST, 0.125 for banknote/debug); for kernel="poly" the dot-product
        scale (gamma*a.b + coef0)^degree; unused by kernel="linear".
      tau: stopping tolerance; converged when b_low <= b_high + 2*tau
        (main3.cpp:196, :213).
      eps: index-set tolerance for I_high/I_low membership, eta positivity guard,
        and U<=V feasibility slack (main3.cpp:109, :158, :253).
      sv_tol: alpha > sv_tol defines a support vector (main3.cpp:297).
      max_iter: SMO update cap (main3.cpp:198).
      max_rounds: cascade round cap (mpi_svm_main3.cpp:544).
      kernel: kernel family, one of KERNEL_FAMILIES; "rbf" (the default) is
        the reference's only kernel, so a zero-flag config stays a parity
        config.
      degree: polynomial degree (kernel="poly" only; static — each degree
        compiles its own solver).
      coef0: polynomial/sigmoid additive term (kernel="poly"/"sigmoid";
        traced).
      epsilon: the epsilon-SVR tube half-width (EpsilonSVR only; ignored by
        classification).
      rff_dim: random-Fourier-feature map dimension D (kernel="rff" only):
        the mapped feature width, validated TPU-tile-aligned up front
        (validate_map_dim — the JXIR104 padding-waste rule applied at
        config time). D/2 Gaussian frequency draws feed cos/sin halves.
      map_seed: deterministic seed of the approximate feature map
        (kernel="rff"/"nystrom"): the same seed reproduces bit-identical
        features across ingest/train/predict/serve.
      landmarks: Nystrom landmark count k (kernel="nystrom" only): the
        mapped feature width, tile-aligned like rff_dim; must also be
        <= n at fit time (landmark rows are drawn from the data).
    """

    C: float = 10.0
    gamma: float = 0.00125
    tau: float = 1e-5
    eps: float = 1e-12
    sv_tol: float = 1e-8
    max_iter: int = 100000
    max_rounds: int = 50
    kernel: str = "rbf"
    degree: int = 3
    coef0: float = 0.0
    epsilon: float = 0.1
    rff_dim: int = 2048
    map_seed: int = 0
    landmarks: int = 256

    def __post_init__(self):
        if self.kernel not in KERNEL_FAMILIES:
            raise ValueError(
                f"unknown kernel family {self.kernel!r}; supported: "
                f"{list(KERNEL_FAMILIES)}"
            )
        if self.degree < 1:
            raise ValueError(f"degree must be >= 1, got {self.degree}")
        if self.epsilon < 0:
            raise ValueError(f"epsilon must be >= 0, got {self.epsilon}")
        # approximate-map dimensions are validated AT CONFIG TIME: the
        # mapped width is the solver's MXU lane dim for the whole fit,
        # so a misaligned choice is rejected before any data is touched
        if self.kernel == "rff":
            validate_map_dim(self.rff_dim, "rff_dim")
        if self.kernel == "nystrom":
            validate_map_dim(self.landmarks, "landmarks")


@dataclasses.dataclass(frozen=True)
class CascadeConfig:
    """Shapes and topology of the distributed cascade (SURVEY.md §2.2 C18-C24).

    XLA needs static shapes, so the dynamically-sized SV sets of the reference
    become fixed-capacity padded buffers carried with validity masks.

    Attributes:
      n_shards: number of mesh members P (reference: `mpirun -np P`).
      sv_capacity: max support vectors a single merged model may hold. Must be
        >= the true global SV count (1548 for MNIST-60k); overflow is detected
        and reported at runtime.
      topology: "tree" = classical binary-reduction cascade (mpi_svm_main3.cpp),
        "star" = modified two-layer cascade (mpi_svm_main2.cpp).
      star_merge_capacity: buffer capacity of the star topology's layer-2
        merged retrain (the rank-0 solve over the union of all worker SV
        sets). The union is deduped and compacted before the solve, so this
        only needs to hold the union's VALID rows — not the concatenation —
        and the solver's cost scales with the padded size, so a tight value
        here is a large speedup at high P. None (default) =
        n_shards * sv_capacity, the structural bound (rank 0's merged set
        in the reference is P worker-sized sets, mpi_svm_main2.cpp:540-621)
        — overflow-proof by construction, so the common path never pays a
        mid-fit recompile. Set an explicit tighter value to trade that
        guarantee for a smaller layer-2 solve at high P: if a round's
        union then overflows, the fit transparently widens to the full
        concatenation capacity (with a RuntimeWarning and one recompile),
        re-runs the round, and stays at full width for the remaining
        rounds (the union grows with the global SV set, so a later shrink
        would just re-overflow). Only meaningful for topology="star";
        setting it with "tree" raises.
    """

    n_shards: int = 8
    sv_capacity: int = 4096
    topology: str = "tree"
    star_merge_capacity: Optional[int] = None

    def __post_init__(self):
        if self.topology not in ("tree", "star"):
            raise ValueError(f"unknown cascade topology: {self.topology!r}")
        if self.topology == "tree" and (self.n_shards & (self.n_shards - 1)) != 0:
            # mpi_svm_main3.cpp:420-428 aborts on non-power-of-two world size.
            raise ValueError(
                f"tree cascade requires a power-of-two shard count, got {self.n_shards}"
            )
        if self.star_merge_capacity is not None:
            if self.topology != "star":
                raise ValueError(
                    "star_merge_capacity only applies to the star topology; "
                    f"got topology={self.topology!r}"
                )
            if self.star_merge_capacity < 1:
                raise ValueError(
                    f"star_merge_capacity must be >= 1, "
                    f"got {self.star_merge_capacity}"
                )

    def resolved_star_merge_capacity(self) -> int:
        # default = the structural concatenation bound (P worker SV sets),
        # so the zero-config path cannot overflow-and-recompile mid-fit
        # (VERDICT r4 #7: the old tight min(2*cap, P*cap) default tripped
        # on the standard multichip dryrun's very first round). A tighter
        # explicit value remains available and is self-healed on overflow.
        cap = self.star_merge_capacity
        if cap is None:
            cap = self.n_shards * self.sv_capacity
        return cap


def resolve_accum_dtype(accum_dtype):
    """Resolve the accumulator-dtype sentinel used by the model/cascade APIs.

    "auto" (the library default) = float64 accumulators, enabling jax x64
    mode on first use. This makes the zero-config path the documented-good
    mixed-precision configuration — f32 features/kernel rows (full
    HBM-bandwidth win) with f64 O(n) accumulators — matching the all-double
    reference (main3.cpp uses double throughout) and the CLI's --accum
    default. float32 accumulators alone can livelock SMO near convergence
    (STALLED: updates below f32 resolution). Pass None for same-as-features
    accumulators, or an explicit dtype.
    """
    if isinstance(accum_dtype, str):
        if accum_dtype != "auto":
            raise ValueError(
                f"accum_dtype must be 'auto', None, or a dtype; "
                f"got {accum_dtype!r}"
            )
        import jax
        import jax.numpy as jnp

        if not jax.config.jax_enable_x64:
            import warnings

            # the flip is process-global and affects unrelated JAX code
            # (default dtypes become 64-bit); make it discoverable at the
            # one call that actually performs it
            warnings.warn(
                "tpusvm: enabling jax x64 mode for float64 solver "
                "accumulators (the default, matching the all-double "
                "reference); pass accum_dtype=None to keep f32 "
                "accumulators and leave jax_enable_x64 untouched",
                UserWarning,
                stacklevel=3,
            )
            jax.config.update("jax_enable_x64", True)
        return jnp.float64
    return accum_dtype


# Flag-compatibility table for blocked_smo_solve's pallas_* kwargs — the
# single source of truth shared by the solver's runtime validation
# (tpusvm/solver/blocked.py) and the static linter's JX008 rule
# (tpusvm/analysis/rules/jx008_pallas_flags.py). Each entry declares the
# value at which the flag is inactive (its default) and what the resolved
# solver config must look like for an ACTIVE value to take effect; an
# active flag outside its requirements used to be silently ignored
# (ADVICE.md round 5: an A/B run could record eta_exclude=true while
# measuring the plain XLA engine), which is exactly the hazard class the
# linter exists to catch. Keep this table in sync with the kwargs of
# blocked_smo_solve — a new pallas_* flag MUST add a row here, which makes
# both the runtime raise and the lint rule pick it up for free.
PALLAS_FLAG_RULES = {
    # vector layout inside the fused inner kernel
    "pallas_layout": {"inactive": "packed", "requires_wss": None},
    # degenerate-partner exclusion folded into the kernel's gain selection
    # (second-order selection only)
    "pallas_eta_exclude": {"inactive": False, "requires_wss": 2},
    # batched slot-pair kernel (first-order selection only)
    "pallas_multipair": {"inactive": 1, "requires_wss": 1},
    # violator-mask + per-block top-k candidate selection fused into the
    # f-update kernel's epilogue: a FUSED-FUPDATE-path flag, not an
    # inner-engine flag — it requires the fused f-update contraction to
    # be the resolved path (requires_fused), with no constraint on the
    # inner engine or wss
    "pallas_fused_selection": {"inactive": False, "requires_wss": None,
                               "requires_fused": True},
}


def pallas_flag_errors(inner, wss, flags: dict, fused=None) -> list:
    """Error strings for active pallas_* flags the resolved config ignores.

    `inner`/`wss`/`fused` are the RESOLVED solver config (after 'auto'
    resolution); pass None for a dimension the caller does not know —
    static analysis calls this with only the literals it can see in a
    call site, the solver calls it with everything fully resolved.
    `flags` maps flag name -> passed value for whichever
    PALLAS_FLAG_RULES keys the caller has. Flags marked requires_fused
    are judged against the fused-f-update resolution instead of the
    inner engine (they configure the contraction kernel's epilogue, not
    the subproblem engine).
    """
    errors = []
    for name, spec in PALLAS_FLAG_RULES.items():
        if name not in flags:
            continue
        value = flags[name]
        if type(value) is type(spec["inactive"]) and value == spec["inactive"]:
            continue
        if spec.get("requires_fused"):
            if fused is not None and not fused:
                errors.append(
                    f"{name}={value!r} extends the fused Pallas f-update "
                    "kernel; the effective fused_fupdate here is False "
                    "(fused_fupdate='auto' resolves to the fused kernel "
                    "only on TPU at full-f32 precision with a "
                    "VMEM-feasible shape)"
                )
            continue
        if inner is not None and inner != "pallas":
            errors.append(
                f"{name}={value!r} is a pallas-engine feature; the "
                f"effective inner engine here is {inner!r} (inner='auto' "
                "resolves to pallas only on TPU with lane-aligned q)"
            )
        elif (spec["requires_wss"] is not None and wss is not None
                and wss != spec["requires_wss"]):
            errors.append(
                f"{name}={value!r} requires wss={spec['requires_wss']}, "
                f"got wss={wss}"
            )
    return errors


# TPU minimum tile shapes (sublane x lane) per operand dtype: an MXU/VMEM
# operand whose trailing two dims are not multiples of its tile is padded
# up to it by the compiler, silently burning HBM bandwidth and MXU cycles
# on zeros. The lane dim is always 128; the sublane dim shrinks as the
# dtype widens. Single source of truth shared by the IR auditor's
# JXIR104 tile-alignment rule (tpusvm.analysis.ir.rules), the serve/
# shrink power-of-two bucket invariants (which exist precisely so padded
# shapes land ON these tiles), and the Pallas kernels' shape validation.
TPU_TILE_SHAPES = {
    "float32": (8, 128),
    "bfloat16": (16, 128),
    "int8": (32, 128),
    "float8_e4m3fn": (32, 128),
    "float8_e5m2": (32, 128),
}


def tpu_tile_for(dtype_name: str):
    """Min (sublane, lane) tile for a dtype name; f32's for unlisted
    dtypes (i32/f64 tile like f32 — 4-byte lanes)."""
    return TPU_TILE_SHAPES.get(dtype_name, TPU_TILE_SHAPES["float32"])


# Named dataset presets mirroring the reference's edit-in-place dataset switch
# (main3.cpp:308-313): each maps to (C, gamma).
DATASET_PRESETS = {
    "mnist": (10.0, 0.00125),
    "banknote": (1.0, 0.125),
    "debug": (1.0, 0.125),
}


def preset(name: str, **overrides) -> SVMConfig:
    """Build an SVMConfig from a named dataset preset."""
    if name not in DATASET_PRESETS:
        raise ValueError(f"unknown preset {name!r}; known: {sorted(DATASET_PRESETS)}")
    C, gamma = DATASET_PRESETS[name]
    return dataclasses.replace(SVMConfig(C=C, gamma=gamma), **overrides)
